"""CLI entry point — flag-compatible with the reference's ``main.py:8-179``.

Same ~60 flags, same modes (train / test / train_test), same log-dir layout.
TPU-specific deltas: ``--device`` is gone (JAX owns device placement; the
mesh covers every visible chip), torch-compile flags are gone (jit is always
on), and multi-host init uses ``jax.distributed`` instead of torchrun env
vars (seist_tpu/parallel/dist.py).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from seist_tpu.utils.logger import logger
from seist_tpu.utils.misc import dump_namespace, get_time_str, setup_seed


def bool_(x) -> bool:
    return (
        False
        if str(x).strip().lower() in ("0", "false", "f", "no", "n")
        else bool(x)
    )


def get_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        description="seist_tpu model training/testing arguments"
    )

    # Mode
    parser.add_argument("--mode", type=str, default="train_test",
                        help="train/test/train_test (default:'train_test')")

    # Model
    parser.add_argument("--model-name", default="seist_m_dpk", type=str)
    parser.add_argument("--checkpoint", default="", type=str,
                        help="path to latest checkpoint (default: none)")
    parser.add_argument("--seq-shards", default=1, type=int,
                        dest="seq_shards",
                        help="shard the sequence axis over this many devices "
                        "(ring attention through the SeisT attention blocks; "
                        "device count must be divisible; use for long "
                        "--in-samples). Default 1 = pure data parallel")
    parser.add_argument("--conv-kernel-l1-alpha", default=0.0, type=float,
                        dest="conv_kernel_l1_alpha",
                        help="L1 (sign) regularization strength on "
                        "eqtransformer's encoder/decoder conv kernels "
                        "(ref eqtransformer.py conv_kernel_l1_regularization)")
    parser.add_argument("--conv-bias-l1-alpha", default=0.0, type=float,
                        dest="conv_bias_l1_alpha",
                        help="as --conv-kernel-l1-alpha, for conv biases")
    parser.add_argument("--dtype", default="fp32", type=str,
                        choices=["fp32", "bf16"],
                        help="compute dtype for train/eval steps: bf16 runs "
                        "matmuls/activations in bfloat16 on the MXU with "
                        "fp32 params/optimizer/BN-stats/softmax/loss "
                        "(default: fp32)")
    parser.add_argument("--loader-processes", default=0, type=int,
                        dest="loader_processes",
                        help="assemble batches with this many worker "
                        "PROCESSES instead of the --workers thread pool "
                        "(sidesteps the GIL for Python-bound augmentation "
                        "mixes; batches are bit-identical). Default 0 = "
                        "threads")
    parser.add_argument("--profile-steps", default=0, type=int,
                        dest="profile_steps",
                        help="capture a jax.profiler trace of this many "
                        "steady-state train steps (first epoch, after "
                        "warmup) into a unique "
                        "<logdir>/profile/<timestamp>_p<pid> dir (a "
                        "relaunched supervise attempt never clobbers the "
                        "previous capture); view with TensorBoard's "
                        "profile plugin. Later captures can be re-armed "
                        "live via SIGUSR2 or POST /profile on "
                        "--metrics-port. Default 0 = off")
    parser.add_argument("--metrics-port", default=0, type=int,
                        dest="metrics_port",
                        help="serve the telemetry plane on this loopback "
                        "port (docs/OBSERVABILITY.md): GET /metrics is "
                        "Prometheus text exposition of the metrics bus "
                        "(step spans, loss/wps gauges, data-plane "
                        "counters), /metrics.json + /flight are JSON "
                        "views, POST /profile triggers an on-demand "
                        "jax.profiler capture. -1 binds an ephemeral "
                        "port (logged). Default 0 = off")
    parser.add_argument("--flight-steps", default=256, type=int,
                        dest="flight_steps",
                        help="flight-recorder ring size: the last N "
                        "steps' metrics and span events are dumped to "
                        "<logdir>/flight/*.json on every death path "
                        "(rollback, stall, preempt, quarantine "
                        "overflow, crash). Default 256")
    parser.add_argument("--steps-per-call", default=0, type=int,
                        dest="steps_per_call",
                        help="scan this many optimizer updates inside one "
                        "jitted call (distinct micro-batches, NOT gradient "
                        "accumulation) — amortizes per-dispatch latency on "
                        "remote/contended devices. Per-step train metrics "
                        "are skipped (loss only); trailing batches that "
                        "don't fill a call are dropped. Default 0 = auto: "
                        "1 on the host path, min(32, steps/epoch) under "
                        "--device-aug cached (pass an explicit 1 to keep "
                        "per-step save/preempt granularity there)")
    parser.add_argument("--grad-accum-steps", default=1, type=int,
                        dest="grad_accum_steps",
                        help="accumulate gradients over this many "
                        "micro-batches into ONE optimizer update (scanned "
                        "in a single jitted program; peak memory is one "
                        "micro-batch) — train the reference's batch-500 "
                        "effective batch on a memory-tight chip by e.g. "
                        "--batch-size 100 --grad-accum-steps 5. Per-step "
                        "train metrics are skipped (loss only), and "
                        "trailing batches that don't fill an update are "
                        "dropped, as with --steps-per-call. Mutually "
                        "exclusive with --steps-per-call. Default 1")

    parser.add_argument("--device-aug", default="off", type=str,
                        choices=["off", "step", "cached"], dest="device_aug",
                        help="device-side augmentation + label synthesis "
                        "(docs/DATA_PIPELINE.md). 'step': the jitted train "
                        "step augments raw rows the host feeds (no "
                        "per-sample numpy work, no Python stacking). "
                        "'cached': whole raw epochs live in HBM, sharded "
                        "over the mesh data axis, and a scan executor "
                        "consumes (k,B) index arrays — zero per-step host "
                        "stacking; falls back to 'step' over the HBM "
                        "budget, to 'off' on unsupported configs (both "
                        "logged). Default off")
    parser.add_argument("--device-aug-hbm-gb", default=0.0, type=float,
                        dest="device_aug_hbm_gb",
                        help="HBM budget (GiB) for the --device-aug cached "
                        "epoch store. 0 = auto: half the device "
                        "bytes_limit, or 4 GiB when the backend reports "
                        "no memory stats")
    parser.add_argument("--ingest", default="auto", type=str,
                        choices=["auto", "direct", "host"],
                        help="raw-row feed for the device-aug step path "
                        "(docs/DATA.md). 'auto': direct shard->staging->"
                        "device ingest whenever the dataset is packed "
                        "(no Event decode, no resident waveform upload); "
                        "'host': always upload a resident RawStore; "
                        "'direct': demand the fast path, error instead "
                        "of degrading. Default auto")

    # Random seed
    parser.add_argument("--seed", default=0, type=int)

    # Logs
    parser.add_argument("--log-base", default="./logs", type=str)
    parser.add_argument("--log-step", default=4, type=int)
    parser.add_argument("--use-tensorboard", default=True, type=bool_)

    # Save results
    parser.add_argument("--save-test-results", default=True, type=bool_)

    # Dataset
    parser.add_argument("--data", default="", type=str, help="path to dataset")
    parser.add_argument("--dataset-name", default="diting_light", type=str,
                        help="'diting', 'diting_light', 'pnw', 'pnw_light', "
                        "'sos' or 'synthetic'")
    parser.add_argument("--data-split", type=bool_, default=True)
    parser.add_argument("--train-size", type=float, default=0.8)
    parser.add_argument("--val-size", type=float, default=0.1)
    parser.add_argument("--mixture-temperature", default=0.0, type=float,
                        dest="mixture_temperature",
                        help="temperature-weighted TRAIN sampling over a "
                        "multi-source packed mixture (pack_dataset.py "
                        "--mixture): per epoch slot, source s is drawn "
                        "with p ∝ (n_s/N)^(1/T) — 1.0 = proportional, "
                        "higher = flatter across sources. Deterministic "
                        "under the (seed, epoch, start_batch) resume "
                        "contract; 0 disables (plain global shuffle). "
                        "Eval/test always walk their splits plainly")

    # Data loader
    parser.add_argument("--shuffle", type=bool_, default=True)
    parser.add_argument("--workers", default=8, type=int)

    # Data preprocess
    parser.add_argument("--in-samples", default=8192, type=int)
    parser.add_argument("--label-width", type=float, default=0.5,
                        help="width of soft label (seconds)")
    parser.add_argument("--label-shape", type=str, default="gaussian",
                        help="'gaussian' 'triangle' 'box' or 'sigmoid'")
    parser.add_argument("--coda-ratio", default=2.0, type=float)
    parser.add_argument("--norm-mode", default="std", type=str)
    parser.add_argument("--min-snr", type=float, default=-float("inf"))
    parser.add_argument("--p-position-ratio", type=float, default=-1)

    # Data augmentation
    parser.add_argument("--augmentation", type=bool_, default=True)
    parser.add_argument("--add-event-rate", default=0.0, type=float)
    parser.add_argument("--max-event-num", default=1, type=int)
    parser.add_argument("--shift-event-rate", default=0.2, type=float)
    parser.add_argument("--add-noise-rate", default=0.4, type=float)
    parser.add_argument("--add-gap-rate", default=0.4, type=float)
    parser.add_argument("--min-event-gap", default=0.5, type=float,
                        help="minimum event gap (seconds)")
    parser.add_argument("--drop-channel-rate", default=0.4, type=float)
    parser.add_argument("--scale-amplitude-rate", default=0.4, type=float)
    parser.add_argument("--pre-emphasis-rate", default=0.4, type=float)
    parser.add_argument("--pre-emphasis-ratio", default=0.97, type=float)
    parser.add_argument("--generate-noise-rate", default=0.05, type=float)
    parser.add_argument("--mask-percent", default=0, type=int)
    parser.add_argument("--noise-percent", default=0, type=int)

    # Train
    parser.add_argument("--epochs", default=200, type=int)
    parser.add_argument("--patience", default=30, type=int)
    parser.add_argument("--steps", default=0, type=int,
                        help="if steps > 0, epochs is ignored")
    parser.add_argument("--start-epoch", default=0, type=int)
    parser.add_argument("--batch-size", default=500, type=int,
                        help="per-host batch size")
    parser.add_argument("--optim", default="Adam", type=str)
    parser.add_argument("--momentum", default=0.9, type=float)
    parser.add_argument("--weight_decay", default=0.0, type=float)
    parser.add_argument("--save-interval-steps", default=0, type=int,
                        dest="save_interval_steps",
                        help="step-granular async checkpoints every N "
                        "batches (orbax CheckpointManager; resume continues "
                        "mid-epoch at the exact data position). 0 = only "
                        "the best-val epoch checkpoints. A preemption "
                        "loses at most N batches of work")
    parser.add_argument("--keep-checkpoints", default=3, type=int,
                        dest="keep_checkpoints",
                        help="checkpoint retention: keep the last K step "
                        "checkpoints plus the best-val one; older ones are "
                        "GC'd (logged). Default 3")
    parser.add_argument("--bad-step-guard", default=True, type=bool_,
                        dest="bad_step_guard",
                        help="detect non-finite loss/grad-norm inside the "
                        "jitted step and skip the poisoned update (params, "
                        "optimizer state and LR-schedule step untouched). "
                        "Default true")
    parser.add_argument("--max-bad-steps", default=3, type=int,
                        dest="max_bad_steps",
                        help="consecutive guard-skipped updates before "
                        "rolling back to the last checkpoint. 0 disables "
                        "rollback (skips only). Default 3")
    parser.add_argument("--max-quarantine-frac", default=0.05, type=float,
                        dest="max_quarantine_frac",
                        help="abort the run once more than this fraction "
                        "of the dataset has been quarantined by the "
                        "data-plane guard (corrupt samples are benched "
                        "and deterministically replaced; past this "
                        "threshold the dataset is considered rotted and "
                        "training on fallbacks would be silent garbage). "
                        "Default 0.05")
    parser.add_argument("--data-watchdog-sec", default=600.0, type=float,
                        dest="data_watchdog_sec",
                        help="pipeline stall watchdog: if the train loop "
                        "waits longer than this for the next host batch "
                        "(loader wedged or a worker thread dead), dump "
                        "all thread stacks and exit with the clean-"
                        "preempt code (75) so tools/supervise.py "
                        "relaunches from the newest checkpoint. Only "
                        "time spent BLOCKED on the data plane counts — "
                        "step compute/compiles/validation do not. "
                        "0 disables. Default 600")
    parser.add_argument("--use-lr-scheduler", default=True, type=bool_)
    parser.add_argument("--lr-scheduler-mode", default="exp_range", type=str,
                        help="'triangular', 'triangular2' or 'exp_range'")
    parser.add_argument("--base-lr", default=8e-5, type=float)
    parser.add_argument("--max-lr", default=1e-3, type=float)
    parser.add_argument("--warmup-steps", default=2000, type=float,
                        help="<1 means ratio of total steps")
    parser.add_argument("--down-steps", default=3000, type=float,
                        help="<1 means ratio of total steps")

    # Val/Test
    parser.add_argument("--time-threshold", default=0.1, type=float,
                        help="pick residual threshold (seconds)")
    parser.add_argument("--min-peak-dist", default=1.0, type=float,
                        help="minimum peak distance (seconds)")
    parser.add_argument("--ppk-threshold", default=0.3, type=float)
    parser.add_argument("--spk-threshold", default=0.3, type=float)
    parser.add_argument("--det-threshold", default=0.5, type=float)
    parser.add_argument("--max-detect-event-num", default=1, type=int)

    # Synthetic-dataset shortcuts (no reference analogue; synthetic only)
    parser.add_argument("--synthetic-events", default=0, type=int,
                        help="synthetic dataset size (0 = default)")

    args = parser.parse_args(argv)

    if not 0 <= args.p_position_ratio <= 1:
        args.p_position_ratio = -1

    args.log_base = os.path.abspath(args.log_base)
    if args.data:
        args.data = os.path.abspath(args.data)
    if args.checkpoint:
        args.checkpoint = os.path.abspath(args.checkpoint)

    args.dataset_kwargs = None
    if args.dataset_name == "synthetic" and args.synthetic_events:
        args.dataset_kwargs = {"num_events": args.synthetic_events}
    return args


def main_worker(args: argparse.Namespace) -> None:
    """Mode dispatch (ref main.py:182-210)."""
    from seist_tpu.train.worker import is_main_process, test_worker, train_worker
    from seist_tpu.utils.misc import enable_compile_cache

    enable_compile_cache()

    log_dir = (
        os.path.join(
            args.log_base,
            f"{get_time_str()}_{args.model_name}_{args.dataset_name}",
        )
        if not args.checkpoint
        else args.checkpoint.split("checkpoints")[0]
    )
    # Multi-host: the timestamped dir is built from per-host wall clocks
    # that can straddle a second boundary; every process must agree on one
    # path before the collective orbax save (ref broadcasts the ckpt path
    # rank0->all, train.py:481-482 — here the whole log dir is agreed up
    # front instead).
    from seist_tpu.parallel.dist import broadcast_object, process_count

    if process_count() > 1:
        log_dir = broadcast_object(log_dir)
    logger.set_logdir(log_dir)
    logger.set_logger("global")
    if not is_main_process():
        logger.enable_console(False)
    logger.info(f"pid: {os.getpid()}")
    logger.info(f"\n{dump_namespace(args)}")

    mode = args.mode.split("_")
    if not set(("train", "test")) & set(mode):
        raise ValueError(
            f"`mode` must be 'train','test' or 'train_test', got '{args.mode}'"
        )
    if "train" in mode:
        setup_seed(args.seed)
        logger.set_logger("train")
        ckpt_path = train_worker(args)
        args.checkpoint = ckpt_path
    if "test" in mode:
        setup_seed(args.seed)
        logger.set_logger("test")
        test_worker(args)


def main(argv: Optional[List[str]] = None) -> None:
    import sys

    import seist_tpu
    from seist_tpu.parallel.dist import init_distributed_mode

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "serve":
        # Online inference service (seist_tpu/serve/): own flag namespace,
        # no train/test machinery — dispatch before the big parser.
        from seist_tpu.serve.server import main as serve_main

        return serve_main(argv[1:])
    args = get_args(argv)
    args.distributed = init_distributed_mode()
    seist_tpu.load_all()
    main_worker(args)


if __name__ == "__main__":
    main()
