"""Generic name -> factory registries.

Replaces the reference's two ad-hoc registries (``models/_factory.py:17-56``
and ``datasets/_factory.py:19-33`` in /root/reference) with one typed,
reusable component. Registration happens at import time via decorators, same
contract as the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class Registry:
    """A simple string-keyed factory registry."""

    def __init__(self, kind: str):
        self._kind = kind
        self._factories: Dict[str, Callable[..., Any]] = {}

    @property
    def kind(self) -> str:
        return self._kind

    def register(self, fn: Optional[Callable] = None, *, name: Optional[str] = None):
        """Decorator: register ``fn`` under ``name`` (default ``fn.__name__``)."""

        def _do_register(f: Callable) -> Callable:
            key = name or f.__name__
            if key in self._factories:
                raise KeyError(f"{self._kind} '{key}' is already registered.")
            self._factories[key] = f
            return f

        if fn is None:
            return _do_register
        return _do_register(fn)

    def get(self, name: str) -> Callable[..., Any]:
        if name not in self._factories:
            raise KeyError(
                f"Unknown {self._kind}: '{name}'. Registered: {sorted(self._factories)}"
            )
        return self._factories[name]

    def create(self, name: str, **kwargs) -> Any:
        return self.get(name)(**kwargs)

    def names(self) -> List[str]:
        return sorted(self._factories)

    def __contains__(self, name: str) -> bool:
        return name in self._factories

    def __len__(self) -> int:
        return len(self._factories)


# Global registries (populated by importing seist_tpu.models / seist_tpu.data).
MODELS = Registry("model")
DATASETS = Registry("dataset")


def register_model(fn=None, *, name: Optional[str] = None):
    return MODELS.register(fn, name=name)


def register_dataset(fn=None, *, name: Optional[str] = None):
    return DATASETS.register(fn, name=name)
