"""Lease plane for the fault-tolerant multi-worker batch fleet.

PR 14/15 made re-picking shard-deterministic, segment-committed, and
kill/resume-safe *within one process*; the replay lane proves the
catalog is a pure function of (archive, plan). This module adds the
layer above: N workers on N machines sharing one archive, surviving
SIGKILL, exit-75 preemption, and coordination-plane partitions without
reprocessing, double-committing, or a human restart. The classic
lease / heartbeat / fencing-token loop:

* **Lease** — one :class:`~seist_tpu.batch.catalog.WorkUnit` at a time
  per worker, acquired by a compare-and-swap that issues fence token
  ``current + 1``. Fences are per-unit monotonic: every acquisition —
  first claim, reclaim of an expired lease, takeover after a crash —
  gets a strictly larger token, so "who owns this unit NOW" is always
  the highest fence, and any actor holding a smaller one is a zombie.
* **Heartbeat** — the holder renews its deadline every
  ``heartbeat_s``; a worker that dies (SIGKILL, VM reclaim) simply
  stops renewing and the lease expires ``ttl_s`` later, at which point
  any peer may reclaim at the next fence.
* **Fenced commit** — before every segment commit the holder verifies
  its fence is still current (:meth:`HeldLease.check_commit`); the
  segment file itself is published with an *exclusive* link
  (catalog.commit_segment with ``fence=``), so even a zombie that
  races past the check cannot overwrite a committed segment — it gets
  :class:`DoubleCommit`, which the chaos lane pins to zero.
* **Partition degradation** — every store operation runs behind retry
  with jittered exponential backoff and an overall deadline
  (:class:`GuardedLeaseStore`); when the store stays unreachable the
  worker finishes work it can prove it still owns (commit is allowed
  while the lease is *locally* valid: a monotonic clock says less than
  ``ttl_s`` passed since the last successful renew — exactly the
  window in which no peer can have reclaimed), then PARKS and
  re-acquires on heal. Never crash, never double-commit.

Two pluggable stores implement the same five primitives
(``try_acquire`` / ``renew`` / ``release`` / ``mark_done`` /
``current_fence``): :class:`DirLeaseStore` for single-host or
shared-filesystem fleets and tests (lock-free — the CAS is an
exclusive ``os.link``), and :class:`KVLeaseStore` over the jax
coordination-service KV client (``parallel/dist.py``) for real slices.
Neither ever holds a Python lock across store I/O (``make lockgraph``).

Because segment content is a pure function of (archive, plan), every
recovery path — reclaim-and-redo, zombie-discard, park-and-resume —
converges on the same bytes: the merged catalog of ANY fleet history
is byte-identical to the serial no-fault run (``make batch-chaos``).

Tuning env vars (registered in detlint's env registry; see
docs/FAULT_TOLERANCE.md "Batch fleet faults"): ``SEIST_LEASE_TTL_S``,
``SEIST_LEASE_HEARTBEAT_S``, ``SEIST_LEASE_GRACE_S``,
``SEIST_LEASE_RETRIES``, ``SEIST_LEASE_BACKOFF_MS``,
``SEIST_LEASE_BACKOFF_CAP_MS``, ``SEIST_LEASE_OP_TIMEOUT_S``,
``SEIST_LEASE_PARK_S``, ``SEIST_LEASE_RESCAN_S``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Set

from seist_tpu.data.io_guard import RetryPolicy
from seist_tpu.obs.bus import BUS
from seist_tpu.utils.faults import BatchFaultInjector, _env_float, _env_int, batch_faults
from seist_tpu.utils.logger import logger

_FENCE_RE = re.compile(r"^unit_(\d{5})\.fence_(\d{6})\.json$")


def _wall_now() -> float:
    """Shared-clock 'now' for lease deadlines. Wall clock is REQUIRED
    here: deadlines are compared by peers on other machines, so a
    process-local monotonic clock cannot express them. The value is
    coordination state only — it never reaches catalog bytes (segment
    content is a pure function of (archive, plan))."""
    # detlint: disable=wallclock-in-deterministic-path -- lease deadlines
    # are cross-process coordination state compared against a shared
    # clock by peers on other machines; they never touch catalog rows.
    return time.time()


def _monotonic() -> float:
    return time.monotonic()


# ------------------------------------------------------------------ errors
class LeaseError(RuntimeError):
    """Base class for every lease-plane failure."""


class LeaseStoreError(LeaseError):
    """One lease-store operation failed (possibly transient — the
    guarded wrapper retries these)."""


class LeaseStoreUnavailable(LeaseError):
    """Retries + deadline exhausted: the store is partitioned away.
    Workers park on this; they never crash on it."""


class LeaseLost(LeaseError):
    """This holder's fence is no longer current (expired + reclaimed,
    or locally expired during a partition)."""


class FenceRejected(LeaseLost):
    """A commit/done attempt carried a stale fence — the zombie write
    the fencing token exists to stop. Counted on the obs bus."""


class DoubleCommit(LeaseError):
    """An exclusive segment publish hit an already-committed file: the
    exactly-once machinery's last line of defense fired. The content is
    identical (purity), but the chaos gate pins this counter to zero —
    a nonzero count means the fence check ladder has a hole."""


# ------------------------------------------------------------------ config
@dataclasses.dataclass(frozen=True)
class LeaseConfig:
    """Lease-plane tuning. ``from_env`` reads the ``SEIST_LEASE_*``
    registry (all optional; the defaults suit real fleets — tests and
    chaos lanes shrink the clocks)."""

    ttl_s: float = 30.0
    heartbeat_s: float = 0.0  # 0 -> ttl_s / 3
    grace_s: float = 0.5  # reclaim waits deadline + grace (clock-skew margin)
    retries: int = 3
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    op_timeout_s: float = 10.0  # overall deadline per guarded store op
    park_s: float = 0.5  # base park interval while partitioned
    rescan_s: float = 0.25  # idle wait when peers hold every open unit

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "LeaseConfig":
        env = os.environ if env is None else env
        return cls(
            ttl_s=_env_float(env, "SEIST_LEASE_TTL_S", 30.0),
            heartbeat_s=_env_float(env, "SEIST_LEASE_HEARTBEAT_S", 0.0),
            grace_s=_env_float(env, "SEIST_LEASE_GRACE_S", 0.5),
            retries=max(1, _env_int(env, "SEIST_LEASE_RETRIES", 3)),
            backoff_base_s=_env_float(env, "SEIST_LEASE_BACKOFF_MS", 50.0)
            / 1000.0,
            backoff_cap_s=_env_float(env, "SEIST_LEASE_BACKOFF_CAP_MS", 2000.0)
            / 1000.0,
            op_timeout_s=_env_float(env, "SEIST_LEASE_OP_TIMEOUT_S", 10.0),
            park_s=_env_float(env, "SEIST_LEASE_PARK_S", 0.5),
            rescan_s=_env_float(env, "SEIST_LEASE_RESCAN_S", 0.25),
        )

    @property
    def heartbeat(self) -> float:
        return self.heartbeat_s if self.heartbeat_s > 0 else self.ttl_s / 3.0


@dataclasses.dataclass(frozen=True)
class LeaseRecord:
    """One issued lease: (unit, fence, owner, wall-clock deadline).
    ``fence > 1`` means this acquisition reclaimed/superseded an
    earlier holder."""

    unit_id: int
    fence: int
    owner: str
    deadline: float  # wall-clock epoch seconds

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "LeaseRecord":
        d = json.loads(blob)
        return cls(
            unit_id=int(d["unit_id"]),
            fence=int(d["fence"]),
            owner=str(d["owner"]),
            deadline=float(d["deadline"]),
        )


# ----------------------------------------------------------- dir lease store
class DirLeaseStore:
    """Shared-directory lease store: one fence file per issued fence,
    one done marker per finished unit. LOCK-FREE — the acquire CAS is
    an exclusive ``os.link`` (EEXIST == lost the race), renewal is an
    atomic overwrite of the holder's own fence file, and reads are
    atomic whole-file JSON. Works for multi-process single-host fleets
    and any POSIX shared filesystem whose link/rename are atomic."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -------------------------------------------------------------- paths
    def _fence_path(self, unit_id: int, fence: int) -> str:
        return os.path.join(
            self.root, f"unit_{unit_id:05d}.fence_{fence:06d}.json"
        )

    def _done_path(self, unit_id: int) -> str:
        return os.path.join(self.root, f"unit_{unit_id:05d}.done.json")

    def _cas_create(self, path: str, blob: str) -> bool:
        """Exclusive create via link: True iff WE published ``path``."""
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(blob)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    # ------------------------------------------------------------- reads
    def current_fence(self, unit_id: int) -> int:
        """Highest fence ever issued for ``unit_id`` (0 = none).
        ``max`` is order-insensitive, so readdir order cannot matter."""
        prefix = f"unit_{unit_id:05d}.fence_"
        fences = [
            int(m.group(2))
            for m in (
                _FENCE_RE.match(name) for name in sorted(os.listdir(self.root))
            )
            if m is not None and int(m.group(1)) == unit_id
        ]
        del prefix
        return max(fences) if fences else 0

    def peek(self, unit_id: int) -> Optional[LeaseRecord]:
        fence = self.current_fence(unit_id)
        if fence == 0:
            return None
        with open(self._fence_path(unit_id, fence)) as f:
            return LeaseRecord.from_json(f.read())

    def is_done(self, unit_id: int) -> bool:
        return os.path.exists(self._done_path(unit_id))

    def done_fence(self, unit_id: int) -> Optional[int]:
        try:
            with open(self._done_path(unit_id)) as f:
                return int(json.load(f)["fence"])
        except FileNotFoundError:
            return None

    # ------------------------------------------------------------ writes
    def try_acquire(
        self, unit_id: int, owner: str, ttl_s: float, grace_s: float = 0.5
    ) -> Optional[LeaseRecord]:
        """CAS-acquire at fence ``current + 1``. None when the unit is
        done, the current holder's lease is still live (reclaim waits
        ``deadline + grace_s`` — clock-skew margin vs the holder's own
        local-validity window), or another acquirer won the race."""
        if self.is_done(unit_id):
            return None
        cur = self.peek(unit_id)
        if cur is not None and _wall_now() < cur.deadline + grace_s:
            return None
        fence = (cur.fence if cur is not None else 0) + 1
        rec = LeaseRecord(unit_id, fence, owner, _wall_now() + ttl_s)
        if self._cas_create(self._fence_path(unit_id, fence), rec.to_json()):
            return rec
        return None

    def renew(self, record: LeaseRecord, ttl_s: float) -> LeaseRecord:
        """Extend the holder's deadline. Raises :class:`LeaseLost` when
        a higher fence exists (someone reclaimed) or the unit finished
        under another fence. The overwrite itself cannot steal the unit
        back — peers always look at the HIGHEST fence."""
        cur = self.current_fence(record.unit_id)
        if cur != record.fence:
            raise LeaseLost(
                f"unit {record.unit_id}: fence advanced to {cur} past "
                f"{record.fence} (lease reclaimed)"
            )
        done = self.done_fence(record.unit_id)
        if done is not None and done != record.fence:
            raise LeaseLost(
                f"unit {record.unit_id}: completed under fence {done}"
            )
        new = dataclasses.replace(record, deadline=_wall_now() + ttl_s)
        path = self._fence_path(record.unit_id, record.fence)
        tmp = f"{path}.renew.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(new.to_json())
        os.replace(tmp, path)
        return new

    def release(self, record: LeaseRecord) -> None:
        """Zero the deadline so peers reclaim immediately (graceful
        handoff on preemption). Only meaningful while still current."""
        if self.current_fence(record.unit_id) != record.fence:
            return
        expired = dataclasses.replace(record, deadline=0.0)
        path = self._fence_path(record.unit_id, record.fence)
        tmp = f"{path}.rel.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(expired.to_json())
        os.replace(tmp, path)

    def mark_done(self, unit_id: int, fence: int, owner: str) -> bool:
        """Terminal marker (first writer wins): True iff WE marked it."""
        blob = json.dumps(
            {"unit_id": unit_id, "fence": fence, "owner": owner},
            sort_keys=True,
        )
        return self._cas_create(self._done_path(unit_id), blob)

    def done_fences(self, unit_ids: Sequence[int]) -> Dict[int, int]:
        """unit -> completing fence, for the merge-side ledger audit."""
        out: Dict[int, int] = {}
        for uid in unit_ids:
            fence = self.done_fence(int(uid))
            if fence is not None:
                out[int(uid)] = fence
        return out


# ------------------------------------------------------------ KV lease store
class KVLeaseStore:
    """The same lease algorithm over a key-value coordination service.
    ``kv`` is any object with the four-primitive protocol below —
    :class:`JaxCoordinationKV` adapts the jax coordination-service
    client (the store real multi-host slices rendezvous through); tests
    drive the identical logic with an in-memory fake, so the fence
    machinery is exercised on a single CPU process.

    Protocol: ``put_new(key, value) -> bool`` (exclusive create; False
    when the key exists — the CAS), ``put(key, value)`` (overwrite),
    ``get(key) -> Optional[str]``, ``keys(prefix) -> List[str]``.
    """

    def __init__(self, kv: Any, prefix: str = "seist_tpu/fleet"):
        self.kv = kv
        self.prefix = prefix.rstrip("/")

    @classmethod
    def from_runtime(
        cls, prefix: str = "seist_tpu/fleet"
    ) -> "KVLeaseStore":
        """Build over the live jax coordination service. Raises
        :class:`LeaseStoreError` outside an initialized multi-process
        runtime (callers fall back to :class:`DirLeaseStore`)."""
        from seist_tpu.parallel.dist import _coordination_client

        client = _coordination_client()
        if client is None:
            raise LeaseStoreError(
                "no jax coordination service in this runtime (run under "
                "jax.distributed.initialize, or use a --lease-dir store)"
            )
        return cls(JaxCoordinationKV(client), prefix=prefix)

    # -------------------------------------------------------------- keys
    def _unit_prefix(self, unit_id: int) -> str:
        return f"{self.prefix}/unit_{unit_id:05d}"

    def _fence_key(self, unit_id: int, fence: int) -> str:
        return f"{self._unit_prefix(unit_id)}/fence/{fence:06d}"

    def _done_key(self, unit_id: int) -> str:
        return f"{self._unit_prefix(unit_id)}/done"

    # ------------------------------------------------------------- reads
    def current_fence(self, unit_id: int) -> int:
        names = self.kv.keys(f"{self._unit_prefix(unit_id)}/fence/")
        fences = [int(n.rsplit("/", 1)[-1]) for n in sorted(names)]
        return max(fences) if fences else 0

    def peek(self, unit_id: int) -> Optional[LeaseRecord]:
        fence = self.current_fence(unit_id)
        if fence == 0:
            return None
        blob = self.kv.get(self._fence_key(unit_id, fence))
        if blob is None:
            return None
        return LeaseRecord.from_json(blob)

    def is_done(self, unit_id: int) -> bool:
        return self.kv.get(self._done_key(unit_id)) is not None

    def done_fence(self, unit_id: int) -> Optional[int]:
        blob = self.kv.get(self._done_key(unit_id))
        if blob is None:
            return None
        return int(json.loads(blob)["fence"])

    # ------------------------------------------------------------ writes
    def try_acquire(
        self, unit_id: int, owner: str, ttl_s: float, grace_s: float = 0.5
    ) -> Optional[LeaseRecord]:
        if self.is_done(unit_id):
            return None
        cur = self.peek(unit_id)
        if cur is not None and _wall_now() < cur.deadline + grace_s:
            return None
        fence = (cur.fence if cur is not None else 0) + 1
        rec = LeaseRecord(unit_id, fence, owner, _wall_now() + ttl_s)
        if self.kv.put_new(self._fence_key(unit_id, fence), rec.to_json()):
            return rec
        return None

    def renew(self, record: LeaseRecord, ttl_s: float) -> LeaseRecord:
        cur = self.current_fence(record.unit_id)
        if cur != record.fence:
            raise LeaseLost(
                f"unit {record.unit_id}: fence advanced to {cur} past "
                f"{record.fence} (lease reclaimed)"
            )
        done = self.done_fence(record.unit_id)
        if done is not None and done != record.fence:
            raise LeaseLost(
                f"unit {record.unit_id}: completed under fence {done}"
            )
        new = dataclasses.replace(record, deadline=_wall_now() + ttl_s)
        self.kv.put(self._fence_key(record.unit_id, record.fence), new.to_json())
        return new

    def release(self, record: LeaseRecord) -> None:
        if self.current_fence(record.unit_id) != record.fence:
            return
        expired = dataclasses.replace(record, deadline=0.0)
        self.kv.put(
            self._fence_key(record.unit_id, record.fence), expired.to_json()
        )

    def mark_done(self, unit_id: int, fence: int, owner: str) -> bool:
        blob = json.dumps(
            {"unit_id": unit_id, "fence": fence, "owner": owner},
            sort_keys=True,
        )
        return self.kv.put_new(self._done_key(unit_id), blob)

    def done_fences(self, unit_ids: Sequence[int]) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for uid in unit_ids:
            fence = self.done_fence(int(uid))
            if fence is not None:
                out[int(uid)] = fence
        return out


class JaxCoordinationKV:
    """Adapter: the jax coordination-service client -> the KV protocol
    :class:`KVLeaseStore` speaks. Every service error surfaces as
    :class:`LeaseStoreError` so the guarded wrapper's retry/backoff
    applies uniformly; an existing-key collision on ``put_new`` is the
    ONE non-error outcome (it IS the CAS losing)."""

    def __init__(self, client: Any, timeout_ms: int = 5_000):
        self._client = client
        self._timeout_ms = int(timeout_ms)

    def put_new(self, key: str, value: str) -> bool:
        try:
            self._client.key_value_set(key, value)
            return True
        except Exception as e:  # service error surface is impl-defined
            if "ALREADY_EXISTS" in str(e) or "already exists" in str(e):
                return False
            raise LeaseStoreError(f"kv put_new({key}): {e}") from e

    def put(self, key: str, value: str) -> None:
        try:
            set_fn = getattr(self._client, "key_value_set", None)
            set_fn(key, value, allow_overwrite=True)
        except TypeError:
            # Older client without allow_overwrite: delete-then-set (the
            # only writer of a fence key is its holder, so no lost race).
            try:
                self._client.key_value_delete(key)
                self._client.key_value_set(key, value)
            except Exception as e:  # service error surface is impl-defined
                raise LeaseStoreError(f"kv put({key}): {e}") from e
        except Exception as e:  # service error surface is impl-defined
            raise LeaseStoreError(f"kv put({key}): {e}") from e

    def get(self, key: str) -> Optional[str]:
        try_get = getattr(self._client, "key_value_try_get", None)
        if try_get is not None:
            try:
                return try_get(key)
            except Exception as e:  # NOT_FOUND or service error
                if "NOT_FOUND" in str(e) or "not found" in str(e):
                    return None
                raise LeaseStoreError(f"kv get({key}): {e}") from e
        try:
            return self._client.blocking_key_value_get(key, self._timeout_ms)
        except Exception as e:  # timeout == absent; anything else too —
            # a flaky service reads as a transient store error upstream
            if "NOT_FOUND" in str(e) or "DEADLINE" in str(e):
                return None
            raise LeaseStoreError(f"kv get({key}): {e}") from e

    def keys(self, prefix: str) -> List[str]:
        try:
            pairs = self._client.key_value_dir_get(prefix)
        except Exception as e:  # service error surface is impl-defined
            raise LeaseStoreError(f"kv keys({prefix}): {e}") from e
        return sorted(k for k, _ in pairs)


# ----------------------------------------------------------- guarded wrapper
class GuardedLeaseStore:
    """Every lease-store operation behind retry + jittered exponential
    backoff + an overall per-op deadline, with the batch fault injector
    hooked in front of each raw attempt (latency / error / partition
    windows). Owns the fleet's lease counters — bus counters for
    /metrics.json and a local mirror (:meth:`snapshot`) for worker
    verdict lines. No lock is ever held across a store call: the
    counter lock guards plain ints only."""

    #: transient per-attempt failures the retry loop absorbs
    _TRANSIENT = (OSError, LeaseStoreError)

    def __init__(
        self,
        store: Any,
        config: Optional[LeaseConfig] = None,
        faults: Optional[BatchFaultInjector] = None,
    ):
        self.store = store
        self.config = config or LeaseConfig.from_env()
        self.faults = faults if faults is not None else batch_faults()
        # The io_guard policy carries the repo's ONE rationale'd jitter
        # suppression — lease retries ride it rather than a fresh RNG.
        self._policy = RetryPolicy(
            attempts=self.config.retries,
            backoff_base_s=self.config.backoff_base_s,
            backoff_cap_s=self.config.backoff_cap_s,
        )
        self._counts_lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "acquires": 0,
            "reclaims": 0,
            "renews": 0,
            "releases": 0,
            "expires": 0,
            "fence_rejects": 0,
            "double_commits": 0,
            "store_errors": 0,
            "parks": 0,
        }
        self._bus = {
            "acquires": BUS.counter("batch_lease_acquire"),
            "reclaims": BUS.counter("batch_lease_reclaim"),
            "renews": BUS.counter("batch_lease_renew"),
            "releases": BUS.counter("batch_lease_release"),
            "expires": BUS.counter("batch_lease_expire"),
            "fence_rejects": BUS.counter("batch_lease_fence_reject"),
            "double_commits": BUS.counter("batch_segment_double_commit"),
            "store_errors": BUS.counter("batch_lease_store_error"),
            "parks": BUS.counter("batch_lease_park"),
        }

    def bump(self, name: str, n: int = 1) -> None:
        with self._counts_lock:
            self._counts[name] += n
        self._bus[name].inc(n)

    def snapshot(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self._counts)

    # ------------------------------------------------------------ guarded op
    def _call(self, op: str, fn: Callable, *args) -> Any:
        deadline = _monotonic() + self.config.op_timeout_s
        attempt = 0
        while True:
            try:
                self.faults.store_op(op)
                return fn(*args)
            except LeaseLost:
                raise  # authoritative, not transient
            except self._TRANSIENT as e:
                self.bump("store_errors")
                attempt += 1
                now = _monotonic()
                if attempt >= self.config.retries or now >= deadline:
                    raise LeaseStoreUnavailable(
                        f"lease store op '{op}' failed {attempt}x over "
                        f"{self.config.op_timeout_s:.1f}s: {e}"
                    ) from e
                time.sleep(
                    min(
                        self._policy.sleep_s(attempt - 1),
                        max(0.0, deadline - now),
                    )
                )

    # --------------------------------------------------------- protocol ops
    def try_acquire(self, unit_id: int, owner: str) -> Optional[LeaseRecord]:
        cfg = self.config
        before = self._call("peek", self.store.peek, unit_id)
        rec = self._call(
            "try_acquire",
            self.store.try_acquire,
            unit_id,
            owner,
            cfg.ttl_s,
            cfg.grace_s,
        )
        if rec is not None:
            self.bump("acquires")
            if rec.fence > 1:
                self.bump("reclaims")
            if before is not None and before.deadline <= _wall_now():
                self.bump("expires")  # took over an expired lease
        return rec

    def renew(self, record: LeaseRecord) -> LeaseRecord:
        new = self._call("renew", self.store.renew, record, self.config.ttl_s)
        self.bump("renews")
        return new

    def release(self, record: LeaseRecord) -> None:
        self._call("release", self.store.release, record)
        self.bump("releases")

    def mark_done(self, unit_id: int, fence: int, owner: str) -> bool:
        return self._call(
            "mark_done", self.store.mark_done, unit_id, fence, owner
        )

    def is_done(self, unit_id: int) -> bool:
        return self._call("is_done", self.store.is_done, unit_id)

    def done_fence(self, unit_id: int) -> Optional[int]:
        return self._call("done_fence", self.store.done_fence, unit_id)

    def current_fence(self, unit_id: int) -> int:
        return self._call("current_fence", self.store.current_fence, unit_id)


# --------------------------------------------------------------- held lease
class HeldLease:
    """One acquired lease + its heartbeat thread. The engine calls
    :meth:`check_commit` before every segment commit (the fence guard
    ladder) and reads :attr:`fence` for the segment sidecar; the
    heartbeat renews every ``config.heartbeat`` seconds and keeps the
    LOCAL validity anchor (`monotonic` at the last successful renew)
    that authorizes commits during a store partition. Store I/O always
    happens OUTSIDE the lock."""

    def __init__(self, guarded: GuardedLeaseStore, record: LeaseRecord):
        self.guarded = guarded
        self.config = guarded.config
        self._lock = threading.Lock()
        self._record = record
        self._last_renew_m = _monotonic()
        self._lost_reason: Optional[str] = None
        self._g_age = BUS.gauge("batch_lease_heartbeat_age_s")
        self._stop = threading.Event()
        self._hb = threading.Thread(
            target=self._heartbeat,
            name=f"lease-hb-u{record.unit_id}",
            daemon=True,
        )
        self._hb.start()

    # ------------------------------------------------------------- queries
    @property
    def record(self) -> LeaseRecord:
        with self._lock:
            return self._record

    @property
    def unit_id(self) -> int:
        return self.record.unit_id

    @property
    def fence(self) -> int:
        return self.record.fence

    def lost_reason(self) -> Optional[str]:
        with self._lock:
            return self._lost_reason

    def locally_valid(self) -> bool:
        """True while no peer CAN have reclaimed us: less than ``ttl_s``
        of monotonic time since the last successful renew (the store
        deadline peers compare against was written at that renew)."""
        with self._lock:
            if self._lost_reason is not None:
                return False
            return _monotonic() - self._last_renew_m < self.config.ttl_s

    # --------------------------------------------------------- commit guard
    def check_commit(self) -> None:
        """The commit guard ladder, in order of authority:

        1. heartbeat already proved the fence stale -> FenceRejected;
        2. store reachable -> synchronous fence check (advanced fence
           == a zombie commit attempt, rejected and counted);
        3. store partitioned -> commit allowed only while LOCALLY
           valid; past that window a peer may legitimately own the
           unit, so the segment is discarded (LeaseLost — resume
           recomputes it; content purity makes the redo identical).
        """
        reason = self.lost_reason()
        if reason is not None:
            self.guarded.bump("fence_rejects")
            raise FenceRejected(
                f"unit {self.unit_id}: commit refused, lease lost ({reason})"
            )
        rec = self.record
        try:
            cur = self.guarded.current_fence(rec.unit_id)
        except LeaseStoreUnavailable:
            if self.locally_valid():
                return  # partition + provably-unreclaimable == safe
            with self._lock:
                self._lost_reason = "locally expired during store partition"
            raise LeaseLost(
                f"unit {rec.unit_id}: lease store unreachable and the "
                f"lease's local {self.config.ttl_s:.1f}s validity window "
                "has passed — a peer may own this unit now; discarding "
                "the segment (the reclaimer recommits identical bytes)"
            ) from None
        if cur != rec.fence:
            with self._lock:
                self._lost_reason = f"fence advanced to {cur}"
            self.guarded.bump("fence_rejects")
            raise FenceRejected(
                f"unit {rec.unit_id}: commit with stale fence {rec.fence} "
                f"rejected (current fence {cur})"
            )

    # ----------------------------------------------------------- heartbeat
    def _heartbeat(self) -> None:
        try:
            while not self._stop.wait(self.config.heartbeat):
                with self._lock:
                    rec = self._record
                    if self._lost_reason is not None:
                        return
                    age = _monotonic() - self._last_renew_m
                self._g_age.set(age)
                try:
                    new = self.guarded.renew(rec)
                except LeaseLost as e:
                    with self._lock:
                        self._lost_reason = str(e)
                    return
                except LeaseStoreUnavailable:
                    # Partition: keep beating — local validity decays on
                    # its own and check_commit handles the rest.
                    continue
                now = _monotonic()
                with self._lock:
                    self._record = new
                    self._last_renew_m = now
                self._g_age.set(0.0)
        except Exception:  # record-and-die-visible: a silent heartbeat
            # death would look exactly like a partition; mark the lease
            # lost so the next commit refuses instead of trusting it.
            logger.exception(
                f"[fleet] heartbeat for unit {self.record.unit_id} died"
            )
            with self._lock:
                self._lost_reason = "heartbeat thread died"

    def stop(self) -> None:
        self._stop.set()
        self._hb.join(timeout=max(2.0, self.config.heartbeat * 4))


# -------------------------------------------------------------- fleet worker
class FleetWorker:
    """One worker's lease loop: scan the unit list (rotated by a worker
    offset so an N-worker fleet starts spread out), acquire one lease
    at a time, run it via ``run_unit_fn(unit, held_lease) -> stats``,
    mark it done, repeat until every unit carries a done marker.

    Degradation contract: a partitioned store parks the worker
    (jittered backoff, interruptible by ``stop_event``); a lost lease
    abandons the unit (a peer owns it); preemption (``stop_event``)
    drains the in-flight segment, releases the lease, and returns with
    ``preempted=True`` so the caller can exit 75. The loop never raises
    for store trouble — only :class:`DoubleCommit` (a broken invariant)
    and real engine errors propagate."""

    def __init__(
        self,
        store: Any,
        units: Sequence[Any],  # catalog.WorkUnit
        owner: str,
        run_unit_fn: Callable[[Any, HeldLease], Dict[str, Any]],
        *,
        config: Optional[LeaseConfig] = None,
        faults: Optional[BatchFaultInjector] = None,
        stop_event: Optional[threading.Event] = None,
        scan_offset: int = 0,
    ):
        self.guarded = (
            store
            if isinstance(store, GuardedLeaseStore)
            else GuardedLeaseStore(store, config=config, faults=faults)
        )
        self.config = self.guarded.config
        self.faults = self.guarded.faults
        self.units = list(units)
        self.owner = owner
        self.run_unit_fn = run_unit_fn
        self.stop_event = stop_event or threading.Event()
        self.scan_offset = int(scan_offset) % max(1, len(self.units))
        self._park_policy = RetryPolicy(
            attempts=1 << 30,
            backoff_base_s=self.config.park_s,
            backoff_cap_s=max(self.config.park_s, 10.0),
        )

    def _scan_order(self) -> List[Any]:
        return self.units[self.scan_offset:] + self.units[: self.scan_offset]

    def _park(self, stats: Dict[str, Any], attempt: int) -> None:
        """Partitioned: wait (jittered, growing, interruptible) and let
        the caller rescan. Parking is the NEVER-CRASH stance — the
        worker keeps its process, XLA programs, and store connection
        warm for the heal."""
        self.guarded.bump("parks")
        stats["parks"] += 1
        delay = self._park_policy.sleep_s(min(attempt, 6))
        logger.warning(
            f"[fleet] {self.owner}: lease store unreachable — parked "
            f"{delay:.2f}s (park #{stats['parks']})"
        )
        self.stop_event.wait(timeout=delay)

    # ------------------------------------------------------------- one unit
    def _finish_unit(
        self, unit: Any, held: HeldLease, stats: Dict[str, Any]
    ) -> None:
        """Mark a COMPLETED unit done, parking through partitions until
        the marker lands (work is already durable in the segments; the
        marker must not be lost to a transient outage). A competing done
        marker under a different fence means a peer legitimately
        finished our reclaimed unit — the zombie-completion variant of a
        fence reject."""
        park_attempt = 0
        while not self.stop_event.is_set():
            try:
                if self.guarded.mark_done(
                    unit.unit_id, held.fence, self.owner
                ):
                    stats["units_done"] += 1
                    return
                done = self.guarded.done_fence(unit.unit_id)
                if done is not None and done != held.fence:
                    self.guarded.bump("fence_rejects")
                    stats["units_lost"] += 1
                    logger.warning(
                        f"[fleet] {self.owner}: unit {unit.unit_id} was "
                        f"completed under fence {done} while we held "
                        f"stale fence {held.fence} (zombie completion "
                        "rejected)"
                    )
                else:
                    stats["units_done"] += 1
                return
            except LeaseStoreUnavailable:
                self._park(stats, park_attempt)
                park_attempt += 1

    def _run_leased(
        self, unit: Any, rec: LeaseRecord, stats: Dict[str, Any]
    ) -> str:
        """-> 'done' | 'preempted' | 'lost'."""
        held = HeldLease(self.guarded, rec)
        try:
            out = self.run_unit_fn(unit, held)
        except (FenceRejected, LeaseLost) as e:
            stats["units_lost"] += 1
            logger.warning(f"[fleet] {self.owner}: {e}")
            return "lost"
        except DoubleCommit as e:
            # The last-resort publish guard fired: content is identical
            # (purity) but the fence ladder failed to stop a zombie —
            # surface it, count it, and abandon the unit to its owner.
            self.guarded.bump("double_commits")
            stats["units_lost"] += 1
            logger.error(f"[fleet] {self.owner}: DOUBLE COMMIT — {e}")
            return "lost"
        finally:
            held.stop()
        if out.get("preempted"):
            try:
                self.guarded.release(held.record)
            except (LeaseStoreUnavailable, LeaseLost):
                pass  # expiry hands the unit over anyway
            return "preempted"
        self._finish_unit(unit, held, stats)
        return "done"

    # ----------------------------------------------------------------- run
    def run(self) -> Dict[str, Any]:
        stats: Dict[str, Any] = {
            "owner": self.owner,
            "units_done": 0,
            "units_lost": 0,
            "parks": 0,
            "preempted": False,
        }
        done_local: Set[int] = set()
        acquired_ordinal = 0
        idle_rounds = 0
        park_attempt = 0
        while not self.stop_event.is_set():
            progressed = False
            open_units = 0
            parked = False
            for unit in self._scan_order():
                if unit.unit_id in done_local:
                    continue
                if self.stop_event.is_set():
                    break
                try:
                    if self.guarded.is_done(unit.unit_id):
                        done_local.add(unit.unit_id)
                        continue
                    rec = self.guarded.try_acquire(unit.unit_id, self.owner)
                except LeaseStoreUnavailable:
                    self._park(stats, park_attempt)
                    park_attempt += 1
                    parked = True
                    break
                park_attempt = 0
                if rec is None:
                    open_units += 1  # held by a live peer (or done-raced)
                    continue
                acquired_ordinal += 1
                self.faults.on_unit(acquired_ordinal)
                outcome = self._run_leased(unit, rec, stats)
                progressed = True
                if outcome == "done":
                    done_local.add(unit.unit_id)
                elif outcome == "preempted":
                    break
            if self.stop_event.is_set():
                break
            if parked:
                continue
            if open_units == 0 and len(done_local) == len(self.units):
                break  # every unit carries a done marker
            if not progressed:
                idle_rounds += 1
                self.stop_event.wait(
                    timeout=self._jittered_rescan(idle_rounds)
                )
            else:
                idle_rounds = 0
        stats["preempted"] = self.stop_event.is_set()
        stats["all_done"] = len(done_local) == len(self.units)
        stats["lease"] = self.guarded.snapshot()
        return stats

    def _jittered_rescan(self, idle_rounds: int) -> float:
        policy = RetryPolicy(
            attempts=1 << 30,
            backoff_base_s=self.config.rescan_s,
            backoff_cap_s=max(self.config.rescan_s, 2.0),
        )
        return policy.sleep_s(min(idle_rounds - 1, 3))
