"""Batch (archive-scale) inference: the throughput-bound twin of serve/.

Layout:

    seist_tpu.batch.catalog    deterministic work units over a packed
                               archive + segment-committed, resumable,
                               byte-identical catalog output (the PR 14
                               plan-first/sidecar-commit pattern applied
                               to OUTPUTS)
    seist_tpu.batch.engine     straight-line device feed: double-buffered
                               PackedRawStore fills against ONE AOT
                               multi-batch executable (trunk-once head
                               fan-out for groups), batched decode ->
                               catalog rows

CLI: ``python -m tools.repick_archive`` (map-reduce driver/worker/merge);
``make repick-smoke`` pins the kill/resume byte-identity and the
zero-compile-after-warm-up gate. See docs/DATA.md "Batch re-picking".
"""

from seist_tpu.batch.catalog import (  # noqa: F401
    WorkUnit,
    merge_catalog,
    plan_units,
)
from seist_tpu.batch.engine import RepickEngine  # noqa: F401
