"""Planetary-archive batch inference: straight-line device feed.

The serving path answers one trace in milliseconds; this engine answers
the opposite traffic shape — re-pick an entire packed archive when a
model improves (billions of windows, purely throughput-bound). It is
deliberately NOT a client of the serving stack: no HTTP, no
micro-batcher, no per-request decode. Per work unit (one packed shard,
seist_tpu/batch/catalog.py) it runs the train loop's feed discipline
against serving's AOT executables:

* **fill** — :class:`~seist_tpu.data.ingest.PackedRawStore` batch fills
  (one memcpy per sample from the shard memmap into a staging slab, the
  PR 14 direct-ingest lane) on a producer thread, double-buffered
  through ``pipeline._double_buffer`` so the host read overlaps the
  device compute; io_guard fault semantics (retry / quarantine with
  deterministic ``(seed=0, epoch=0, row)``-keyed replacement) carry
  over unchanged, which keeps resume byte-identical even through
  injected corruption;
* **device** — ONE ahead-of-time-compiled executable per engine
  (``serve/aot.aot_compile_multi``): ``batches_per_call`` full batches
  enter with a leading step axis and ``lax.map`` runs normalize ->
  trunk -> heads entirely in-program — the PR 10 trunk-once fan-out for
  groups, the ``steps_per_call`` idea from the train loop for dispatch
  — so host Python touches the critical path once per K batches and
  post-warm-up traffic can never trigger an XLA compile
  (``CompileBudget`` gate, ``make repick-smoke``);
* **decode** — batched ``ops/postprocess.decode_head_batch`` (the same
  compiled pick/detect programs eval and serve use) + ONE
  ``jax.device_get`` per call, then ``ops/results.catalog_rows``;
* **write** — rows committed per segment via catalog.commit_segment
  (tmp+rename), the resume granularity.

Variants: the engine compiles its program per the serving weight
conventions (``aot.variant_compute`` / ``transform_variables``) and
non-fp32 variants are parity-gated at load against the engine's own
fp32 program — disable, don't re-pick wrong.

Observability: ``batch_infer_batches/waveforms/bytes`` counters,
``batch_infer_fill/device/decode/write`` spans, and prefetch
backpressure (``batch_infer_backpressure_s``) on the obs bus; the same
stage budget is accumulated locally for the BENCH ``step_breakdown``.

Chaos: ``SEIST_FAULT_REPICK_SLOW_MS`` sleeps that long per device call
(the smoke lane uses it to land a SIGKILL mid-shard deterministically).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from seist_tpu.batch import catalog
from seist_tpu.ops.postprocess import decode_head_batch
from seist_tpu.ops.results import catalog_row_lines, catalog_rows
from seist_tpu.serve import aot
from seist_tpu.utils.logger import logger

#: Decode thresholds (serve/protocol.PredictOptions defaults, restated
#: here so the engine does not import the serving wire layer).
DEFAULT_DECODE = {
    "ppk_threshold": 0.3,
    "spk_threshold": 0.3,
    "det_threshold": 0.5,
    "min_peak_dist": 1.0,
    "max_events": 8,
}


def _block(out: Any) -> None:
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        getattr(leaf, "block_until_ready", lambda: None)()


def normalize_transpose(raw):
    """In-trace input prep: (B, C, L) float32 -> normalized
    channels-last (B, L, C) — the same 'std' z-score the serving path
    applies host-side (preprocess.normalize, zero std divides by 1),
    moved on device so the host fill stays a pure memcpy. Module-level
    so the irlint manifest lowers the exact program the engine runs."""
    import jax.numpy as jnp

    x = raw - jnp.mean(raw, axis=2, keepdims=True)
    std = jnp.std(raw, axis=2, keepdims=True)
    x = x / jnp.where(std == 0, 1.0, std)
    return jnp.transpose(x, (0, 2, 1))


def dequant_rows(q, scale):
    """In-trace dequant of int8 shard rows: (B, C, L) int8 + per-row
    per-channel (B, C) float32 scales -> float32 waveforms. Fused into
    the consuming program (stage_raw ingest) so the widening happens on
    DEVICE — the host->device transfer stays 4x narrow. The z-score in
    :func:`normalize_transpose` is per-channel scale-invariant, so the
    quantized path's parity vs fp32 storage is bounded by rounding
    alone."""
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale[:, :, None]


class RepickEngine:
    """One worker's archive re-picking loop: a loaded pool entry
    (ModelEntry or MultiTaskEntry) driven at full batch straight off a
    :class:`~seist_tpu.data.ingest.PackedRawStore`."""

    def __init__(
        self,
        entry: Any,
        store: Any,
        *,
        sampling_rate: int,
        batch_size: int = 64,
        batches_per_call: int = 4,
        variant: str = "fp32",
        decode_opts: Optional[Dict[str, Any]] = None,
        keys: Optional[Sequence[str]] = None,
        stations: Optional[Dict[str, Dict[str, Any]]] = None,
        prefetch: int = 2,
        tasks: Optional[Sequence[str]] = None,
    ) -> None:
        if entry.window != store.raw_len:
            raise ValueError(
                f"model window {entry.window} != archive trace length "
                f"{store.raw_len}; the repick engine feeds one archive "
                "row per window (load the entry with window=raw_len)"
            )
        if entry.in_channels != store.n_ch:
            raise ValueError(
                f"model wants {entry.in_channels} channels, archive has "
                f"{store.n_ch}"
            )
        if variant not in aot.VARIANTS:
            raise ValueError(
                f"unknown variant {variant!r}; use one of {aot.VARIANTS}"
            )
        self.entry = entry
        self.store = store
        self.sampling_rate = int(sampling_rate)
        self.batch_size = int(batch_size)
        self.batches_per_call = int(batches_per_call)
        self.rows_per_call = self.batch_size * self.batches_per_call
        self.variant = variant
        self.decode_opts = {**DEFAULT_DECODE, **(decode_opts or {})}
        self.keys = np.asarray(keys) if keys is not None else None
        # {key: station metadata} for catalog provenance (catalog_rows).
        self.stations = dict(stations) if stations else None
        self.prefetch = int(prefetch)
        self.tasks = (
            tuple(tasks)
            if tasks is not None
            else (tuple(entry.tasks) if entry.is_group else (entry.name,))
        )
        if entry.is_group:
            unknown = [t for t in self.tasks if t not in entry.heads]
            if unknown:
                raise ValueError(
                    f"group '{entry.name}' does not serve tasks {unknown}; "
                    f"available: {list(entry.tasks)}"
                )
        # int8 end-to-end: a stage_raw store hands the engine int8 rows
        # plus resident per-row scales; the program dequantizes on
        # device (dequant_rows fused ahead of the z-score).
        self.stage_raw = bool(getattr(store, "stage_raw", False))
        self._program: Optional[aot.AotProgram] = None
        self._warm = False
        self._slow_ms = float(
            os.environ.get("SEIST_FAULT_REPICK_SLOW_MS", "0") or 0
        )
        self.stage = {"fill": 0.0, "device": 0.0, "decode": 0.0, "write": 0.0}
        self.warmup_report: Dict[str, Any] = {}
        from seist_tpu.obs.bus import BUS

        self._c_batches = BUS.counter("batch_infer_batches")
        self._c_calls = BUS.counter("batch_infer_calls")
        self._c_waveforms = BUS.counter("batch_infer_waveforms")
        self._c_bytes = BUS.counter("batch_infer_bytes")

    # ------------------------------------------------------------ programs
    def _step_fn(self, variant: str):
        """One micro-batch's full program body: [dequant ->] prep ->
        forward (single model) or trunk -> requested heads (group
        fan-out) under the serving variant conventions
        (aot.variant_compute / head_variant_compute + eager
        transform_variables, so the executable holds the variant's
        weights at rest). stage_raw stores add a second (B, C) scale
        arg and the int8->f32 widening happens HERE, in-program."""
        entry = self.entry
        if not entry.is_group:
            compute = aot.variant_compute(
                lambda v, x: entry.model.apply(v, x, train=False), variant
            )
            tv = aot.transform_variables(entry.variables, variant)
            task = self.tasks[0]

            def body(x):
                return {task: compute(tv, x)}

        else:
            from seist_tpu.models.seist import backbone_apply

            trunk_compute = aot.variant_compute(
                lambda v, x: backbone_apply(entry.trunk_model, v, x),
                variant,
                cast_outputs=False,  # bf16 features flow to bf16 heads
            )
            trunk_v = aot.transform_variables(entry.trunk_variables, variant)
            head_computes = {
                t: aot.head_variant_compute(entry.heads[t].model, variant)
                for t in self.tasks
            }
            head_vs = {
                t: aot.transform_variables(entry.heads[t].variables, variant)
                for t in self.tasks
            }

            def body(x):
                feats = trunk_compute(trunk_v, x)
                return {
                    t: head_computes[t](head_vs[t], feats, x)
                    for t in self.tasks
                }

        if self.stage_raw:

            def step(raw, scale):
                return body(normalize_transpose(dequant_rows(raw, scale)))

        else:

            def step(raw):
                return body(normalize_transpose(raw))

        return step

    def _arg_shapes(self):
        """PER-STEP compile signature: stage_raw programs take the int8
        rows AS STORED plus the per-row scale sidecar."""
        b, c, n = self.batch_size, self.store.n_ch, self.store.raw_len
        if self.stage_raw:
            return [((b, c, n), np.int8), ((b, c), np.float32)]
        return [((b, c, n), np.float32)]

    def _compile(self, variant: str) -> aot.AotProgram:
        key = (
            f"repick/{self.entry.name}/b{self.batch_size}"
            f"x{self.batches_per_call}/{variant}"
            + ("+i8shards" if self.stage_raw else "")
        )
        return aot.aot_compile_multi(
            key,
            self._step_fn(variant),
            self._arg_shapes(),
            steps=self.batches_per_call,
            model=self.entry.name,
        )

    def warmup(self) -> Dict[str, Any]:
        """Compile the full-batch program (parity-gating non-fp32
        variants against the engine's own fp32 program) and push one
        synthetic call through the COMPLETE path — forward, pick/detect
        decode programs, device_get — so nothing compiles after this
        returns (the CompileBudget gate's contract)."""
        from seist_tpu.obs.bus import monotonic

        t0 = monotonic()
        program = self._compile(self.variant)
        if self.variant != "fp32":
            ref_prog = self._compile("fp32")
            self._gate_variant(ref_prog, program)
        self._program = program
        # One call end-to-end: warms pick_peaks/detect_events at the
        # decode shape and proves the executable answers.
        shape = (
            self.batches_per_call, self.batch_size, self.store.n_ch,
            self.store.raw_len,
        )
        if self.stage_raw:
            args = (
                np.zeros(shape, np.int8),
                np.ones(shape[:3], np.float32),
            )
        else:
            args = (np.zeros(shape, np.float32),)
        out = program(*args)
        _block(out)
        self._decode_call(out, n_valid=1, row_lo=0)
        self._warm = True
        self.stage = {k: 0.0 for k in self.stage}
        self.warmup_report = {
            "program": program.key,
            "compile_ms": round(program.compile_ms, 1),
            "flops_per_call": program.flops,
            "warmup_s": round(monotonic() - t0, 2),
        }
        logger.info(
            f"[repick] aot {program.key} ({program.compile_ms:.0f} ms, "
            f"{program.flops:.3g} flops/call)"
        )
        return self.warmup_report

    def _gate_variant(
        self, ref_prog: aot.AotProgram, var_prog: aot.AotProgram
    ) -> None:
        """Decision-level parity of the variant program against fp32 on
        a deterministic probe — per head for groups. A failing head
        refuses the run (re-picking an archive wrong is strictly worse
        than re-picking it slower)."""
        import jax

        rng = np.random.default_rng(0)
        probe = rng.standard_normal(
            (self.batches_per_call, self.batch_size, self.store.n_ch,
             self.store.raw_len)
        ).astype(np.float32)
        if self.stage_raw:
            # Quantize the probe with the PACK-TIME quantizer so both
            # programs see the archive's actual inputs; ref (fp32
            # weights) and variant then differ by the weight variant
            # alone — the gate isolates exactly that error.
            from seist_tpu.data import packed

            k, b, c, n = probe.shape
            q, sc = packed.quantize_rows(probe.reshape(-1, n))
            args = (
                q.reshape(k, b, c, n),
                sc.reshape(k, b, c),
            )
        else:
            args = (probe,)
        ref = jax.device_get(ref_prog(*args))
        out = jax.device_get(var_prog(*args))
        failed = []
        for task in self.tasks:
            spec = (
                self.entry.heads[task].spec
                if self.entry.is_group
                else self.entry.spec
            )
            # head_scale lives on the TaskHead for groups but on the
            # MODEL for single-task entries (serve/pool._gate_variants
            # reads it the same way).
            scale_owner = (
                self.entry.heads[task]
                if self.entry.is_group
                else self.entry.model
            )
            kind, _ = aot.parity_kind(spec)
            scale = float(getattr(scale_owner, "head_scale", 1.0) or 1.0)
            a = _first_leaf(ref[task])
            b = _first_leaf(out[task])
            ok, err = aot.variant_parity(
                a, b, self.variant, kind=kind, scale=scale
            )
            logger.info(
                f"[repick] variant gate {self.entry.name}/{task}/"
                f"{self.variant}: {'ok' if ok else 'FAILED'} "
                f"(err={err:.2g}, {kind})"
            )
            if not ok:
                failed.append(task)
        if failed:
            raise RuntimeError(
                f"variant '{self.variant}' failed the parity gate for "
                f"task(s) {failed} — refusing to re-pick the archive "
                "with divergent outputs (run fp32, or fix the variant)"
            )

    # -------------------------------------------------------------- decode
    def _decode_call(
        self, out: Any, *, n_valid: int, row_lo: int
    ) -> List[Dict[str, Any]]:
        import jax

        n_rows = self.rows_per_call
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((n_rows,) + a.shape[2:]), out
        )
        decoded = {}
        for task in self.tasks:
            spec = (
                self.entry.heads[task].spec
                if self.entry.is_group
                else self.entry.spec
            )
            is_picker = (
                self.entry.heads[task].is_picker
                if self.entry.is_group
                else self.entry.is_picker
            )
            decoded[task] = decode_head_batch(
                spec,
                flat[task],
                is_picker=is_picker,
                sampling_rate=self.sampling_rate,
                **self.decode_opts,
            )
        # ONE device->host round trip for every head's results (the
        # Metrics.to_dict batched-get idiom); catalog_rows then slices
        # plain host arrays.
        decoded = jax.device_get(decoded)
        row_ids = np.arange(row_lo, row_lo + n_valid, dtype=np.int64)
        keys = (
            self.keys[row_lo : row_lo + n_valid]
            if self.keys is not None
            else None
        )
        return catalog_rows(
            decoded, n_valid=n_valid, row_ids=row_ids, keys=keys,
            stations=self.stations,
        )

    # ---------------------------------------------------------------- feed
    def _fill_calls(
        self,
        unit: catalog.WorkUnit,
        start_call: int,
        stop_event: Optional[threading.Event],
        abort: Optional[threading.Event] = None,
    ):
        """Producer-side call feed: one PackedRawStore staging fill per
        device call, reshaped (free) to the program's (k, B, C, L). The
        tail call pads by repeating the last row — padding is a pure
        function of the plan, so resume stays byte-identical; decode
        drops pad rows via n_valid."""
        from seist_tpu.obs.bus import BUS, monotonic

        n_calls = catalog.calls_per_unit(unit, self.rows_per_call)
        for c in range(start_call, n_calls):
            if stop_event is not None and stop_event.is_set():
                return
            if abort is not None and abort.is_set():
                return
            lo = unit.row_lo + c * self.rows_per_call
            hi = min(lo + self.rows_per_call, unit.row_hi)
            ids = np.arange(lo, hi, dtype=np.int64)
            n_valid = ids.size
            if n_valid < self.rows_per_call:
                ids = np.concatenate(
                    [ids, np.repeat(ids[-1], self.rows_per_call - n_valid)]
                )
            t0 = monotonic()
            with BUS.span("batch_infer_fill"):
                rows = self.store.row_batch_at(ids, epoch=0, idx=ids)
                x = rows["data"].reshape(
                    self.batches_per_call,
                    self.batch_size,
                    self.store.n_ch,
                    self.store.raw_len,
                )
                if self.stage_raw:
                    # Resident per-row scales ride the same fallback
                    # gather as the labels — row<->scale stays
                    # consistent through quarantine replacement.
                    args = (
                        x,
                        rows["data_scale"].reshape(
                            self.batches_per_call,
                            self.batch_size,
                            self.store.n_ch,
                        ),
                    )
                else:
                    args = (x,)
            yield c, args, n_valid, lo, monotonic() - t0

    @staticmethod
    def _put(item):
        """Double-buffer transform: start the host->device copy of the
        staged slab ahead of the consumer (async on accelerators; on CPU
        device_put may alias, which is safe here because CPU staging
        slabs are fresh per fill — ingest.py's reuse_staging auto rule)."""
        import jax

        c, args, n_valid, lo, fill_s = item
        return c, jax.device_put(args), n_valid, lo, fill_s

    # ----------------------------------------------------------------- run
    def run_unit(
        self,
        unit: catalog.WorkUnit,
        out_dir: str,
        *,
        commit_every: int = 4,
        stop_event: Optional[threading.Event] = None,
        lease: Optional[Any] = None,  # batch.fleet.HeldLease
    ) -> Dict[str, Any]:
        """Re-pick one work unit, committing a segment every
        ``commit_every`` device calls; resumes at the first missing
        segment. Returns per-unit stats. ``stop_event`` (SIGTERM) is
        honored at segment boundaries — the current segment commits,
        later ones stay holes for the resume.

        Under a fleet ``lease`` every commit first passes the fence
        guard ladder (``lease.check_commit()`` — raises FenceRejected /
        LeaseLost when this worker no longer owns the unit) and the
        segment is published EXCLUSIVELY with the lease's fencing token;
        an existing segment file surfaces as ``fleet.DoubleCommit``
        (zombie publish stopped at the filesystem, counted on the bus)."""
        from seist_tpu.data.pipeline import _double_buffer
        from seist_tpu.obs.bus import BUS, monotonic

        if not self._warm:
            self.warmup()
        n_calls = catalog.calls_per_unit(unit, self.rows_per_call)
        total_seg = catalog.segments_per_unit(
            unit, self.rows_per_call, commit_every
        )
        start_seg = catalog.first_missing_segment(
            out_dir, unit, self.rows_per_call, commit_every
        )
        stats = {
            "unit": unit.unit_id,
            "rows": 0,
            "calls": 0,
            "segments": 0,
            "segments_skipped": start_seg,
            "preempted": False,
        }
        if start_seg >= total_seg:
            return stats
        # The engine's own stop flag rides alongside the caller's: set
        # in the finally-drain so a consumer-side exception halts the
        # producer at its next fill instead of letting it read/device_put
        # the whole remaining unit while the error waits to propagate.
        abort = threading.Event()
        gen = _double_buffer(
            self._fill_calls(
                unit, start_seg * commit_every, stop_event, abort
            ),
            self._put,
            self.prefetch,
            account="batch_infer",
        )
        lines: List[str] = []
        seg = start_seg
        try:
            for c, x_dev, n_valid, row_lo, fill_s in gen:
                self.stage["fill"] += fill_s
                if self._slow_ms:
                    time.sleep(self._slow_ms / 1e3)
                t0 = monotonic()
                with BUS.span("batch_infer_device"):
                    out = self._program(*x_dev)
                    _block(out)
                self.stage["device"] += monotonic() - t0
                t0 = monotonic()
                with BUS.span("batch_infer_decode"):
                    rows = self._decode_call(
                        out, n_valid=n_valid, row_lo=row_lo
                    )
                    lines.extend(catalog_row_lines(rows))
                self.stage["decode"] += monotonic() - t0
                self._c_calls.inc()
                self._c_batches.inc(self.batches_per_call)
                self._c_waveforms.inc(n_valid)
                self._c_bytes.inc(n_valid * self.store.row_nbytes)
                stats["rows"] += n_valid
                stats["calls"] += 1
                if (c + 1) == min((seg + 1) * commit_every, n_calls):
                    t0 = monotonic()
                    with BUS.span("batch_infer_write"):
                        if lease is not None:
                            lease.check_commit()
                            try:
                                catalog.commit_segment(
                                    out_dir, unit.unit_id, seg, lines,
                                    fence=lease.fence,
                                )
                            except FileExistsError as e:
                                # Counted by the fleet worker's guarded
                                # store (single source for the bus + the
                                # verdict-line mirror).
                                from seist_tpu.batch import fleet

                                raise fleet.DoubleCommit(
                                    f"unit {unit.unit_id} seg {seg}: "
                                    f"already committed — fence "
                                    f"{lease.fence} raced past its check"
                                ) from e
                        else:
                            catalog.commit_segment(
                                out_dir, unit.unit_id, seg, lines
                            )
                    self.stage["write"] += monotonic() - t0
                    lines = []
                    seg += 1
                    stats["segments"] += 1
                    if stop_event is not None and stop_event.is_set():
                        stats["preempted"] = True
                        break
        finally:
            # A preempted/aborted consumer must drain the bounded queue
            # so the producer thread can observe the stop and exit (at
            # most `prefetch` already-filled items — cheap, BECAUSE the
            # abort flag stops further fills first).
            abort.set()
            for _ in gen:
                pass
        if (
            stats["calls"] < n_calls - start_seg * commit_every
            and not stats["preempted"]
        ):
            # The producer stopped early (stop_event raced a fill — at
            # worst mid-segment, whose partial rows are discarded; the
            # resume recomputes the whole segment, keeping segment
            # content pure). The unit is NOT complete and must say so.
            stats["preempted"] = True
        return stats

    def run_units(
        self,
        units: Sequence[catalog.WorkUnit],
        out_dir: str,
        *,
        commit_every: int = 4,
        stop_event: Optional[threading.Event] = None,
        compile_gate: bool = False,
        progress: Optional[Any] = None,  # train.checkpoint.ProgressFile
        unit_retries: int = 0,
    ) -> Dict[str, Any]:
        """Re-pick a worker's unit list. With ``compile_gate`` the whole
        post-warm-up loop runs inside a ``CompileBudget`` window (the
        jaxlint runtime monitor) and the stats report how many traces /
        XLA compiles it saw — the acceptance gate pins ZERO.

        A unit that raises is retried up to ``unit_retries`` times (the
        committed-segment resume makes a retry cheap: it restarts at the
        unit's first hole), and EVERY failed attempt emits a structured
        record — ``batch_unit_error{unit=,exc=}`` on the obs bus (so
        /metrics.json distinguishes a STUCK unit from a slow one — the
        fleet supervisor's signal) plus a ``unit_errors`` list entry in
        the returned stats. With the default ``unit_retries=0`` the
        exception still propagates after being recorded: fail-loud is
        unchanged, just no longer invisible to telemetry."""
        from seist_tpu.obs.bus import BUS, monotonic

        if not self._warm:
            self.warmup()
        budget = None
        if compile_gate:
            from tools.jaxlint.runtime import CompileBudget

            budget = CompileBudget()
        t0 = monotonic()
        stats: Dict[str, Any] = {
            "units": 0, "units_skipped": 0, "rows": 0, "calls": 0,
            "segments": 0, "segments_skipped": 0, "preempted": False,
            "unit_errors": [],
        }
        ctx = budget if budget is not None else _NullCtx()
        with ctx:
            for unit in units:
                attempt = 0
                while True:
                    try:
                        u = self.run_unit(
                            unit, out_dir, commit_every=commit_every,
                            stop_event=stop_event,
                        )
                        break
                    except Exception as e:  # record + retry/re-raise: a
                        # quarantined unit must be VISIBLE on the bus,
                        # not only in a log line
                        record = {
                            "unit": unit.unit_id,
                            "exc": type(e).__name__,
                            "retries": attempt,
                        }
                        stats["unit_errors"].append(record)
                        BUS.counter(
                            "batch_unit_error",
                            unit=str(unit.unit_id),
                            exc=type(e).__name__,
                        ).inc()
                        logger.warning(
                            f"[batch] unit {unit.unit_id} attempt "
                            f"{attempt + 1} failed: {type(e).__name__}: {e}"
                        )
                        if attempt >= unit_retries:
                            raise
                        attempt += 1
                stats["rows"] += u["rows"]
                stats["calls"] += u["calls"]
                stats["segments"] += u["segments"]
                stats["segments_skipped"] += u["segments_skipped"]
                if u["rows"] == 0 and u["segments_skipped"]:
                    stats["units_skipped"] += 1
                else:
                    stats["units"] += 1
                if progress is not None:
                    progress.save({
                        "unit": unit.unit_id,
                        "next_segment": u["segments_skipped"] + u["segments"],
                        "preempted": u["preempted"],
                        **{k: stats[k] for k in ("rows", "calls", "segments")},
                    })
                if u["preempted"]:
                    stats["preempted"] = True
                    break
        wall = monotonic() - t0
        stats["wall_s"] = round(wall, 3)
        stats["waveforms_per_sec"] = (
            round(stats["rows"] / wall, 2) if wall > 0 else 0.0
        )
        stats["stage_seconds"] = {
            k: round(v, 3) for k, v in self.stage.items()
        }
        if stats["rows"]:
            stats["stage_ms_per_wf"] = {
                k: round(v * 1e3 / stats["rows"], 4)
                for k, v in self.stage.items()
            }
        if budget is not None:
            stats["compiles_after_warmup"] = budget.total("")
            stats["xla_compiles_after_warmup"] = budget.backend_compiles
        return stats


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _first_leaf(out: Any) -> Any:
    return out[0] if isinstance(out, (tuple, list)) else out
