"""Repick catalog: deterministic work units + segment-committed output.

The batch-inference engine (seist_tpu/batch/engine.py) is a map-reduce
over a packed archive (data/packed.py). This module owns the MAP side's
addressing and the REDUCE side's merge — the plan-first / sidecar-commit
pattern PR 14 built for packing, applied to OUTPUTS:

* **Work unit** = one packed shard's index rows ``[row_lo, row_hi)`` in
  pack order. :func:`plan_units` is a pure function of the archive's
  index — never of worker count or of what output already exists — so
  any worker layout produces the identical unit list.
* **Segment** = ``commit_every`` consecutive device calls of one unit
  (a call is ``batches_per_call x batch_size`` rows). Each segment's
  catalog rows are written to ``unit_XXXXX.seg_XXXX.jsonl`` via
  tmp+rename: the rename is the commit point, so a SIGKILL at any
  instant leaves either a complete segment or a resumable hole, and
  :func:`first_missing_segment` restarts a worker at its exact offset.
* **Plan identity** — ``repick_plan.json`` records everything that
  determines segment boundaries and row content (batch geometry, model,
  variant, thresholds). Workers refuse to resume into an output
  directory whose plan differs (same rule as the packer's sidecar plan
  identity: a geometry change must restart, never silently mix).
* **Merge** — segments concatenated in (unit, segment) order into
  ``catalog.jsonl``; ``catalog_meta.json`` is written LAST (a directory
  without it is an incomplete catalog). Because every row is a pure
  function of (archive, plan), the merged catalog is byte-identical
  across worker counts and across kill/resume histories — ``make
  repick-smoke`` pins this.

Rows are compact JSON objects, one per waveform, sorted keys (see
ops/results.catalog_rows and docs/DATA.md "Batch re-picking").
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_PLAN = "repick_plan.json"
_CATALOG = "catalog.jsonl"
_CATALOG_META = "catalog_meta.json"
_SEG_RE = re.compile(r"^unit_(\d{5})\.seg_(\d{4})\.jsonl$")


@dataclasses.dataclass(frozen=True)
class WorkUnit:
    """One packed shard's rows ``[row_lo, row_hi)`` (pack index order)."""

    unit_id: int  # == packed shard id
    row_lo: int
    row_hi: int

    @property
    def n(self) -> int:
        return self.row_hi - self.row_lo


def plan_units(shards_col: np.ndarray) -> List[WorkUnit]:
    """The deterministic unit partition from the archive index's
    ``shard`` column (rows of one shard are contiguous in pack order —
    the index is merged sidecar-by-sidecar)."""
    shards_col = np.asarray(shards_col, np.int64)
    if shards_col.size == 0:
        return []
    if (np.diff(shards_col) < 0).any():
        raise ValueError(
            "archive index 'shard' column is not in pack order; refusing "
            "to plan work units over a reordered index"
        )
    units: List[WorkUnit] = []
    ids, starts = np.unique(shards_col, return_index=True)
    bounds = list(starts) + [shards_col.size]
    for i, uid in enumerate(ids):
        units.append(WorkUnit(int(uid), int(bounds[i]), int(bounds[i + 1])))
    return units


# ------------------------------------------------------------- segment math
def calls_per_unit(unit: WorkUnit, rows_per_call: int) -> int:
    return -(-unit.n // rows_per_call)


def segments_per_unit(
    unit: WorkUnit, rows_per_call: int, commit_every: int
) -> int:
    return -(-calls_per_unit(unit, rows_per_call) // commit_every)


def segment_path(out_dir: str, unit_id: int, seg: int) -> str:
    return os.path.join(out_dir, f"unit_{unit_id:05d}.seg_{seg:04d}.jsonl")


def segment_fence_path(out_dir: str, unit_id: int, seg: int) -> str:
    return segment_path(out_dir, unit_id, seg) + ".fence"


def read_segment_fence(out_dir: str, unit_id: int, seg: int) -> Optional[int]:
    """The fencing token recorded beside a fleet-committed segment, or
    None for serial commits / the crash window between link and sidecar
    (both tolerated by the merge audit — the segment bytes themselves
    are identical either way)."""
    try:
        with open(segment_fence_path(out_dir, unit_id, seg)) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return None


def commit_segment(
    out_dir: str,
    unit_id: int,
    seg: int,
    lines: Sequence[str],
    *,
    fence: Optional[int] = None,
) -> str:
    """Atomically commit one segment's catalog rows.

    Serial path (``fence=None``, the PR 14 contract unchanged):
    tmp+rename — last rename wins with identical content, since rows are
    a pure function of the plan.

    Fleet path (``fence`` = the committer's lease fencing token):
    EXCLUSIVE publish via ``os.link`` — the first committer wins and a
    zombie worker racing past its fence check hits FileExistsError
    instead of silently re-publishing (the engine converts that into the
    counted ``DoubleCommit``). The winning fence is recorded in a
    ``.fence`` sidecar AFTER the link so the merge audit can reject
    stale-fence histories; catalog bytes are untouched (byte-identity
    with serial runs is preserved — the sidecar is not merged)."""
    path = segment_path(out_dir, unit_id, seg)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("".join(lines))
    if fence is None:
        os.replace(tmp, path)
        return path
    try:
        os.link(tmp, path)
    finally:
        os.unlink(tmp)
    fpath = segment_fence_path(out_dir, unit_id, seg)
    ftmp = f"{fpath}.tmp.{os.getpid()}"
    with open(ftmp, "w") as f:
        f.write(str(int(fence)))
    os.replace(ftmp, fpath)
    return path


def first_missing_segment(
    out_dir: str, unit: WorkUnit, rows_per_call: int, commit_every: int
) -> int:
    """Resume point: the first segment of ``unit`` with no committed
    file. Returns ``segments_per_unit`` when the unit is complete.
    Committed files are trusted (the rename only ever publishes whole
    segments); holes after a committed segment are repacked from the
    hole on — later segments are redundant work at worst, never wrong
    (their content is deterministic)."""
    total = segments_per_unit(unit, rows_per_call, commit_every)
    for seg in range(total):
        if not os.path.exists(segment_path(out_dir, unit.unit_id, seg)):
            return seg
    return total


# --------------------------------------------------------------- plan file
def write_or_check_plan(out_dir: str, plan: Dict[str, Any]) -> None:
    """Create ``repick_plan.json`` (atomic) or validate the existing one
    matches — the resume geometry guard. Two workers racing the create
    write identical bytes, so either rename is correct."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, _PLAN)
    blob = json.dumps(plan, sort_keys=True)
    if os.path.exists(path):
        with open(path) as f:
            existing = f.read()
        if existing != blob:
            raise ValueError(
                f"output dir {out_dir} holds a catalog built under a "
                "different plan (batch geometry / model / variant / "
                "thresholds changed); resume would mix incompatible "
                "segments — use a fresh --out or delete the directory"
            )
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(blob)
    os.replace(tmp, path)


def read_plan(out_dir: str) -> Dict[str, Any]:
    with open(os.path.join(out_dir, _PLAN)) as f:
        return json.load(f)


# ------------------------------------------------------------------- merge
def merge_catalog(
    out_dir: str,
    units: Sequence[WorkUnit],
    rows_per_call: int,
    commit_every: int,
    *,
    meta: Optional[Dict[str, Any]] = None,
    fences: Optional[Dict[int, int]] = None,
) -> Dict[str, Any]:
    """Reduce step: concatenate every unit's segments in (unit, segment)
    order into ``catalog.jsonl`` (tmp+rename), then commit
    ``catalog_meta.json`` LAST. Refuses loudly while any segment is
    missing (a partial merge would look complete).

    ``fences`` (fleet merges only) maps unit id -> the fencing token the
    unit was marked DONE under, from the lease store's done ledger. The
    merge then audits every segment's ``.fence`` sidecar: a sidecar
    GREATER than the done fence means a zombie published a segment after
    the unit was already completed and handed over — the exactly-once
    invariant is broken and the merge refuses. Sidecars at or below the
    done fence are normal history (earlier incarnations' segments are
    trusted: content is a pure function of the plan); a missing sidecar
    is the link-to-sidecar crash window, also trusted. The audit summary
    lands in ``catalog_meta.json``; catalog bytes never depend on it."""
    missing: List[str] = []
    stale: List[str] = []
    fleet_segments = 0
    for unit in units:
        total = segments_per_unit(unit, rows_per_call, commit_every)
        done_fence = (fences or {}).get(unit.unit_id)
        for seg in range(total):
            if not os.path.exists(segment_path(out_dir, unit.unit_id, seg)):
                missing.append(f"unit {unit.unit_id} seg {seg}")
                continue
            if fences is None:
                continue
            seg_fence = read_segment_fence(out_dir, unit.unit_id, seg)
            if seg_fence is not None:
                fleet_segments += 1
                if done_fence is not None and seg_fence > done_fence:
                    stale.append(
                        f"unit {unit.unit_id} seg {seg}: committed under "
                        f"fence {seg_fence} > done fence {done_fence}"
                    )
    if missing:
        raise FileNotFoundError(
            f"catalog merge: {len(missing)} segment(s) not committed yet "
            f"(first: {missing[0]}) — finish or resume the workers first"
        )
    if stale:
        raise ValueError(
            f"catalog merge: {len(stale)} segment(s) carry a fence NEWER "
            f"than the fence their unit was completed under (first: "
            f"{stale[0]}) — a zombie worker wrote after handover; the "
            "exactly-once commit invariant is broken, refusing to merge"
        )
    cat_path = os.path.join(out_dir, _CATALOG)
    tmp = f"{cat_path}.tmp.{os.getpid()}"
    n_rows = 0
    with open(tmp, "w") as f:
        for unit in units:
            total = segments_per_unit(unit, rows_per_call, commit_every)
            for seg in range(total):
                with open(
                    segment_path(out_dir, unit.unit_id, seg)
                ) as seg_f:
                    for line in seg_f:
                        f.write(line)
                        n_rows += 1
    os.replace(tmp, cat_path)
    out_meta = dict(meta or {})
    out_meta.update({
        "n_rows": n_rows,
        "n_units": len(units),
        "catalog": _CATALOG,
    })
    if fences is not None:
        out_meta["fleet"] = {
            "done_fences": {str(k): fences[k] for k in sorted(fences)},
            "fenced_segments": fleet_segments,
            "stale_fence_segments": 0,  # a nonzero count never merges
        }
    meta_tmp = os.path.join(out_dir, _CATALOG_META + f".tmp.{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(out_meta, f, sort_keys=True)
    os.replace(meta_tmp, os.path.join(out_dir, _CATALOG_META))
    return out_meta


def catalog_paths(out_dir: str) -> Dict[str, str]:
    return {
        "catalog": os.path.join(out_dir, _CATALOG),
        "meta": os.path.join(out_dir, _CATALOG_META),
        "plan": os.path.join(out_dir, _PLAN),
    }
