"""Repick catalog: deterministic work units + segment-committed output.

The batch-inference engine (seist_tpu/batch/engine.py) is a map-reduce
over a packed archive (data/packed.py). This module owns the MAP side's
addressing and the REDUCE side's merge — the plan-first / sidecar-commit
pattern PR 14 built for packing, applied to OUTPUTS:

* **Work unit** = one packed shard's index rows ``[row_lo, row_hi)`` in
  pack order. :func:`plan_units` is a pure function of the archive's
  index — never of worker count or of what output already exists — so
  any worker layout produces the identical unit list.
* **Segment** = ``commit_every`` consecutive device calls of one unit
  (a call is ``batches_per_call x batch_size`` rows). Each segment's
  catalog rows are written to ``unit_XXXXX.seg_XXXX.jsonl`` via
  tmp+rename: the rename is the commit point, so a SIGKILL at any
  instant leaves either a complete segment or a resumable hole, and
  :func:`first_missing_segment` restarts a worker at its exact offset.
* **Plan identity** — ``repick_plan.json`` records everything that
  determines segment boundaries and row content (batch geometry, model,
  variant, thresholds). Workers refuse to resume into an output
  directory whose plan differs (same rule as the packer's sidecar plan
  identity: a geometry change must restart, never silently mix).
* **Merge** — segments concatenated in (unit, segment) order into
  ``catalog.jsonl``; ``catalog_meta.json`` is written LAST (a directory
  without it is an incomplete catalog). Because every row is a pure
  function of (archive, plan), the merged catalog is byte-identical
  across worker counts and across kill/resume histories — ``make
  repick-smoke`` pins this.

Rows are compact JSON objects, one per waveform, sorted keys (see
ops/results.catalog_rows and docs/DATA.md "Batch re-picking").
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_PLAN = "repick_plan.json"
_CATALOG = "catalog.jsonl"
_CATALOG_META = "catalog_meta.json"
_SEG_RE = re.compile(r"^unit_(\d{5})\.seg_(\d{4})\.jsonl$")


@dataclasses.dataclass(frozen=True)
class WorkUnit:
    """One packed shard's rows ``[row_lo, row_hi)`` (pack index order)."""

    unit_id: int  # == packed shard id
    row_lo: int
    row_hi: int

    @property
    def n(self) -> int:
        return self.row_hi - self.row_lo


def plan_units(shards_col: np.ndarray) -> List[WorkUnit]:
    """The deterministic unit partition from the archive index's
    ``shard`` column (rows of one shard are contiguous in pack order —
    the index is merged sidecar-by-sidecar)."""
    shards_col = np.asarray(shards_col, np.int64)
    if shards_col.size == 0:
        return []
    if (np.diff(shards_col) < 0).any():
        raise ValueError(
            "archive index 'shard' column is not in pack order; refusing "
            "to plan work units over a reordered index"
        )
    units: List[WorkUnit] = []
    ids, starts = np.unique(shards_col, return_index=True)
    bounds = list(starts) + [shards_col.size]
    for i, uid in enumerate(ids):
        units.append(WorkUnit(int(uid), int(bounds[i]), int(bounds[i + 1])))
    return units


# ------------------------------------------------------------- segment math
def calls_per_unit(unit: WorkUnit, rows_per_call: int) -> int:
    return -(-unit.n // rows_per_call)


def segments_per_unit(
    unit: WorkUnit, rows_per_call: int, commit_every: int
) -> int:
    return -(-calls_per_unit(unit, rows_per_call) // commit_every)


def segment_path(out_dir: str, unit_id: int, seg: int) -> str:
    return os.path.join(out_dir, f"unit_{unit_id:05d}.seg_{seg:04d}.jsonl")


def commit_segment(
    out_dir: str, unit_id: int, seg: int, lines: Sequence[str]
) -> str:
    """Atomically commit one segment's catalog rows (tmp+rename; the pid
    suffix keeps two workers erroneously owning the same unit from
    corrupting each other's tmp — last rename wins with identical
    content, since rows are a pure function of the plan)."""
    path = segment_path(out_dir, unit_id, seg)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("".join(lines))
    os.replace(tmp, path)
    return path


def first_missing_segment(
    out_dir: str, unit: WorkUnit, rows_per_call: int, commit_every: int
) -> int:
    """Resume point: the first segment of ``unit`` with no committed
    file. Returns ``segments_per_unit`` when the unit is complete.
    Committed files are trusted (the rename only ever publishes whole
    segments); holes after a committed segment are repacked from the
    hole on — later segments are redundant work at worst, never wrong
    (their content is deterministic)."""
    total = segments_per_unit(unit, rows_per_call, commit_every)
    for seg in range(total):
        if not os.path.exists(segment_path(out_dir, unit.unit_id, seg)):
            return seg
    return total


# --------------------------------------------------------------- plan file
def write_or_check_plan(out_dir: str, plan: Dict[str, Any]) -> None:
    """Create ``repick_plan.json`` (atomic) or validate the existing one
    matches — the resume geometry guard. Two workers racing the create
    write identical bytes, so either rename is correct."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, _PLAN)
    blob = json.dumps(plan, sort_keys=True)
    if os.path.exists(path):
        with open(path) as f:
            existing = f.read()
        if existing != blob:
            raise ValueError(
                f"output dir {out_dir} holds a catalog built under a "
                "different plan (batch geometry / model / variant / "
                "thresholds changed); resume would mix incompatible "
                "segments — use a fresh --out or delete the directory"
            )
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(blob)
    os.replace(tmp, path)


def read_plan(out_dir: str) -> Dict[str, Any]:
    with open(os.path.join(out_dir, _PLAN)) as f:
        return json.load(f)


# ------------------------------------------------------------------- merge
def merge_catalog(
    out_dir: str,
    units: Sequence[WorkUnit],
    rows_per_call: int,
    commit_every: int,
    *,
    meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Reduce step: concatenate every unit's segments in (unit, segment)
    order into ``catalog.jsonl`` (tmp+rename), then commit
    ``catalog_meta.json`` LAST. Refuses loudly while any segment is
    missing (a partial merge would look complete)."""
    missing: List[str] = []
    for unit in units:
        total = segments_per_unit(unit, rows_per_call, commit_every)
        for seg in range(total):
            if not os.path.exists(segment_path(out_dir, unit.unit_id, seg)):
                missing.append(f"unit {unit.unit_id} seg {seg}")
    if missing:
        raise FileNotFoundError(
            f"catalog merge: {len(missing)} segment(s) not committed yet "
            f"(first: {missing[0]}) — finish or resume the workers first"
        )
    cat_path = os.path.join(out_dir, _CATALOG)
    tmp = f"{cat_path}.tmp.{os.getpid()}"
    n_rows = 0
    with open(tmp, "w") as f:
        for unit in units:
            total = segments_per_unit(unit, rows_per_call, commit_every)
            for seg in range(total):
                with open(
                    segment_path(out_dir, unit.unit_id, seg)
                ) as seg_f:
                    for line in seg_f:
                        f.write(line)
                        n_rows += 1
    os.replace(tmp, cat_path)
    out_meta = dict(meta or {})
    out_meta.update({
        "n_rows": n_rows,
        "n_units": len(units),
        "catalog": _CATALOG,
    })
    meta_tmp = os.path.join(out_dir, _CATALOG_META + f".tmp.{os.getpid()}")
    with open(meta_tmp, "w") as f:
        json.dump(out_meta, f, sort_keys=True)
    os.replace(meta_tmp, os.path.join(out_dir, _CATALOG_META))
    return out_meta


def catalog_paths(out_dir: str) -> Dict[str, str]:
    return {
        "catalog": os.path.join(out_dir, _CATALOG),
        "meta": os.path.join(out_dir, _CATALOG_META),
        "plan": os.path.join(out_dir, _PLAN),
    }
