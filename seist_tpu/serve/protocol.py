"""Wire protocol for the serve subsystem: request parsing, response
shaping, and the error taxonomy the HTTP front-end maps to status codes.

Everything is plain JSON over stdlib types — no new dependencies. A
predict request body is::

    {"model": "seist_s_dpk",              # optional when one model loaded
     "data": [[...], ...],                # (C, L) or (L, C) floats
     "options": {"ppk_threshold": 0.3, "spk_threshold": 0.3,
                 "det_threshold": 0.5, "min_peak_dist": 1.0,
                 "sampling_rate": 50, "norm_mode": "std",
                 "timeout_ms": 2000}}

Multi-task fan-out (``model`` names a task GROUP served with
``--model-group``, e.g. ``seist_s``)::

    {"model": "seist_s", "tasks": ["dpk", "emg", "dis"],  # default: all
     "data": [[...], ...],
     "options": {"variant": "bf16"}}      # fp32 (default) | bf16 | int8

and the response carries one entry per requested head::

    {"model": "seist_s", "trunk_runs": 1,
     "tasks": {"dpk": {...picks...}, "emg": {...}, "dis": {...}}}

The single-task request/response shape above is unchanged (PR 1 wire
compatibility); ``tasks`` on a single-task model is a 400.

``data`` orientation is resolved against the model's channel count (the
same (C, L)/(L, C) tolerance as tools/predict.py); windows shorter than
the model's compiled window are right-padded with zeros AFTER
normalization (so padding never shifts the z-score), longer ones are
rejected toward ``POST /annotate`` which exists precisely for long
records.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np


class ServeError(Exception):
    """Base service error; ``status`` is the HTTP status it maps to."""

    status = 500
    code = "internal"

    def payload(self) -> Dict[str, Any]:
        return {"error": self.code, "message": str(self)}

    def headers(self) -> Dict[str, str]:
        """Extra HTTP response headers (e.g. Retry-After for shedding)."""
        return {}


class BadRequest(ServeError):
    status = 400
    code = "bad_request"


class UnknownModel(ServeError):
    status = 404
    code = "unknown_model"


class QueueFull(ServeError):
    """Bounded-queue backpressure — the 429 the ISSUE's '429-style
    rejection' refers to. Clients should retry with backoff."""

    status = 429
    code = "queue_full"


class DeadlineExceeded(ServeError):
    status = 504
    code = "deadline_exceeded"


class ShuttingDown(ServeError):
    """The replica is draining (SIGTERM latch) or its stream mux has
    been closed (``MuxClosed``) — nothing here is wrong with the
    request. 503 is deliberate: the router treats it as retryable, so
    an in-flight ``/stream`` packet re-routes to a surviving replica,
    which restores the station's session from its journal (or
    gap-stitches a fresh one). The failover handoff IS this status
    code."""

    status = 503
    code = "shutting_down"


class IncompatibleCheckpoint(ServeError):
    """The checkpoint's param tree does not fit the target model config
    (missing/extra keys, shape or dtype mismatch). Raised by the loader
    BEFORE any swap/serving, naming the first mismatching path — without
    this, a wrong-architecture checkpoint surfaces as a deep flax apply
    traceback mid-request."""

    status = 400
    code = "incompatible_checkpoint"


class ReloadFailed(ServeError):
    """A hot reload (``POST /admin/reload``) was rejected or died before
    the atomic swap: the incumbent entry keeps serving, unchanged. 409:
    the request was well-formed, the candidate just didn't earn the
    traffic (the "disable, don't serve wrong" contract applied to
    reload)."""

    status = 409
    code = "reload_failed"


class ParityGateFailed(ReloadFailed):
    """A reload candidate failed the load-time acceptance gates (variant
    parity vs fp32, or the fp32 finite-output probe). Same 409 contract
    as :class:`ReloadFailed` with the gate verdict in the message."""

    code = "parity_gate_failed"


#: Priority tiers, highest first. Order IS the shed order reversed:
#: ``batch`` (backfill) is dropped first under overload, ``alert``
#: (streaming early-warning picks — a missed one is a missed event) last.
#: The numeric level is what serve/shed.py compares thresholds against.
PRIORITIES = {"alert": 0, "interactive": 1, "batch": 2}
DEFAULT_PRIORITY = "interactive"

#: Serving weight variants (serve/aot.py builds + parity-gates them):
#: fp32 = the checkpoint as restored; bf16 = weights+activations cast;
#: int8 = weight-only quantization. Selected per request via
#: ``options.variant``; a variant a model/task wasn't loaded (or failed
#: its parity gate) for is a 400.
VARIANTS = ("fp32", "bf16", "int8")
DEFAULT_VARIANT = "fp32"


class Overloaded(ServeError):
    """Adaptive load shedding (serve/shed.py): the replica's queue delay
    says this request's tier cannot be served within its latency budget.
    Distinct from QueueFull's 429 (a hard bounded-queue bounce) — this is
    a *policy* drop of a low tier, delivered as 503 + Retry-After so
    well-behaved batch clients back off for a computed interval while
    alert traffic keeps flowing."""

    status = 503
    code = "shed"

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        # No floor here: the shed policy (ShedConfig.min_retry_after_s)
        # owns the minimum — clamping again would silently override a
        # sub-second operator setting. Only guard against negatives.
        self.retry_after_s = max(0.0, float(retry_after_s))

    def payload(self) -> Dict[str, Any]:
        p = super().payload()
        p["retry_after_s"] = round(self.retry_after_s, 1)
        return p

    def headers(self) -> Dict[str, str]:
        # Retry-After is delta-seconds, integral per RFC 9110.
        return {"Retry-After": str(int(math.ceil(self.retry_after_s)))}


@dataclass
class PredictOptions:
    """Per-request knobs; defaults mirror cli.py's eval flags."""

    ppk_threshold: float = 0.3
    spk_threshold: float = 0.3
    det_threshold: float = 0.5
    min_peak_dist: float = 1.0  # seconds
    sampling_rate: int = 50
    norm_mode: str = "std"
    max_events: int = 8
    timeout_ms: float = 5000.0
    priority: str = DEFAULT_PRIORITY  # admission tier (serve/shed.py)
    variant: str = DEFAULT_VARIANT  # weight variant (serve/aot.py)
    # /annotate only:
    stride: int = 0  # 0 = window // 2
    combine: str = "max"
    record_max_events: int = 0  # 0 = scale with record length

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "PredictOptions":
        d = dict(d or {})
        unknown = set(d) - set(cls.__dataclass_fields__)
        if unknown:
            raise BadRequest(f"unknown options: {sorted(unknown)}")
        int_fields = ("sampling_rate", "max_events", "stride",
                      "record_max_events")
        for key, value in d.items():
            if key in ("norm_mode", "combine", "priority", "variant"):
                if not isinstance(value, str):
                    raise BadRequest(f"option '{key}' must be a string")
                continue
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                # bool is an int subclass; a JSON true/false here is a
                # client bug, not a number.
                raise BadRequest(
                    f"option '{key}' must be a number, "
                    f"got {type(value).__name__}"
                )
            if not math.isfinite(value):
                # json.loads accepts NaN/Infinity; NaN would sail through
                # every range check below (all comparisons are False).
                raise BadRequest(f"option '{key}' must be finite")
            if key in int_fields:
                if float(value) != int(value):
                    raise BadRequest(
                        f"option '{key}' must be an integer, got {value}"
                    )
                d[key] = int(value)
        try:
            opts = cls(**d)
        except (TypeError, ValueError) as e:
            raise BadRequest(f"bad options: {e}") from None
        # Range checks: a negative timeout_ms would otherwise turn
        # lock.acquire()/Event.wait() timeouts into unbounded waits or
        # ValueErrors deep in the service (500s instead of 400s).
        if opts.timeout_ms <= 0:
            raise BadRequest(f"timeout_ms must be > 0, got {opts.timeout_ms}")
        if opts.sampling_rate <= 0:
            raise BadRequest(
                f"sampling_rate must be > 0, got {opts.sampling_rate}"
            )
        if opts.min_peak_dist < 0:
            raise BadRequest(
                f"min_peak_dist must be >= 0, got {opts.min_peak_dist}"
            )
        if opts.max_events < 1:
            raise BadRequest(f"max_events must be >= 1, got {opts.max_events}")
        if opts.stride < 0 or opts.record_max_events < 0:
            raise BadRequest("stride and record_max_events must be >= 0")
        if opts.combine not in ("max", "mean"):
            raise BadRequest(
                f"combine must be 'max' or 'mean', got '{opts.combine}'"
            )
        if opts.priority not in PRIORITIES:
            raise BadRequest(
                f"priority must be one of {sorted(PRIORITIES)}, "
                f"got '{opts.priority}'"
            )
        if opts.variant not in VARIANTS:
            raise BadRequest(
                f"variant must be one of {list(VARIANTS)}, "
                f"got '{opts.variant}'"
            )
        return opts


def parse_tasks(obj: Any) -> Optional[Tuple[str, ...]]:
    """Validate a request's ``tasks`` field: a non-empty list of unique
    task-name strings (which tasks EXIST is the pool entry's call —
    ``resolve_tasks``); ``None`` passes through (single-task request /
    group default = all its tasks)."""
    if obj is None:
        return None
    if not isinstance(obj, (list, tuple)) or not obj:
        raise BadRequest(
            "'tasks' must be a non-empty list of task names, "
            f"got {type(obj).__name__}"
        )
    out = []
    for t in obj:
        if not isinstance(t, str):
            raise BadRequest(
                f"'tasks' entries must be strings, got {type(t).__name__}"
            )
        if t in out:
            raise BadRequest(f"duplicate task '{t}' in 'tasks'")
        out.append(t)
    return tuple(out)


def parse_body(raw: bytes) -> Dict[str, Any]:
    try:
        body = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise BadRequest(f"body is not valid JSON: {e}") from None
    if not isinstance(body, dict):
        raise BadRequest(f"body must be a JSON object, got {type(body).__name__}")
    return body


def parse_waveform(obj: Any, in_channels: int) -> np.ndarray:
    """JSON nested lists -> (L, C) float32, resolving (C, L) vs (L, C) by
    the model's channel count (ambiguous square inputs read as (L, C))."""
    try:
        arr = np.asarray(obj, dtype=np.float32)
    except (ValueError, TypeError) as e:
        raise BadRequest(f"'data' is not a numeric array: {e}") from None
    if arr.ndim != 2:
        raise BadRequest(f"'data' must be 2-D, got shape {arr.shape}")
    if arr.shape[1] == in_channels:
        pass  # already (L, C)
    elif arr.shape[0] == in_channels:
        arr = arr.T
    else:
        raise BadRequest(
            f"'data' shape {arr.shape} has no axis of {in_channels} channels"
        )
    if not np.all(np.isfinite(arr)):
        raise BadRequest("'data' contains non-finite values")
    return arr


_STATION_FIELDS = {"id", "network", "lat", "lon"}


def parse_station(obj: Any, required: bool = False) -> Optional[Dict[str, Any]]:
    """Validate a request's ``station`` metadata block: ``{"id": str,
    "network": str?, "lat": float?, "lon": float?}``. ``id`` is
    mandatory inside the block; ``lat``/``lon`` must come together (a
    lone coordinate cannot place a station, and the associator needs
    both or neither). Returns a normalized dict, or None when the block
    is absent and not required."""
    if obj is None:
        if required:
            raise BadRequest("'station' metadata is required: {'id': ...}")
        return None
    if not isinstance(obj, dict):
        raise BadRequest(
            f"'station' must be an object, got {type(obj).__name__}"
        )
    unknown = set(obj) - _STATION_FIELDS
    if unknown:
        raise BadRequest(f"unknown station fields: {sorted(unknown)}")
    sid = obj.get("id")
    if not isinstance(sid, str) or not sid:
        raise BadRequest("'station.id' must be a non-empty string")
    if len(sid) > 64:
        # Journal filenames slug the id (stream/journal.py) and router
        # affinity hashes it; a bounded id keeps slugs collision-free
        # and is far beyond any real SEED/FDSN station code.
        raise BadRequest("'station.id' must be <= 64 characters")
    out: Dict[str, Any] = {"id": sid, "network": ""}
    net = obj.get("network")
    if net is not None:
        if not isinstance(net, str):
            raise BadRequest("'station.network' must be a string")
        out["network"] = net
    lat, lon = obj.get("lat"), obj.get("lon")
    if (lat is None) != (lon is None):
        raise BadRequest("'station.lat' and 'station.lon' must come together")
    if lat is not None:
        for key, val in (("lat", lat), ("lon", lon)):
            if isinstance(val, bool) or not isinstance(val, (int, float)) \
                    or not math.isfinite(val):
                raise BadRequest(f"'station.{key}' must be a finite number")
        if not -90.0 <= float(lat) <= 90.0:
            raise BadRequest("'station.lat' out of range [-90, 90]")
        if not -180.0 <= float(lon) <= 360.0:
            raise BadRequest("'station.lon' out of range [-180, 360]")
        out["lat"], out["lon"] = float(lat), float(lon)
    return out


def json_bytes(payload: Dict[str, Any]) -> bytes:
    return json.dumps(payload, default=_jsonable).encode("utf-8")


def _jsonable(x: Any):
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    raise TypeError(f"not JSON-serializable: {type(x)}")
