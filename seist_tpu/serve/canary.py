"""Canary rollout + shadow mode for the front-tier router — the
traffic-shifting half of the live-model flywheel (docs/SERVING.md "Live
rollout"; the replica-side half is serve/pool.ModelPool.reload).

A new model version never takes the fleet by fiat (the t5x operational
model, arXiv:2203.17189): it earns traffic incrementally —

* **Canary** (:class:`CanaryController`): ``k%`` of first attempts route
  to the replicas serving the CANDIDATE version (discovered from each
  replica's ``/healthz/ready`` ``versions`` payload by the router's
  prober); everything else — including every retry — stays on the
  incumbent cohort, so a sick candidate can make a request slower, never
  make it fail. The controller compares the two cohorts' error rates and
  latency EWMAs online; a candidate whose delta exceeds the budget is
  **auto-rolled-back** — drained to 0% instantly, the verdict kept in
  ``status()``, counted on the bus (``router_canary_rollback``) and
  flagged on the triggering request's trace (``canary_rollback``, tail-
  retained).
* **Shadow** (:class:`ShadowMirror`): a deterministic sample of /predict
  requests is MIRRORED to the candidate cohort after the incumbent
  answered (the client only ever sees the incumbent's response); the two
  decoded responses are diffed at DECISION level (:func:`decision_diff` —
  the PR 10 parity-gate comparisons applied online to the wire format:
  pick positions, argmax classes, scaled regression values) and every
  verdict appended to a JSONL report. Shadow is how a candidate earns
  its first percent: disagreement shows up in the report before any
  client ever saw the new weights.

Stdlib only — this module runs in the router/supervisor process, which
never imports jax (serve/router.py's front-tier contract).
"""

from __future__ import annotations

import json
import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional, Tuple

from seist_tpu.utils.logger import logger

#: Decision-level tolerances for online response diffs — the wire-format
#: analog of serve/aot._PARITY_TOL's "decision, not bits" philosophy.
PICK_TOL_SAMPLES = 10  # a pick moved further than this is a decision flip
VALUE_REL_TOL = 0.05  # regression values compare relative to magnitude
VALUE_ABS_TOL = 0.05  # ...with an absolute floor near zero


def serves_version(
    versions: Optional[Mapping[str, Any]],
    version: int,
    model: Optional[str] = None,
) -> bool:
    """Does a replica's probed ``{model: version}`` map serve
    ``version`` — for ``model`` when scoped, for any model otherwise?
    The ONE cohort-membership test behind canary routing, shadow
    targeting and the router's pick predicate."""
    if not versions:
        return False
    try:
        if model:
            served = versions.get(model)
            return served is not None and int(served) == int(version)
        return any(int(v) == int(version) for v in versions.values())
    except (TypeError, ValueError, AttributeError):
        return False


@dataclass(frozen=True)
class CanaryBudget:
    """Auto-rollback budget: how much worse the candidate cohort may run
    before it is drained. Deltas are candidate-minus-incumbent, so a
    fleet-wide slowdown (overload, noisy box) does not scapegoat the
    canary."""

    #: rollback when cand_error_rate - incumbent_error_rate exceeds this
    max_error_delta: float = 0.10
    #: rollback when the candidate's latency EWMA exceeds the
    #: incumbent's by more than this (ms); inf = latency never trips
    max_latency_delta_ms: float = float("inf")
    #: candidate requests observed before any verdict (small-sample
    #: noise must not kill a healthy canary)
    min_requests: int = 20


@dataclass
class _CohortStats:
    requests: int = 0
    errors: int = 0
    latency_ewma_ms: float = 0.0

    def observe(self, error: bool, latency_ms: Optional[float]) -> None:
        self.requests += 1
        if error:
            self.errors += 1
        if latency_ms is not None:
            self.latency_ewma_ms = (
                latency_ms
                if self.latency_ewma_ms == 0.0
                else 0.8 * self.latency_ewma_ms + 0.2 * latency_ms
            )

    @property
    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "requests": self.requests,
            "errors": self.errors,
            "error_rate": round(self.error_rate, 4),
            "latency_ewma_ms": round(self.latency_ewma_ms, 3),
        }


class CanaryController:
    """Weighted version-aware routing + cohort-delta auto-rollback.

    States: ``inactive`` (no canary; routing untouched) -> ``active``
    (``percent``% of first attempts go candidate) -> ``rolled_back``
    (candidate drained to 0%; incumbent serves 100% until an operator
    clears or restarts the canary). Thread-safe: the router's handler
    threads route and observe concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = "inactive"
        self.version: Optional[int] = None
        self.model: Optional[str] = None
        self.percent = 0.0
        self.budget = CanaryBudget()
        self._n = 0  # weighted round-robin counter
        self._cohorts = {
            "candidate": _CohortStats(), "incumbent": _CohortStats()
        }
        self._rollback_reason = ""

    # ------------------------------------------------------------- control
    def start(
        self,
        version: int,
        percent: float,
        budget: Optional[CanaryBudget] = None,
        model: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Start (or re-weight) a canary for ``version`` at ``percent``%
        of first attempts. Restarting resets the cohort stats — a new
        observation window, not a continuation of a rolled-back one.

        ``model`` scopes the cohort test to ONE entry of a multi-model
        pool: without it a bare version number would match any model's
        version in the replicas' ``versions`` maps, and a fleet whose
        model A already runs at version 5 could never canary model B's
        version 5 (the incumbent cohort would be empty and the healthy
        canary would be rolled back on phantom deltas)."""
        version = int(version)
        percent = float(percent)
        if not (0.0 < percent <= 100.0):
            raise ValueError(
                f"percent must be in (0, 100], got {percent} "
                "(use stop() / percent=0 to clear)"
            )
        if not math.isfinite(percent):
            raise ValueError("percent must be finite")
        with self._lock:
            self._state = "active"
            self.version = version
            self.model = model or None
            self.percent = percent
            self.budget = budget or CanaryBudget()
            self._n = 0
            self._cohorts = {
                "candidate": _CohortStats(), "incumbent": _CohortStats()
            }
            self._rollback_reason = ""
        logger.info(
            f"[router] canary started: "
            + (f"model {model} " if model else "")
            + f"version {version} at {percent:g}%"
        )
        return self.status()

    def stop(self) -> Dict[str, Any]:
        """Clear the canary entirely (back to version-blind routing)."""
        with self._lock:
            self._state = "inactive"
            self.version = None
            self.model = None
            self.percent = 0.0
            self._rollback_reason = ""
        return self.status()

    # ------------------------------------------------------------- routing
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def routing_cohort(self, first_attempt: bool) -> Optional[str]:
        """Which cohort this attempt must route to: ``None`` = no canary
        (version-blind pick). Retries NEVER go candidate — a failed
        candidate attempt retries on the incumbent, so canary failures
        cost latency, not availability. In ``rolled_back`` the candidate
        cohort gets exactly 0%."""
        with self._lock:
            if self._state == "inactive":
                return None
            if self._state == "rolled_back" or not first_attempt:
                return "incumbent"
            # Deterministic weighted round-robin: candidate exactly when
            # floor(n*p/100) increments — k% without RNG, test-exact.
            self._n += 1
            take = (self._n * self.percent) // 100.0 > (
                (self._n - 1) * self.percent
            ) // 100.0
            return "candidate" if take else "incumbent"

    def cohort_of(self, versions: Mapping[str, Any]) -> str:
        """Cohort of a replica given its served ``{model: version}``
        (from the prober): candidate iff it serves the canary version —
        for the canary's model when one was scoped, for any model
        otherwise (single-model fleets)."""
        with self._lock:
            version, model = self.version, self.model
        if version is None:
            return "incumbent"
        return (
            "candidate"
            if serves_version(versions, version, model)
            else "incumbent"
        )

    # ----------------------------------------------------------- verdicts
    def observe(
        self, cohort: str, error: bool, latency_ms: Optional[float] = None
    ) -> Optional[str]:
        """Record one settled attempt outcome for ``cohort`` and evaluate
        the rollback budget. Returns the rollback reason EXACTLY ONCE —
        on the observation that tripped it — so the caller can flag that
        request's trace and count the event without dedup bookkeeping."""
        with self._lock:
            if self._state != "active" or cohort not in self._cohorts:
                return None
            self._cohorts[cohort].observe(error, latency_ms)
            cand = self._cohorts["candidate"]
            inc = self._cohorts["incumbent"]
            if cand.requests < self.budget.min_requests:
                return None
            reason = ""
            err_delta = cand.error_rate - inc.error_rate
            if err_delta > self.budget.max_error_delta:
                reason = (
                    f"error-rate delta {err_delta:.3f} > budget "
                    f"{self.budget.max_error_delta:.3f} (candidate "
                    f"{cand.errors}/{cand.requests}, incumbent "
                    f"{inc.errors}/{inc.requests})"
                )
            elif (
                math.isfinite(self.budget.max_latency_delta_ms)
                and cand.latency_ewma_ms > 0.0
                and inc.latency_ewma_ms > 0.0
                and cand.latency_ewma_ms - inc.latency_ewma_ms
                > self.budget.max_latency_delta_ms
            ):
                reason = (
                    f"latency delta "
                    f"{cand.latency_ewma_ms - inc.latency_ewma_ms:.1f} ms "
                    f"> budget {self.budget.max_latency_delta_ms:.1f} ms "
                    f"(candidate EWMA {cand.latency_ewma_ms:.1f}, "
                    f"incumbent {inc.latency_ewma_ms:.1f})"
                )
            if not reason:
                return None
            self._state = "rolled_back"
            self.percent = 0.0
            self._rollback_reason = (
                f"version {self.version} rolled back: {reason}"
            )
            return self._rollback_reason

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "version": self.version,
                "model": self.model,
                "percent": self.percent,
                "budget": {
                    "max_error_delta": self.budget.max_error_delta,
                    "max_latency_delta_ms": self.budget.max_latency_delta_ms,
                    "min_requests": self.budget.min_requests,
                },
                "cohorts": {
                    k: v.snapshot() for k, v in self._cohorts.items()
                },
                "rollback_reason": self._rollback_reason,
            }


class ShadowMirror:
    """Mirror a sample of /predict traffic to the candidate cohort and
    diff the decisions offline — the client always gets the incumbent's
    answer. Mirrors are breaker-neutral by design (shadow is observation;
    a sick candidate must surface in the REPORT, not destabilize the
    routing state the incumbent depends on)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self.version: Optional[int] = None
        self.model: Optional[str] = None
        self.sample = 0.0
        self.report_path = ""
        self._counts = {
            "mirrored": 0, "match": 0, "mismatch": 0,
            "mirror_errors": 0, "no_candidate": 0, "skipped_busy": 0,
        }

    def start(
        self,
        version: int,
        sample: float,
        report_path: str = "",
        model: Optional[str] = None,
    ) -> Dict[str, Any]:
        sample = float(sample)
        if not (0.0 < sample <= 1.0):
            raise ValueError(
                f"sample must be in (0, 1], got {sample} "
                "(use stop() / sample=0 to clear)"
            )
        with self._lock:
            self._active = True
            self.version = int(version)
            self.model = model or None
            self.sample = sample
            self.report_path = report_path
            self._counts = {k: 0 for k in self._counts}
        logger.info(
            f"[router] shadow started: "
            + (f"model {model} " if model else "")
            + f"version {version} at {sample:.0%} sample"
            + (f" -> {report_path}" if report_path else "")
        )
        return self.status()

    def stop(self) -> Dict[str, Any]:
        with self._lock:
            self._active = False
            self.version = None
            self.model = None
            self.sample = 0.0
        return self.status()

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active

    def should_mirror(self, trace_id: str) -> bool:
        """Deterministic hash-of-trace-id sampling (the obs/trace
        tail-sampling idiom): every router instance mirrors the SAME
        subset, so a mirrored request's diff can be joined back to its
        primary trace."""
        with self._lock:
            if not self._active:
                return False
            sample = self.sample
        if sample >= 1.0:
            return True
        try:
            u = int(trace_id[:8], 16) / float(0xFFFFFFFF)
        except (ValueError, TypeError):
            return False
        return u < sample

    def record(
        self,
        trace_id: str,
        verdict: str,  # 'match' | 'mismatch' | 'mirror_errors' | 'no_candidate'
        detail: Optional[Dict[str, Any]] = None,
    ) -> None:
        with self._lock:
            if verdict in self._counts:
                self._counts[verdict] += 1
            if verdict in ("match", "mismatch"):
                self._counts["mirrored"] += 1
            path = self.report_path
        if path and detail is not None:
            line = json.dumps({
                "trace_id": trace_id, "verdict": verdict, **detail,
            })
            # Appends are O_APPEND-atomic for these line sizes; the lock
            # above only guards the counters.
            try:
                with open(path, "a") as f:
                    f.write(line + "\n")
            except OSError as e:
                logger.warning(f"[router] shadow report write failed: {e!r}")

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self._active,
                "version": self.version,
                "model": self.model,
                "sample": self.sample,
                "report_path": self.report_path,
                "counts": dict(self._counts),
            }


# ------------------------------------------------------------ decision diff
def _diff_picks(a: Any, b: Any, tol: int) -> Tuple[bool, str]:
    """Compare two decoded pick lists ([{'sample': ...}, ...])."""
    try:
        sa = [int(p.get("sample", p.get("onset", -1))) for p in (a or [])]
        sb = [int(p.get("sample", p.get("onset", -1))) for p in (b or [])]
    except (AttributeError, TypeError):
        # One side isn't a pick list at all — a decision mismatch, not a
        # mirror transport error.
        return False, "shape mismatch: unparseable pick list"
    if len(sa) != len(sb):
        return False, f"count {len(sa)} vs {len(sb)}"
    for x, y in zip(sa, sb):
        if abs(x - y) > tol:
            return False, f"pick moved {abs(x - y)} samples ({x} vs {y})"
    return True, f"{len(sa)} picks within {tol} samples"


def _diff_value(a: float, b: float) -> Tuple[bool, str]:
    tol = max(VALUE_ABS_TOL, VALUE_REL_TOL * abs(a))
    ok = abs(a - b) <= tol
    return ok, f"|{a:.4g} - {b:.4g}| {'<=' if ok else '>'} {tol:.4g}"


def _diff_result(
    a: Mapping[str, Any], b: Mapping[str, Any], tol: int
) -> Dict[str, Any]:
    """Decision-level diff of ONE task's decoded result dict (the
    /predict response shapes of docs/SERVING.md): pick positions for
    picking heads, argmax class for classifiers, tolerance-scaled values
    for regression heads. Version/bookkeeping fields are ignored — the
    whole point is that versions DIFFER."""
    fields: Dict[str, Any] = {}
    match = True
    skip = {"model", "model_version", "task", "trunk_runs", "variant",
            "windows", "record_samples"}
    for key in sorted(set(a) | set(b)):
        if key in skip:
            continue
        if key not in a or key not in b:
            fields[key] = {"match": False, "detail": "missing on one side"}
            match = False
            continue
        va, vb = a[key], b[key]
        if key in ("ppk", "spk", "det"):
            ok, detail = _diff_picks(va, vb, tol)
        elif isinstance(va, Mapping) and "class" in va:
            if isinstance(vb, Mapping):
                ok = va.get("class") == vb.get("class")
                detail = f"class {va.get('class')} vs {vb.get('class')}"
            else:
                # A head whose output SHAPE diverged between versions is
                # the strongest possible mismatch — it must report as
                # one, not crash the mirror thread into 'mirror_errors'.
                ok = False
                detail = f"shape mismatch: dict vs {type(vb).__name__}"
        elif isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            ok, detail = _diff_value(float(va), float(vb))
        else:
            ok, detail = va == vb, "direct compare"
        fields[key] = {"match": ok, "detail": detail}
        match = match and ok
    return {"match": match, "fields": fields}


def decision_diff(
    incumbent: Mapping[str, Any],
    candidate: Mapping[str, Any],
    pick_tol_samples: int = PICK_TOL_SAMPLES,
) -> Dict[str, Any]:
    """Diff two /predict response bodies at decision level — the shadow
    mode comparator. Handles both the single-task shape and the
    multi-task ``{"tasks": {task: result}}`` fan-out (recursing per
    task). Returns ``{"match": bool, ...detail...}``."""
    if "tasks" in incumbent or "tasks" in candidate:
        ta = incumbent.get("tasks") or {}
        tb = candidate.get("tasks") or {}
        tasks: Dict[str, Any] = {}
        match = True
        for t in sorted(set(ta) | set(tb)):
            if t not in ta or t not in tb:
                tasks[t] = {"match": False, "detail": "missing on one side"}
                match = False
                continue
            tasks[t] = _diff_result(ta[t], tb[t], pick_tol_samples)
            match = match and tasks[t]["match"]
        return {"match": match, "tasks": tasks}
    return _diff_result(incumbent, candidate, pick_tol_samples)
