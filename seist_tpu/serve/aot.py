"""AOT-compiled serving executables + quantized weight variants.

Live ``jax.jit`` compiles lazily on first call — which for a serving
replica means the first customer request after every relaunch pays a
multi-second XLA compile (the "compile storm" cold-start the ROADMAP
names). This module moves every request-path compile to replica LOAD
time using jax's ahead-of-time API (the ``tools/check_attn_tpu.py``
technique)::

    compiled = jax.jit(fn).lower(*shape_structs).compile()

``compiled`` is shape-specialized: calling it with matching (shape,
dtype) arguments executes the XLA program directly — no tracing, no
cache lookup through jit machinery, nothing that can compile on the
request path. The pool warm-up builds one executable per (warm bucket
shape x program) combination; after warm-up a ``CompileBudget`` window
over a request storm records zero traces (tests/test_multitask.py).

Programs per entry:

* single-task models: the full forward per bucket;
* SeisT task groups (serve/pool.py): the shared TRUNK per bucket plus
  each task HEAD per bucket — the fan-out path runs trunk once and
  dispatches the requested heads on its features.

Quantized variants (``options.variant``): each program is additionally
built per enabled variant —

* ``fp32`` — the checkpoint as restored (default, always on);
* ``bf16`` — params + activations cast to bfloat16, outputs cast back
  to float32 (half the HBM traffic; on TPU the MXU's native dtype);
* ``int8`` — weight-only quantization: >=2-D float params stored as
  int8 with a per-out-channel scale and dequantized on the fly inside
  the program (weights at rest are 4x smaller than fp32).

Variants are parity-GATED at load (:func:`variant_parity`): a variant
whose probe outputs diverge from fp32 beyond decision-level tolerance
(argmax flips for classifiers/pickers, scaled error for regression) is
disabled for that task rather than served wrong.

Compile cost is published as the ``serve_aot_compile_ms`` gauge (per
model, cumulative) plus a ``serve_aot_programs`` gauge on the obs bus.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

# ONE source of truth for the variant names: the wire contract
# (protocol.py is stdlib+numpy only, so this adds no import weight and
# the two layers cannot drift).
from seist_tpu.serve.protocol import VARIANTS  # noqa: F401  (re-export)

#: Decision-level parity tolerances per variant (see variant_parity).
#: bf16 rounds weights+activations to 8 mantissa bits (~4e-3 relative);
#: int8 weight-only is coarser. Probability outputs compare absolutely,
#: VALUE outputs relative to the head's output scale.
_PARITY_TOL = {
    "bf16": {"abs": 0.02, "rel": 0.01, "argmax_frac": 0.005},
    "int8": {"abs": 0.05, "rel": 0.02, "argmax_frac": 0.01},
}


@dataclass
class AotProgram:
    """One compiled executable + its load-time metadata."""

    key: str  # e.g. "seist_s/trunk/b4/bf16"
    compiled: Any  # jax.stages.Compiled
    compile_ms: float
    flops: float  # XLA cost_analysis FLOPs (0.0 when unavailable)

    def __call__(self, *args):
        return self.compiled(*args)


def compiled_flops(compiled: Any) -> float:
    """FLOPs from the executable's XLA cost analysis — the number the
    multi-task acceptance test sums (a 3-task fan-out must cost <= 0.5x
    three single-task calls). 0.0 when the backend doesn't report."""
    try:
        ca = compiled.cost_analysis()
        entry = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(entry.get("flops", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 - optional metadata, never fatal
        return 0.0


def aot_compile(
    key: str,
    fn: Callable[..., Any],
    arg_shapes: Sequence[Tuple[Tuple[int, ...], Any]],
    *,
    model: str = "",
) -> AotProgram:
    """lower+compile ``fn`` at the given (shape, dtype) signature.

    ``arg_shapes`` is a sequence of (shape tuple, dtype) pairs — one per
    positional argument. Publishes cumulative compile time on the
    ``serve_aot_compile_ms{model=}`` gauge."""
    import jax

    from seist_tpu.obs.bus import BUS

    structs = [
        jax.ShapeDtypeStruct(shape, dtype) for shape, dtype in arg_shapes
    ]
    t0 = time.monotonic()
    compiled = jax.jit(fn).lower(*structs).compile()
    ms = (time.monotonic() - t0) * 1e3
    BUS.gauge("serve_aot_compile_ms", model=model or key).inc(ms)
    BUS.gauge("serve_aot_programs", model=model or key).inc(1)
    return AotProgram(
        key=key, compiled=compiled, compile_ms=ms,
        flops=compiled_flops(compiled),
    )


def aot_compile_multi(
    key: str,
    fn: Callable[..., Any],
    arg_shapes: Sequence[Tuple[Tuple[int, ...], Any]],
    *,
    steps: int,
    model: str = "",
) -> AotProgram:
    """AOT-compile ``steps`` applications of ``fn`` as ONE executable:
    the compiled program takes arguments with a leading ``steps`` axis
    and ``lax.map``s ``fn`` over it. This is the serving-side analog of
    the train loop's ``steps_per_call`` scan — one host->device dispatch
    feeds ``steps`` full batches, keeping host Python (and its dispatch
    latency) off the device's critical path. The batch re-picking engine
    (seist_tpu/batch/engine.py) compiles its full-batch program buckets
    through this; ``arg_shapes`` are the PER-STEP shapes."""
    import jax

    def multi(*args):
        return jax.lax.map(lambda sliced: fn(*sliced), tuple(args))

    shapes = [
        ((steps,) + tuple(shape), dtype) for shape, dtype in arg_shapes
    ]
    return aot_compile(key, multi, shapes, model=model)


# ------------------------------------------------------------------ variants
def _is_float(leaf: Any) -> bool:
    dt = getattr(leaf, "dtype", None)
    if dt is None:
        return False
    import jax.numpy as jnp

    # jnp's lattice, not numpy's: bfloat16 (ml_dtypes) is NOT a subtype
    # of np.floating, and outputs_to_f32 must catch it.
    return bool(jnp.issubdtype(dt, jnp.floating))


def cast_variables(variables: Any, dtype: Any) -> Any:
    """Cast every floating leaf (params AND batch stats) to ``dtype``."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype) if _is_float(a) else a, variables
    )


_INT8_MARK = "__int8__"


def quantize_int8(variables: Any) -> Any:
    """Weight-only int8: every >=2-D floating leaf becomes
    ``{__int8__: q int8, scale f32}`` with a per-out-channel (last axis)
    symmetric scale; 1-D leaves (biases, norm scales, BN stats) stay
    fp32 — they are tiny and precision-critical."""
    import jax.numpy as jnp

    def pack(tree: Any) -> Any:
        if isinstance(tree, Mapping):
            return {k: pack(v) for k, v in tree.items()}
        if _is_float(tree) and getattr(tree, "ndim", 0) >= 2:
            axes = tuple(range(tree.ndim - 1))
            w = jnp.asarray(tree, jnp.float32)
            scale = jnp.maximum(jnp.max(jnp.abs(w), axis=axes), 1e-8) / 127.0
            q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
            return {_INT8_MARK: q, "scale": scale}
        return tree

    return pack(variables)


def dequantize(tree: Any) -> Any:
    """Inverse of :func:`quantize_int8`, run INSIDE the traced program so
    the executable's weights stay int8 in device memory and widen to
    fp32 only as they stream into the matmuls (weight-only quant)."""
    import jax.numpy as jnp

    if isinstance(tree, Mapping):
        if _INT8_MARK in tree:
            return tree[_INT8_MARK].astype(jnp.float32) * tree["scale"]
        return {k: dequantize(v) for k, v in tree.items()}
    return tree


def outputs_to_f32(out: Any) -> Any:
    """Cast every floating leaf of a program's outputs to float32 so
    decode paths are variant-blind (bf16 trunk features stay bf16 — this
    is for FINAL outputs only)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32) if _is_float(a) else a, out
    )


def variant_compute(
    apply_fn: Callable[[Any, Any], Any],
    variant: str,
    *,
    cast_outputs: bool = True,
) -> Callable[[Any, Any], Any]:
    """-> ``fn(variables, x)``: THE in-trace definition of a variant's
    compute convention, assuming ``variables`` already hold the
    variant's weights-at-rest (``cast_variables``/``quantize_int8`` for
    the runtime path; aval-level mirrors for tools/irlint's manifest —
    sharing this one builder is what keeps the audited program and the
    shipped executable from drifting apart):

    * ``fp32`` — plain apply;
    * ``bf16`` — input cast to bfloat16 and the whole trace run under
      ``precision_policy(bf16)`` so trace-time-dtype modules (make_norm,
      common.LSTM's carry) follow the variant — without the policy an
      fp32 LSTM carry silently promotes the recurrence (and everything
      downstream) back to fp32, forfeiting the bandwidth win (irlint
      f32-matmul-under-bf16-policy);
    * ``int8`` — weights dequantized INSIDE the trace (weight-only
      quant: int8 at rest, fp32 compute).

    ``cast_outputs=False`` for INTERIOR programs — a bf16 trunk hands
    bf16 features to bf16 heads; casting in between would forfeit the
    bandwidth win."""
    import jax.numpy as jnp

    out = outputs_to_f32 if cast_outputs else (lambda o: o)
    if variant == "fp32":
        return lambda v, x: apply_fn(v, x)
    if variant == "bf16":
        from seist_tpu.train.precision import precision_policy

        def bf16_fn(v, x):
            with precision_policy(jnp.bfloat16):
                return out(apply_fn(v, x.astype(jnp.bfloat16)))

        return bf16_fn
    if variant == "int8":
        return lambda v, x: out(apply_fn(dequantize(v), x))
    raise ValueError(f"unknown variant {variant!r} (use one of {VARIANTS})")


def head_variant_compute(model: Any, variant: str) -> Callable[..., Any]:
    """-> ``fn(variables, feats, x)``: the in-trace head-program variant
    convention of a task group (``models/seist.head_apply`` on the
    trunk's features), shared by serve/pool.py's fallbacks/warm-up and
    tools/irlint's manifest. bf16 heads consume the bf16 trunk features
    as-is and cast only the raw input; int8 heads run fp32 compute, so
    bf16-variant features widen at the boundary."""
    import jax.numpy as jnp

    from seist_tpu.models.seist import head_apply

    if variant == "fp32":
        return lambda v, feats, x: head_apply(model, v, feats, x)
    if variant == "bf16":
        from seist_tpu.train.precision import precision_policy

        def bf16_fn(v, feats, x):
            with precision_policy(jnp.bfloat16):
                return outputs_to_f32(
                    head_apply(model, v, feats, x.astype(jnp.bfloat16))
                )

        return bf16_fn
    if variant == "int8":
        return lambda v, feats, x: outputs_to_f32(
            head_apply(
                model, dequantize(v), feats.astype(jnp.float32), x
            )
        )
    raise ValueError(f"unknown variant {variant!r} (use one of {VARIANTS})")


def transform_variables(variables: Any, variant: str) -> Any:
    """The eager (load-time) weight transform matching
    :func:`variant_compute`'s conventions — the traced program holds
    bf16/int8 weights at rest, it does not re-derive them per call."""
    import jax.numpy as jnp

    if variant == "fp32":
        return variables
    if variant == "bf16":
        return cast_variables(variables, jnp.bfloat16)
    if variant == "int8":
        return quantize_int8(variables)
    raise ValueError(f"unknown variant {variant!r} (use one of {VARIANTS})")


def make_variant_apply(
    apply_fn: Callable[[Any, Any], Any],
    variables: Any,
    variant: str,
    *,
    cast_outputs: bool = True,
) -> Callable[[Any], Any]:
    """-> ``fn(x) -> outputs``: :func:`transform_variables` (eager, at
    load) closed over :func:`variant_compute` (the in-trace convention).

    ``apply_fn(variables, x)`` is the raw two-arg model apply."""
    compute = variant_compute(apply_fn, variant, cast_outputs=cast_outputs)
    transformed = transform_variables(variables, variant)
    return lambda x: compute(transformed, x)


# -------------------------------------------------------------- parity gate
def outputs_finite(out: Any) -> bool:
    """True iff every floating leaf of a program's outputs is finite —
    the reload gate's last rung: a checkpoint full of NaNs lowers,
    compiles and parity-gates against itself just fine, and must still
    never earn traffic (serve/pool.ModelPool.reload)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not bool(
            np.all(np.isfinite(arr))
        ):
            return False
    return True


def variant_parity(
    fp32_out: Any, variant_out: Any, variant: str, *, kind: str,
    scale: float = 1.0,
) -> Tuple[bool, float]:
    """Decision-level parity of a variant's probe outputs against fp32.

    ``kind``: ``'soft'`` (per-sample probability channels — pickers;
    compare absolutely AND require the post-argmax channel decision to
    match on all but a tiny near-tie fraction), ``'onehot'`` (classifier
    — argmax must be identical), ``'value'`` (regression — error
    relative to the head's output ``scale``). Returns (ok, err)."""
    tol = _PARITY_TOL[variant]
    a = np.asarray(fp32_out, np.float32)
    b = np.asarray(variant_out, np.float32)
    if kind == "onehot":
        ok = bool(np.array_equal(np.argmax(a, -1), np.argmax(b, -1)))
        return ok, float(np.max(np.abs(a - b)))
    if kind == "value":
        err = float(np.max(np.abs(a - b))) / max(scale, 1e-8)
        return err <= tol["rel"], err
    # soft: dense per-sample probabilities
    err = float(np.max(np.abs(a - b)))
    flips = float(np.mean(np.argmax(a, -1) != np.argmax(b, -1)))
    return err <= tol["abs"] and flips <= tol["argmax_frac"], err


def parity_kind(spec: Any) -> Tuple[str, float]:
    """Map a taskspec to the parity-gate comparison (kind, scale)."""
    from seist_tpu import taskspec

    names = [
        n for group in spec.labels
        for n in (group if isinstance(group, (tuple, list)) else [group])
    ]
    kinds = {
        taskspec.get_kind(n) for n in names if n in taskspec.IO_ITEMS
    }
    if kinds == {taskspec.VALUE}:
        return "value", 1.0
    if kinds == {taskspec.ONEHOT}:
        return "onehot", 1.0
    return "soft", 1.0
