"""Online inference service: micro-batching, bucketed warm compiles,
stdlib HTTP front-end, tiered load shedding, and a replica-fleet front
tier. See docs/SERVING.md.

    seist_tpu.serve.protocol   wire format + error taxonomy (HTTP statuses)
    seist_tpu.serve.batcher    request coalescing, backpressure, deadlines
    seist_tpu.serve.pool       model loading, shared-trunk task groups,
                               AOT warm-up, output decode
    seist_tpu.serve.aot        AOT-compiled executables + bf16/int8
                               quantized variants (parity-gated)
    seist_tpu.serve.shed       priority tiers + queue-delay load shedding
    seist_tpu.serve.server     ServeService core + HTTP shim + `serve` CLI
    seist_tpu.serve.router     front-tier router: health-checked replica
                               registry, circuit breaking, retries, hedging
    seist_tpu.serve.canary     live-rollout traffic shifting: canary with
                               auto-rollback + shadow-mode decision diffs
"""

from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher  # noqa: F401
from seist_tpu.serve.canary import (  # noqa: F401
    CanaryBudget,
    CanaryController,
    ShadowMirror,
    decision_diff,
)
from seist_tpu.serve.pool import ModelPool, load_model_entry  # noqa: F401
from seist_tpu.serve.protocol import PredictOptions, ServeError  # noqa: F401
from seist_tpu.serve.router import (  # noqa: F401
    CircuitBreaker,
    ReplicaRegistry,
    Router,
    RouterConfig,
)
from seist_tpu.serve.server import (  # noqa: F401
    ServeHTTPServer,
    ServeService,
    start_http_server,
)
from seist_tpu.serve.shed import AdmissionController, ShedConfig  # noqa: F401
