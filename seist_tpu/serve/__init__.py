"""Online inference service: micro-batching, bucketed warm compiles,
stdlib HTTP front-end. See docs/SERVING.md.

    seist_tpu.serve.protocol   wire format + error taxonomy (HTTP statuses)
    seist_tpu.serve.batcher    request coalescing, backpressure, deadlines
    seist_tpu.serve.pool       model loading + per-bucket warm-up + decode
    seist_tpu.serve.server     ServeService core + HTTP shim + `serve` CLI
"""

from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher  # noqa: F401
from seist_tpu.serve.pool import ModelPool, load_model_entry  # noqa: F401
from seist_tpu.serve.protocol import PredictOptions, ServeError  # noqa: F401
from seist_tpu.serve.server import (  # noqa: F401
    ServeHTTPServer,
    ServeService,
    start_http_server,
)
