"""Online inference service: micro-batched /predict, streaming /annotate,
health + metrics — stdlib HTTP only (http.server), no new dependencies.

Layering:

* :class:`ServeService` — transport-free core (also the in-process test
  client): model pool + one MicroBatcher per model + counters. Single
  fixed-window traces go through the batcher; long records go through
  ``ops/stream.annotate`` driving the SAME warm per-bucket forward
  (``jitted=True``, ``batch_size=largest bucket``), so the expensive
  model forward never compiles after warm-up. (The lightweight
  stitch/pick programs in /annotate still compile once per new record
  length — small, host-bound, and amortized across same-length records.)
* :class:`ServeHTTPServer` + handler — a thin JSON shim: ServeError
  subclasses carry their own HTTP status (429 queue-full backpressure,
  504 deadline, 503 draining, 400/404 client errors).

Endpoints::

    POST /predict       one (window, C) trace -> picks / regression / class
    POST /annotate      one (L >= window, C) record -> picks over the record
    POST /stream        one station packet into a long-lived StreamSession;
                        picks stream out as they become final, network
                        alerts ride along (docs/SERVING.md "Streaming
                        inference")
    POST /admin/reload  hot-swap a new checkpoint behind the full gate
                        ladder (docs/SERVING.md "Live rollout")
    GET  /healthz       liveness + model list + per-entry version/variants
    GET  /metrics       queue depth, batch-fill ratio, latency histograms
    GET  /stream/alerts recent cross-station association alerts + mux stats

CLI: ``python main.py serve --model seist_s_dpk=CKPT --port 8080 ...``
(see ``main()``); ``make serve-smoke`` runs the no-checkpoint smoke.
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from seist_tpu.obs import flight as obs_flight
from seist_tpu.obs import trace as obs_trace
from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher
from seist_tpu.serve.pool import ModelPool, decode_outputs
from seist_tpu.serve.protocol import (
    PRIORITIES,
    BadRequest,
    DeadlineExceeded,
    Overloaded,
    PredictOptions,
    QueueFull,
    ReloadFailed,
    ServeError,
    ShuttingDown,
    json_bytes,
    parse_body,
    parse_station,
    parse_tasks,
    parse_waveform,
)
from seist_tpu.serve.shed import AdmissionController, ShedConfig
from seist_tpu.utils.faults import ServeFaultInjector, stream_faults
from seist_tpu.utils.logger import logger
from seist_tpu.utils.meters import LatencyHistogram

MAX_BODY_BYTES = 64 * 1024 * 1024  # one hours-long fp32 record is ~tens of MB

_NORM_MODES = ("std", "max", "absmax", "")

# Clean-preempt exit code (sysexits EX_TEMPFAIL), shared with the train
# plane: a SIGTERM'd replica drains and exits 75, telling its supervisor
# (tools/supervise_fleet.py) "managed drain — relaunch immediately, budget
# untouched". Kept in sync with seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE
# by tests/test_serve_fleet.py (checkpoint.py drags orbax in; a serve
# replica should not pay that import).
PREEMPT_EXIT_CODE = 75

#: replica lifecycle as a scrapeable gauge (serve_state_code): the
#: warming -> ok -> draining state machine the router's health probes,
#: the flight recorder and events.jsonl all see identically.
STATE_CODES = {"dead": 0, "warming": 1, "ok": 2, "draining": 3}


class _BadCandidate(ServeError):
    """SEIST_FAULT_SERVE_BAD_CANDIDATE chaos verdict: this replica is
    deliberately serving a "bad" model version, so its /predict errors —
    the elevated-error-rate signal the router's canary auto-rollback
    must catch. 500: the router classifies it retryable + breaker
    failure, exactly like a genuine candidate regression."""

    status = 500
    code = "bad_candidate"


class ServeService:
    """Transport-free serving core; every public method raises ServeError
    subclasses on failure and returns JSON-able dicts on success."""

    def __init__(
        self,
        pool: ModelPool,
        batcher_config: Optional[BatcherConfig] = None,
        warmup_async: bool = False,
        shed_config: Optional[ShedConfig] = None,
        event_log: Optional[Any] = None,  # obs.EventLog
        faults: Optional[ServeFaultInjector] = None,
        stream_config: Optional[Dict[str, Any]] = None,
    ):
        self.pool = pool
        self.config = batcher_config or BatcherConfig()
        self.buckets = self.config.resolved_buckets()
        self.shed_config = shed_config or ShedConfig()
        self._event_log = event_log
        # Serving-plane fault injection (SEIST_FAULT_SERVE_*): inert
        # unless the env schedules a fault targeting this replica.
        self._faults = faults if faults is not None else (
            ServeFaultInjector.from_env()
        )
        # One batcher per (entry, enabled variant): requests batch by
        # TRUNK INPUT SHAPE within a variant (a bf16 program cannot serve
        # an fp32 request), task-blind — a group's dpk+emg+dis traffic
        # coalesces into the same flushes. The fp32 batcher keeps the
        # bare model name (wire/metrics back-compat); other variants are
        # keyed "<model>@<variant>". The forward closes over the entry
        # NAME, not the entry object: each flush resolves the entry from
        # the pool, so a hot reload (/admin/reload swapping the pool
        # slot) takes effect at the very next flush with no batcher
        # restart — the hot-swap seam.
        self._batchers: Dict[str, MicroBatcher] = {}
        self._shedders: Dict[str, AdmissionController] = {}
        self._reload_lock = threading.Lock()
        for name in pool.names():
            entry = pool.get(name)
            entry_batchers = []
            # getattr defaults keep bare-namespace test pools (see
            # watch_until_shutdown) and pre-variant entries working.
            for variant in getattr(entry, "variants", ("fp32",)):
                key = name if variant == "fp32" else f"{name}@{variant}"
                self._batchers[key] = MicroBatcher(
                    self._make_forward(name, variant), self.config, name=key
                )
                entry_batchers.append(self._batchers[key])
            # Tiered admission gate per model, fed by the worst
            # queue-delay estimate across its variant batchers
            # (serve/shed.py): overload on any variant sheds the entry.
            self._shedders[name] = AdmissionController(
                lambda _bs=tuple(entry_batchers): max(
                    b.queue_delay_ms() for b in _bs
                ),
                self.shed_config,
                model=name,
            )
        self._annotate_locks = {n: threading.Lock() for n in pool.names()}
        # /stream: one StationMux (sessions + associator) per picking
        # model, created lazily on the first stream request for that
        # model — see _stream_mux_for for the config-freeze contract.
        self._stream_config = dict(stream_config or {})
        self._stream_muxes: Dict[str, Any] = {}
        self._stream_lock = threading.Lock()
        # Streaming-plane fault injection (SEIST_FAULT_STREAM_*): the
        # module singleton so journal.py's corrupt hook and the /stream
        # kill share one stamp. Reorder faults hold a packet here until
        # the station's next one arrives (delivered late -> stale seq).
        self._stream_faults = stream_faults()
        self._held_packets: Dict[Any, Any] = {}
        self.annotate_latency_ms = LatencyHistogram()
        self._lock = threading.Lock()
        self._requests = {"predict": 0, "annotate": 0, "stream": 0}
        self._annotate_windows = 0
        # monotonic: _started_at only ever feeds uptime_s intervals, and a
        # wall-clock step must not make uptime jump (or go negative).
        self._started_at = time.monotonic()
        self._draining = False
        # Readiness gate: /healthz/ready reports 503 while the pool is
        # still pre-compiling (warmup_async=True lets the HTTP socket come
        # up first so orchestrators can probe during the compile) and
        # during SIGTERM drain. Requests arriving while warming are still
        # served — they just pay the compile — so readiness is advisory,
        # exactly what a load balancer wants.
        self._warming = True
        self._warmup_error: Optional[BaseException] = None
        self._last_state: Optional[str] = None
        # Metrics-bus collector (obs/bus.py): the request/annotate half
        # of metrics(); batchers self-register their own. One key per
        # service — a restarted service replaces its predecessor.
        from seist_tpu.obs.bus import BUS

        BUS.register_collector("serve", self._bus_metrics)
        self.publish_state("startup")
        if warmup_async:
            threading.Thread(
                target=self._run_warmup, name="serve-warmup", daemon=True
            ).start()
        else:
            self._run_warmup()
            if self._warmup_error is not None:
                raise self._warmup_error  # sync path keeps crashing loudly

    def _make_forward(self, name: str, variant: str):
        """Flush-time forward for one (entry, variant) batcher. Resolves
        the entry from the pool PER FLUSH (hot reload swaps the pool
        slot; in-flight flushes keep the entry they already grabbed) and
        dispatches by its capabilities."""
        injector = self._faults

        def batched_forward(batch, tasks=None, _n=name, _v=variant,
                            _inj=injector):
            entry = self.pool.get(_n)
            # Injected model slowness runs IN the flush thread, so
            # queued requests age exactly as behind a slow device.
            _inj.forward_delay()
            if getattr(entry, "is_group", False):
                return entry.fanout(batch, sorted(tasks or entry.tasks), _v)
            if hasattr(entry, "run"):
                return entry.run(batch, _v)
            # bare forward-only entry (test doubles)
            import jax.numpy as jnp

            return entry.forward(jnp.asarray(batch))

        return batched_forward

    def _run_warmup(self) -> None:
        try:
            self.pool.warmup(self.buckets)
            self._warming = False
            self.publish_state("warmup_done")
        except BaseException as e:  # noqa: BLE001
            # A failed warm-up (compile OOM, bad bucket, XLA error) must
            # never flip the service to ready: record it so liveness goes
            # false and the watchdog exits non-zero — the async
            # equivalent of the sync path's crash.
            self._warmup_error = e
            logger.warning(f"[serve] warm-up failed: {e!r}")
            self.publish_state("warmup_failed")

    # ------------------------------------------------------ lifecycle state
    def publish_state(self, reason: str = "") -> None:
        """Publish the replica lifecycle state machine (warming -> ok ->
        draining, or -> dead) everywhere an observer might look: a bus
        gauge (``serve_state_code``, scraped by Prometheus and the
        router's operators), a structured ``events.jsonl`` event, and the
        flight recorder ring when one is installed — one state machine,
        three views (docs/SERVING.md). Transition-edge-triggered: calling
        it redundantly is free."""
        state = self._state_str()
        with self._lock:
            if state == self._last_state:
                return
            prev, self._last_state = self._last_state, state
        from seist_tpu.obs import flight
        from seist_tpu.obs.bus import BUS

        BUS.gauge("serve_state_code").set(STATE_CODES.get(state, 0))
        if self._event_log is not None:
            self._event_log.emit(
                "serve_state", state=state, prev=prev, reason=reason
            )
        rec = flight.get()
        if rec is not None:
            rec.record_event("serve_state", state=state, prev=prev,
                             reason=reason)
        logger.info(
            f"[serve] state {prev or 'start'} -> {state}"
            + (f" ({reason})" if reason else "")
        )

    # ----------------------------------------------------------- predict
    def _batcher_for(self, name: str, variant: str) -> MicroBatcher:
        return self._batchers[
            name if variant == "fp32" else f"{name}@{variant}"
        ]

    def _check_variant(self, entry: Any, variant: str, tasks: Any) -> None:
        if variant == "fp32":
            return
        if variant not in getattr(entry, "variants", ("fp32",)):
            # Never loaded — no batcher, no programs: always a 400.
            raise BadRequest(
                f"variant '{variant}' is not loaded for model "
                f"'{entry.name}' (serve --variants); loaded: "
                f"{list(getattr(entry, 'variants', ('fp32',)))}"
            )
        if self._warming:
            # Parity gates are computed by the (async) warm-up; a loaded
            # variant must not bounce 400 during the warm-up window when
            # the documented pre-warm fallback can serve it — the same
            # contract fp32 traffic gets. Gate verdicts apply once warm.
            return
        supported = entry.supported_variants(tasks)
        if variant not in supported:
            raise BadRequest(
                f"variant '{variant}' is not served for this request "
                f"(model '{entry.name}'"
                + (f", tasks {list(tasks)}" if tasks else "")
                + f"); available: {supported} — variants are enabled at "
                "load (serve --variants) and parity-gated against fp32"
            )

    def predict(
        self,
        data: Any,
        model: Optional[str] = None,
        options: Optional[Dict[str, Any]] = None,
        tasks: Optional[Any] = None,
        station: Optional[Any] = None,
        trace: Optional[obs_trace.RequestTrace] = None,
    ) -> Dict[str, Any]:
        """One fixed-window trace through the micro-batcher.

        ``station`` (optional ``{"id", "network", "lat", "lon"}``):
        provenance metadata, validated and echoed back verbatim so a
        caller fanning one response out into a catalog keeps the trace's
        origin without a side channel (the same block /stream requires).

        ``tasks`` (multi-task groups only): which heads to answer with —
        the shared trunk runs ONCE and fans out to all of them
        (serve/pool.MultiTaskEntry); default is every task the group
        serves. Single-task models keep the PR 1 request/response shape
        byte-for-byte.

        ``trace`` (obs/trace.RequestTrace, minted by the HTTP handler
        from the request's ``traceparent``): every stage of this method
        becomes a child span — admission (with the shed verdict), parse,
        normalize, the batcher's queue wait + device forward, decode —
        so a slow request decomposes instead of being one opaque number."""
        if self._draining:
            raise ShuttingDown("service is draining")
        t = obs_trace.ensure(trace)
        entry = self.pool.get(model)
        version = int(getattr(entry, "version", 0) or 0)
        opts = PredictOptions.from_dict(options)
        req_tasks = entry.resolve_tasks(parse_tasks(tasks))
        station_meta = parse_station(station)
        self._check_variant(entry, opts.variant, req_tasks)
        t.annotate(model=entry.name, variant=opts.variant,
                   tier=opts.priority, version=version)
        if self._faults.is_bad_candidate(version):
            raise _BadCandidate(
                f"model '{entry.name}' version {version} is the injected "
                "bad candidate (SEIST_FAULT_SERVE_BAD_CANDIDATE)"
            )
        # Request arrival: count, fire any scheduled serving fault
        # (SIGKILL at request k / black-hole window), then the admission
        # gate — shedding happens BEFORE the expensive waveform parse, so
        # an overloaded replica spends no decode work on a request it is
        # about to drop.
        with self._lock:
            self._requests["predict"] += 1
            n_request = self._requests["predict"]
        self._faults.on_request(n_request)
        with t.span("admission", tier=opts.priority) as sp:
            try:
                self._shedders[entry.name].admit(opts.priority)
            except Overloaded as e:
                # The shed verdict rides the trace (and the tail
                # retention always keeps shed traces).
                sp.annotate(verdict="shed",
                            retry_after_s=round(e.retry_after_s, 3))
                t.flag("shed")
                raise
            sp.annotate(verdict="admitted")
        with t.span("parse"):
            x = parse_waveform(data, entry.in_channels)
        if x.shape[0] > entry.window:
            raise BadRequest(
                f"trace length {x.shape[0]} > window {entry.window}; "
                "use POST /annotate for long records"
            )
        with t.span("normalize"):
            x = _normalize_trace(x, opts.norm_mode)
            n_real = x.shape[0]
            if n_real < entry.window:  # pad AFTER normalize: zeros stay 0
                pad = np.zeros(
                    (entry.window - n_real, x.shape[1]), dtype=x.dtype
                )
                x = np.concatenate([x, pad], axis=0)
        raw = self._batcher_for(entry.name, opts.variant).submit(
            x,
            timeout_ms=opts.timeout_ms,
            rank=PRIORITIES[opts.priority],
            tasks=frozenset(req_tasks) if req_tasks is not None else None,
            trace=trace,
        )
        fs = float(opts.sampling_rate)
        if req_tasks is not None:  # multi-task group: one entry per head
            per_task: Dict[str, Any] = {}
            with t.span("decode", heads=",".join(req_tasks)):
                for tk in req_tasks:
                    # The flush may have computed the UNION of coalesced
                    # requests' tasks; decode only what THIS caller asked.
                    r = decode_outputs(entry.heads[tk], raw[tk], opts)
                    if n_real < entry.window:
                        _clip_picks(r, n_real, fs)
                    per_task[tk] = r
            out = {
                "model": entry.name,
                # Which checkpoint generation answered — the rollout
                # acceptance signal (bench_serve by_version accounting).
                "model_version": version,
                "tasks": per_task,
                # The fan-out contract, observable per response: all
                # heads above came from ONE trunk execution.
                "trunk_runs": 1,
                "variant": opts.variant,
            }
            if station_meta is not None:
                out["station"] = station_meta
            return out
        with t.span("decode"):
            result = decode_outputs(entry, raw, opts)
        if n_real < entry.window:
            # The signal->zeros step at the padding boundary can fabricate
            # picks/detections inside samples the client never sent.
            _clip_picks(result, n_real, fs)
        result["model"] = entry.name
        result["model_version"] = version
        if station_meta is not None:
            result["station"] = station_meta
        return result

    # ---------------------------------------------------------- annotate
    def annotate(
        self,
        data: Any,
        model: Optional[str] = None,
        options: Optional[Dict[str, Any]] = None,
        trace: Optional[obs_trace.RequestTrace] = None,
    ) -> Dict[str, Any]:
        """A long (L >= window) record via sliding windows + stitching,
        reusing the pool's warm largest-bucket forward."""
        if self._draining:
            raise ShuttingDown("service is draining")
        t = obs_trace.ensure(trace)
        entry = self.pool.get(model)
        if not entry.is_picker:
            raise BadRequest(
                f"model '{entry.name}' is not a picking model; /annotate "
                "needs (non|det, ppk, spk) outputs"
            )
        opts = PredictOptions.from_dict(options)
        if opts.variant != "fp32":
            # /annotate is hardwired to the fp32 picking path; silently
            # serving fp32 against an explicit bf16/int8 request would
            # misreport which numerics answered.
            raise BadRequest(
                "variant selection is /predict-only; /annotate always "
                "runs fp32"
            )
        # Same tiered gate as /predict: an overloaded replica sheds
        # low-tier record backfill before paying the (large) record parse.
        with t.span("admission", tier=opts.priority) as sp:
            try:
                self._shedders[entry.name].admit(opts.priority)
            except Overloaded as e:
                sp.annotate(verdict="shed",
                            retry_after_s=round(e.retry_after_s, 3))
                t.flag("shed")
                raise
            sp.annotate(verdict="admitted")
        with t.span("parse"):
            record = parse_waveform(data, entry.in_channels)
        if record.shape[0] < entry.window:
            raise BadRequest(
                f"record length {record.shape[0]} < window {entry.window}; "
                "use POST /predict for single windows"
            )
        from seist_tpu.ops.stream import annotate as stream_annotate

        t0 = time.monotonic()
        lock = self._annotate_locks[entry.name]
        # One record at a time per model: annotate saturates the device by
        # itself; interleaving two would only thrash. The wait counts
        # against the request's own deadline.
        if not lock.acquire(timeout=opts.timeout_ms / 1000.0):
            raise DeadlineExceeded(
                f"/annotate queue wait exceeded {opts.timeout_ms:.0f} ms"
            )
        # Groups stream through trunk+dpk (the group's picking path);
        # single-task pickers through their warm AOT forward. Both hit
        # shapes compiled at warm-up (batch_size = largest bucket).
        forward = (
            entry.picker_forward
            if entry.is_group
            else (lambda x: entry.run(x, "fp32"))
        )
        try:
            with self._lock:
                self._requests["annotate"] += 1
            with t.span("stream", model=entry.name,
                        record_samples=int(record.shape[0])):
                picks = stream_annotate(
                    forward,
                    record,
                    window=entry.window,
                    stride=opts.stride or None,
                    batch_size=self.buckets[-1],
                    sampling_rate=opts.sampling_rate,
                    ppk_threshold=opts.ppk_threshold,
                    spk_threshold=opts.spk_threshold,
                    det_threshold=opts.det_threshold,
                    min_peak_dist=opts.min_peak_dist,
                    combine=opts.combine,
                    max_events=opts.record_max_events or None,
                    channel0=entry.channel0,
                    jitted=True,
                )
        finally:
            lock.release()
        self.annotate_latency_ms.observe((time.monotonic() - t0) * 1000.0)
        fs = float(opts.sampling_rate)
        from seist_tpu.ops.stream import window_offsets

        n_windows = len(
            window_offsets(
                record.shape[0], entry.window, opts.stride or entry.window // 2
            )
        )
        with self._lock:
            self._annotate_windows += n_windows
        return {
            "model": entry.name,
            "model_version": int(getattr(entry, "version", 0) or 0),
            "task": "picking",
            "record_samples": int(record.shape[0]),
            "windows": int(n_windows),
            "ppk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["ppk"]
            ],
            "spk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["spk"]
            ],
            "det": [
                {"onset": int(a), "offset": int(b),
                 "onset_s": round(int(a) / fs, 6),
                 "offset_s": round(int(b) / fs, 6)}
                for a, b in picks["det"]
            ],
        }

    # ------------------------------------------------------------- stream
    def _stream_mux_for(self, entry: Any, opts: PredictOptions) -> Any:
        """Lazy per-model StationMux (seist_tpu/stream). The mux — and
        every session it will ever open — is configured from the FIRST
        stream request's options plus the server-level stream_config, and
        frozen: a model's streaming tenant is one coherent pick/stitch
        config shared by the whole network (per-request knobs belong to
        /predict and /annotate). Later requests' session options are
        ignored."""
        name = entry.name
        with self._stream_lock:
            mux = self._stream_muxes.get(name)
            if mux is None:
                from seist_tpu.stream.assoc import AssocConfig, Associator
                from seist_tpu.stream.mux import MuxConfig, StationMux
                from seist_tpu.stream.session import SessionConfig

                sc = self._stream_config
                session = SessionConfig(
                    window=entry.window,
                    stride=opts.stride or entry.window // 2,
                    in_channels=entry.in_channels,
                    channel0=entry.channel0,
                    combine=opts.combine,
                    sampling_rate=opts.sampling_rate,
                    ppk_threshold=opts.ppk_threshold,
                    spk_threshold=opts.spk_threshold,
                    det_threshold=opts.det_threshold,
                    min_peak_dist=opts.min_peak_dist,
                )
                # Durability plane (docs/FAULT_TOLERANCE.md "Streaming
                # faults"): a shared journal_dir turns this replica into
                # a crash-survivable stream home — sessions journal
                # every journal_every_s, the associator WALs each alert
                # before a consumer can see it, and a restart (or a
                # failover survivor pointed at the same dir) seeds its
                # dedup window from the WAL so nothing double-alerts.
                journal_dir = sc.get("journal_dir") or None
                journal = None
                wal = None
                if journal_dir:
                    from seist_tpu.obs.trace import replica_suffix
                    from seist_tpu.stream.journal import (
                        AlertWAL,
                        StationJournal,
                    )

                    journal = StationJournal(str(journal_dir), model=name)
                    # Per-replica WAL file (the journal dir is shared by
                    # the fleet; alerts are per-associator and must not
                    # interleave across writers).
                    wal = AlertWAL(os.path.join(
                        str(journal_dir), name,
                        f"alerts{replica_suffix()}.wal",
                    ))
                assoc = Associator(AssocConfig(
                    window_s=float(sc.get("assoc_window_s", 30.0)),
                    min_stations=int(sc.get("assoc_min_stations", 4)),
                    velocity_kms=float(sc.get("assoc_velocity_kms", 6.0)),
                    tolerance_s=float(sc.get("assoc_tolerance_s", 2.0)),
                    grid_step_deg=float(
                        sc.get("assoc_grid_step_deg", 0.25)
                    ),
                    dedup_window_s=float(
                        sc.get("assoc_dedup_window_s", 2.0)
                    ),
                ), wal=wal)
                if wal is not None:
                    seeded = assoc.seed_from_wal()
                    if seeded:
                        logger.info(
                            f"[serve] stream '{name}': seeded "
                            f"{seeded} WAL alerts into dedup window"
                        )
                batcher = self._batcher_for(name, "fp32")
                timeout_ms = float(opts.timeout_ms)

                def submit(x, _b=batcher, _t=timeout_ms):
                    # Due windows ride the SAME warm fp32 bucket programs
                    # /predict runs, at alert rank — thousands of
                    # stations coalesce in the batcher's flushes with
                    # zero new compiles (tests/test_stream_mux.py pin).
                    return _b.submit(x, timeout_ms=_t,
                                     rank=PRIORITIES["alert"])

                mux = StationMux(
                    submit,
                    MuxConfig(
                        session=session,
                        max_stations=int(sc.get("max_stations", 4096)),
                        idle_timeout_s=float(
                            sc.get("idle_timeout_s", 900.0)
                        ),
                        journal_every_s=float(
                            sc.get("journal_every_s", 5.0)
                        ),
                        model=name,
                    ),
                    assoc=assoc,
                    journal=journal,
                )
                self._stream_muxes[name] = mux
            return mux

    @staticmethod
    def _synthetic_stream_result() -> Dict[str, Any]:
        """Feed-shaped success for a faulted (dropped/held) packet: the
        client sees a 200 with no picks, exactly what a swallowed packet
        looks like from outside."""
        return {
            "n_samples": 0,
            "windows": 0,
            "duplicate": False,
            "closed": False,
            "degraded": False,
            "dropped_windows": 0,
            "picks": {"ppk": [], "spk": [], "det": []},
            "alerts": [],
        }

    def stream(
        self,
        body: Dict[str, Any],
        trace: Optional[obs_trace.RequestTrace] = None,
    ) -> Dict[str, Any]:
        """One station packet into the long-lived streaming plane (``POST
        /stream``): route it to the station's StreamSession, run whatever
        windows fell due through the micro-batcher at alert rank, and
        return the picks that just became final plus any network alerts
        the associator raised. ``end=true`` flushes the tail window and
        closes the session. Packets are raw counts — the session applies
        the same per-window normalization /annotate uses, which is what
        makes its picks bit-identical to offline re-annotation."""
        if self._draining:
            raise ShuttingDown("service is draining")
        t = obs_trace.ensure(trace)
        entry = self.pool.get(body.get("model"))
        if not entry.is_picker:
            raise BadRequest(
                f"model '{entry.name}' is not a picking model; /stream "
                "needs (non|det, ppk, spk) outputs"
            )
        if getattr(entry, "is_group", False):
            raise BadRequest(
                f"model '{entry.name}' is a multi-task group; /stream "
                "serves single-task picking models"
            )
        options = dict(body.get("options") or {})
        # Streaming IS the early-warning path: default to the alert tier
        # (shed last, ride to the 429 bound) unless the caller says so.
        options.setdefault("priority", "alert")
        opts = PredictOptions.from_dict(options)
        if opts.variant != "fp32":
            raise BadRequest(
                "variant selection is /predict-only; /stream always "
                "runs fp32"
            )
        station = parse_station(body.get("station"), required=True)
        end = bool(body.get("end", False))
        seq = body.get("seq")
        if seq is not None and (isinstance(seq, bool)
                                or not isinstance(seq, int)):
            raise BadRequest("'seq' must be an integer")
        version = int(getattr(entry, "version", 0) or 0)
        t.annotate(model=entry.name, tier=opts.priority,
                   station=station["id"], version=version)
        with self._lock:
            self._requests["stream"] += 1
            n_request = self._requests["stream"]
        # Packet arrival: fire any scheduled stream fault (SIGKILL at
        # packet k) before admission — a mid-mainshock crash must not be
        # dodged by the shedder.
        self._stream_faults.on_packet(n_request)
        with t.span("admission", tier=opts.priority) as sp:
            try:
                # end=true RELEASES a station slot — always admitted
                # (serve/shed.py final-exemption contract).
                self._shedders[entry.name].admit(opts.priority, final=end)
            except Overloaded as e:
                sp.annotate(verdict="shed",
                            retry_after_s=round(e.retry_after_s, 3))
                t.flag("shed")
                raise
            sp.annotate(verdict="admitted")
        with t.span("parse"):
            if body.get("data") is None:
                if not end:
                    raise BadRequest(
                        "'data' is required unless end=true (a bare "
                        "end=true flushes and closes the session)"
                    )
                x = np.zeros((0, entry.in_channels), np.float32)
            else:
                x = parse_waveform(body.get("data"), entry.in_channels)
        mux = self._stream_mux_for(entry, opts)
        if n_request % 64 == 0:
            # Amortized housekeeping: sessions whose station went quiet
            # past idle_timeout_s are reaped on the request path itself.
            mux.reap_idle()
        from seist_tpu.stream.mux import MuxClosed, StationLimit

        # Packet fate (SEIST_FAULT_STREAM_{DROP,DUP,REORDER}_P): 'ok'
        # unless the chaos lane scheduled faults for this replica. A
        # dropped packet is swallowed server-side AFTER the client got
        # its 200 — the failure mode a transport ack cannot see, which
        # the session's gap-stitch must absorb. A reordered packet is
        # held and delivered after the station's next one; the plane
        # does not reassemble, so it arrives stale and degrades to
        # gap+duplicate (the documented semantics, now exercised).
        fate = "ok"
        if not end:
            fate = self._stream_faults.packet_fate(station["id"], seq)
        held_key = (entry.name, station["id"])
        try:
            with t.span("stream_feed", station=station["id"],
                        packet_samples=int(x.shape[0]), fate=fate):
                if fate == "drop":
                    t.flag("fault_drop")
                    result = self._synthetic_stream_result()
                elif fate == "reorder":
                    t.flag("fault_reorder")
                    with self._stream_lock:
                        prev_held = self._held_packets.pop(held_key, None)
                        self._held_packets[held_key] = (station, x, seq)
                    if prev_held is not None:
                        # Two holds in a row: deliver the older one now
                        # (still late) instead of losing it outright.
                        mux.feed(prev_held[0], prev_held[1],
                                 seq=prev_held[2], end=False)
                    result = self._synthetic_stream_result()
                else:
                    with self._stream_lock:
                        held = self._held_packets.pop(held_key, None)
                    if held is not None and end:
                        # Flush the held packet before the closing feed;
                        # after end the session is gone.
                        mux.feed(held[0], held[1], seq=held[2], end=False)
                        held = None
                    result = mux.feed(station, x, seq=seq, end=end)
                    if held is not None:
                        # Late delivery: stale seq -> idempotent drop.
                        mux.feed(held[0], held[1], seq=held[2], end=False)
                    if fate == "dup":
                        t.flag("fault_dup")
                        mux.feed(station, x, seq=seq, end=False)
        except StationLimit as e:
            # Same backpressure contract as a full queue: 429, back off.
            raise QueueFull(str(e)) from None
        except MuxClosed as e:
            # close_all() latched (SIGTERM drain): 503 so the router
            # retries this packet on a surviving replica, which restores
            # the station from its journal.
            raise ShuttingDown(str(e)) from None
        fs = float(mux.config.session.sampling_rate)
        picks = result["picks"]
        return {
            "model": entry.name,
            "model_version": version,
            "station": station,
            "n_samples": int(result["n_samples"]),
            "windows": int(result["windows"]),
            "duplicate": bool(result["duplicate"]),
            "closed": bool(result["closed"]),
            "degraded": bool(result["degraded"]),
            "dropped_windows": int(result["dropped_windows"]),
            "ppk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["ppk"]
            ],
            "spk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["spk"]
            ],
            "det": [
                {"onset": int(a), "offset": int(b),
                 "onset_s": round(int(a) / fs, 6),
                 "offset_s": round(int(b) / fs, 6)}
                for a, b in picks["det"]
            ],
            "alerts": result["alerts"],
        }

    def stream_alerts(self, n: int = 50) -> Dict[str, Any]:
        """``GET /stream/alerts``: recent association alerts + mux stats
        per streaming model — the downstream (alerting UI, twin gate)
        poll surface."""
        with self._stream_lock:
            muxes = dict(self._stream_muxes)
        return {
            "models": {
                name: {
                    "alerts": mux.assoc.recent_alerts(n),
                    "stats": mux.stats(),
                }
                for name, mux in muxes.items()
            },
        }

    # ------------------------------------------------------------- reload
    def reload(
        self,
        model: Optional[str] = None,
        checkpoint: Optional[str] = None,
        checkpoints: Optional[Dict[str, str]] = None,
        version: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """Hot-swap one pool entry for a new checkpoint (``POST
        /admin/reload``). The candidate loads beside the incumbent,
        re-runs the full load-time gate ladder (AOT compile + variant
        parity + finite probe — serve/pool.ModelPool.reload), and only
        full success swaps; a failure leaves the incumbent serving and
        raises the structured error. The incumbent serves throughout —
        reload is invisible to in-flight traffic except as the
        ``model_version`` flip in responses."""
        if self._draining:
            raise ShuttingDown("service is draining; not accepting reloads")
        if self._warming:
            raise ReloadFailed(
                "initial warm-up still running; retry once /healthz/ready "
                "reports ready"
            )
        entry = self.pool.get(model)
        if checkpoint is not None and not isinstance(checkpoint, str):
            raise BadRequest("'checkpoint' must be a string path")
        if checkpoints is not None and not (
            isinstance(checkpoints, dict)
            and all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in checkpoints.items()
            )
        ):
            raise BadRequest("'checkpoints' must be {task: ckpt} strings")
        if version is not None:
            try:
                version = int(version)
            except (TypeError, ValueError):
                raise BadRequest(
                    f"'version' must be an integer, got {version!r}"
                ) from None
        with self._reload_lock:  # one reload at a time per replica
            previous = int(getattr(entry, "version", 0) or 0)
            target = version if version is not None else previous + 1
            from seist_tpu.obs.bus import BUS

            t0 = time.monotonic()
            try:
                new_entry, report = self.pool.reload(
                    entry.name,
                    buckets=self.buckets,
                    checkpoint=checkpoint,
                    checkpoints=checkpoints,
                    version=target,
                    force_gate_failure=self._faults.is_bad_candidate(target),
                )
            except ServeError as e:
                BUS.counter(
                    "serve_reload_total", model=entry.name, outcome=e.code
                ).inc()
                if self._event_log is not None:
                    self._event_log.emit(
                        "serve_reload", model=entry.name, outcome=e.code,
                        version=target, error=str(e),
                    )
                raise
            reload_s = time.monotonic() - t0
            BUS.counter(
                "serve_reload_total", model=entry.name, outcome="ok"
            ).inc()
            if self._event_log is not None:
                self._event_log.emit(
                    "serve_reload", model=entry.name, outcome="ok",
                    version=target, previous_version=previous,
                    reload_s=round(reload_s, 3),
                )
            return {
                "model": entry.name,
                "version": target,
                "previous_version": previous,
                "variants": new_entry.supported_variants(),
                "programs": len(report),
                "reload_s": round(reload_s, 3),
            }

    # ------------------------------------------------------ health/metrics
    def alive(self) -> bool:
        """Liveness: warm-up didn't fail and every batcher flush thread
        is still running. Neither condition can recover — the server
        watchdog exits non-zero on this so the orchestrator restarts the
        process instead of leaving a zombie that black-holes requests."""
        return self._warmup_error is None and all(
            b.healthy for b in self._batchers.values()
        )

    def ready(self) -> bool:
        """Readiness: alive, warm-compiled, and not draining."""
        return self.alive() and not self._warming and not self._draining

    def _state_str(self) -> str:
        if not self.alive():
            return "dead"
        if self._draining:
            return "draining"
        if self._warming:
            return "warming"
        return "ok"

    def model_versions(self) -> Dict[str, int]:
        """{model: served version} — rides /healthz AND /healthz/ready so
        the router's prober (canary cohorts) and the fleet supervisor's
        rolling restart can tell a converged fleet from a mid-roll one
        without scraping logs."""
        return {
            name: int(getattr(self.pool.get(name), "version", 0) or 0)
            for name in self.pool.names()
        }

    def healthz(self) -> Dict[str, Any]:
        entries: Dict[str, Any] = {}
        for name in self.pool.names():
            e = self.pool.get(name)
            info: Dict[str, Any] = {
                "version": int(getattr(e, "version", 0) or 0),
                "variants": (
                    e.supported_variants()
                    if hasattr(e, "supported_variants")
                    else ["fp32"]
                ),
            }
            if getattr(e, "is_group", False):
                info["tasks"] = list(e.tasks)
            entries[name] = info
        return {
            "status": self._state_str(),
            "live": self.alive(),
            "ready": self.ready(),
            "models": self.pool.names(),
            # Per-entry served version + variant surface: the converged-
            # vs-mid-roll discriminator (docs/SERVING.md "Live rollout").
            "entries": entries,
            "buckets": list(self.buckets),
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "warmup": self.pool.warmup_report,
        }

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            requests = dict(self._requests)
            annotate_windows = self._annotate_windows
        with self._stream_lock:
            stream_stats = {
                name: mux.stats()
                for name, mux in self._stream_muxes.items()
            }
        return {
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "requests": requests,
            "annotate": {
                "windows": annotate_windows,
                "latency_ms": self.annotate_latency_ms.summary(),
            },
            "models": {
                name: batcher.stats()
                for name, batcher in self._batchers.items()
            },
            "shed": {
                name: shedder.stats()
                for name, shedder in self._shedders.items()
            },
            # Streaming plane: per-model session/window/pick/alert
            # accounting (stream_* / assoc_* counters mirror these on
            # the bus, labeled — docs/OBSERVABILITY.md).
            "stream": stream_stats,
            # Multi-task groups: trunk-once accounting (trunk_runs,
            # per-head runs, amortized trunk FLOPs, variant gates).
            "fanout": {
                name: self.pool.get(name).fanout_stats()
                for name in self.pool.names()
                if getattr(self.pool.get(name), "is_group", False)
            },
        }

    def _bus_metrics(self) -> Dict[str, Any]:
        """The bus-collector payload: everything in :meth:`metrics` except
        the per-model stats (batchers publish those themselves, labeled)."""
        m = self.metrics()
        m.pop("models", None)
        m.pop("shed", None)  # AdmissionControllers publish their own
        m.pop("stream", None)  # StationMux counters publish their own
        return m

    def metrics_prometheus(self) -> str:
        """Prometheus text exposition of the process bus — the serve
        process's scrape surface (``GET /metrics?format=prometheus``),
        same renderer as the train worker's --metrics-port."""
        from seist_tpu.obs.bus import BUS, render_prometheus

        return render_prometheus(BUS)

    # ----------------------------------------------------------- shutdown
    def begin_drain(self) -> None:
        """Flip to not-ready (new /predict //annotate get 503, readiness
        probe fails) without yet stopping the batchers — the signal
        handler calls this so in-flight work finishes while the load
        balancer routes away."""
        self._draining = True
        self.publish_state("drain")

    def shutdown(self, drain: bool = True) -> None:
        """Refuse new work, then (with ``drain``) serve what's queued."""
        self._draining = True
        self.publish_state("shutdown")
        # Streaming sessions close before their batchers stop: a mux
        # submit into a shut-down batcher would only error anyway.
        with self._stream_lock:
            muxes, self._stream_muxes = dict(self._stream_muxes), {}
        for mux in muxes.values():
            mux.close_all()
        for batcher in self._batchers.values():
            batcher.shutdown(drain=drain)
        for shedder in self._shedders.values():
            shedder.close()
        # Mirror the batchers: a shut-down service must neither pin the
        # model pool via the bus's collector ref nor report its stale
        # request counters as live on a later scrape.
        from seist_tpu.obs.bus import BUS

        BUS.unregister_collector("serve", fn=self._bus_metrics)


def _clip_picks(result: Dict[str, Any], n_real: int, fs: float) -> None:
    """Drop decoded picking outputs that fall inside zero-padding (sample
    >= ``n_real``); detection intervals are clipped to the real extent."""
    if result.get("task") != "picking":
        return
    for kind in ("ppk", "spk"):
        if kind in result:
            result[kind] = [p for p in result[kind] if p["sample"] < n_real]
    if "det" in result:
        kept = []
        for d in result["det"]:
            if d["onset"] >= n_real:
                continue
            if d["offset"] >= n_real:
                d = dict(
                    d,
                    offset=n_real - 1,
                    offset_s=round((n_real - 1) / fs, 6),
                )
            kept.append(d)
        result["det"] = kept


def _normalize_trace(x: np.ndarray, norm_mode: str) -> np.ndarray:
    if norm_mode not in _NORM_MODES:
        raise BadRequest(
            f"norm_mode must be one of {_NORM_MODES}, got '{norm_mode}'"
        )
    from seist_tpu.data.preprocess import normalize

    # (L, C): time axis is 0.
    return np.asarray(normalize(x, norm_mode, axis=0), np.float32)


# ---------------------------------------------------------------- HTTP shim
class _Handler(BaseHTTPRequestHandler):
    server_version = "seist-serve/0.1"
    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> ServeService:
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, format: str, *args: Any) -> None:
        logger.debug(f"[serve] {self.address_string()} {format % args}")

    def _reply(
        self,
        status: int,
        payload: Dict[str, Any],
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> None:
        body = json_bytes(payload)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if self.close_connection:
            # Tell the client, not just the socket: without the header an
            # HTTP/1.1 client assumes keep-alive and retries a dead conn.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, text: str, ctype: str) -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            if self.path == "/healthz":
                # Combined report (back-compat); always 200 while the
                # process can answer at all.
                self._reply(200, self.service.healthz())
            elif self.path == "/healthz/live":
                live = self.service.alive()
                self._reply(
                    200 if live else 503,
                    {"status": "ok" if live else "dead"},
                )
            elif self.path == "/healthz/ready":
                ready = self.service.ready()
                self._reply(
                    200 if ready else 503,
                    {
                        "status": self.service._state_str(),
                        "ready": ready,
                        # The router's prober reads versions from here
                        # (one probe, no extra round trip) to keep canary
                        # cohorts and /router/replicas current.
                        "versions": self.service.model_versions(),
                    },
                )
            elif self.path == "/metrics.json":
                # Raw bus snapshot — the payload the fleet aggregator
                # scrapes and merges (obs/fleet.py); bucket counts ride
                # along for bucket-wise histogram merging.
                from seist_tpu.obs.bus import BUS

                self._reply(200, BUS.snapshot())
            elif self.path.split("?", 1)[0] == "/stream/alerts":
                self._reply(200, self.service.stream_alerts())
            elif self.path.split("?", 1)[0].startswith("/traces"):
                routed = obs_trace.handle_traces_path(self.path)
                if routed is None:
                    self._reply(404, {"error": "not_found",
                                      "message": self.path})
                else:
                    self._reply(*routed)
            elif self.path.split("?", 1)[0] == "/metrics":
                # ?format=prometheus selects text exposition regardless
                # of other params/ordering (real scrapers append job
                # labels etc.); bare /metrics stays the back-compat JSON
                # (docs/OBSERVABILITY.md).
                from urllib.parse import parse_qs, urlparse

                query = parse_qs(urlparse(self.path).query)
                if "prometheus" in query.get("format", []):
                    self._reply_text(
                        200,
                        self.service.metrics_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._reply(200, self.service.metrics())
            else:
                self._reply(404, {"error": "not_found", "message": self.path})
        except Exception as e:  # noqa: BLE001
            # An unexpected handler bug is a death-path-shaped event even
            # though the process survives: leave the forensic flight
            # record (non-fatal — must never suppress a later crash dump).
            obs_flight.dump_on_death(
                "serve_handler_exception", arm_dedup=False,
                request_path=self.path, error=repr(e),
            )
            self._reply(500, {"error": "internal", "message": repr(e)})

    def _trace_headers(
        self, rt: Optional[obs_trace.RequestTrace], status: int
    ) -> Dict[str, str]:
        """Finish the request trace and render its response headers: a
        ``Server-Timing``-style breakdown plus the ``traceparent`` echo
        (so a client that did not mint the id can still fetch
        ``/traces/<id>``)."""
        if rt is None:
            return {}
        rt.finish(status)
        return {
            "Server-Timing": rt.server_timing(),
            obs_trace.TRACEPARENT_HEADER: rt.traceparent,
        }

    def do_POST(self) -> None:  # noqa: N802
        rt: Optional[obs_trace.RequestTrace] = None
        try:
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_BODY_BYTES:
                # The unread body would desync this keep-alive connection
                # (its bytes would parse as the next request line) — close.
                self.close_connection = True
                self._reply(
                    413,
                    {"error": "too_large",
                     "message": f"body {length} > {MAX_BODY_BYTES} bytes"},
                )
                return
            raw = self.rfile.read(length)
            if self.path in ("/predict", "/annotate", "/stream"):
                # Continue the upstream trace (bench client / router) or
                # mint here — the replica is the last possible edge.
                rt = obs_trace.RequestTrace(
                    self.headers.get(obs_trace.TRACEPARENT_HEADER),
                    name=f"server:{self.path}",
                )
            body = parse_body(raw)
            if self.path == "/predict":
                result = self.service.predict(
                    body.get("data"),
                    model=body.get("model"),
                    options=body.get("options"),
                    tasks=body.get("tasks"),
                    station=body.get("station"),
                    trace=rt,
                )
            elif self.path == "/annotate":
                result = self.service.annotate(
                    body.get("data"),
                    model=body.get("model"),
                    options=body.get("options"),
                    trace=rt,
                )
            elif self.path == "/stream":
                result = self.service.stream(body, trace=rt)
            elif self.path == "/admin/reload":
                # Hot checkpoint rollout (docs/SERVING.md "Live
                # rollout"): load-gate-swap, incumbent serves throughout;
                # structured 4xx on an unfit candidate.
                result = self.service.reload(
                    model=body.get("model"),
                    checkpoint=body.get("checkpoint"),
                    checkpoints=body.get("checkpoints"),
                    version=body.get("version"),
                )
            else:
                self._reply(404, {"error": "not_found", "message": self.path})
                return
            self._reply(200, result,
                        extra_headers=self._trace_headers(rt, 200))
        except ServeError as e:
            # e.headers() carries e.g. the shed path's Retry-After.
            headers = e.headers()
            headers.update(self._trace_headers(rt, e.status))
            self._reply(e.status, e.payload(), extra_headers=headers)
        except Exception as e:  # noqa: BLE001
            logger.warning(f"[serve] unhandled error: {e!r}")
            obs_flight.dump_on_death(
                "serve_handler_exception", arm_dedup=False,
                request_path=self.path, error=repr(e),
            )
            self._reply(500, {"error": "internal", "message": repr(e)},
                        extra_headers=self._trace_headers(rt, 500))


class ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: a conn-per-request
    # client burst overflows it and dropped SYNs retry at 1/3/7/15/31 s,
    # showing up as client-side latency clusters while the batcher is
    # idle. Overload must surface via the shed/429 tiers, not the
    # kernel's SYN queue (see RouterHTTPServer).
    request_queue_size = 1024

    def __init__(self, addr: Tuple[str, int], service: ServeService):
        super().__init__(addr, _Handler)
        self.service = service


def start_http_server(
    service: ServeService, host: str = "127.0.0.1", port: int = 8080
) -> ServeHTTPServer:
    """Bind + serve on a daemon thread; returns the bound server (use
    ``server.server_address`` to discover an ephemeral port)."""
    server = ServeHTTPServer((host, port), service)
    thread = threading.Thread(
        target=server.serve_forever, name="serve-http", daemon=True
    )
    thread.start()
    return server


# ----------------------------------------------------------------- CLI
def get_serve_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="serve", description="seist_tpu online inference service"
    )
    ap.add_argument(
        "--model", action="append", default=[], metavar="NAME[=CKPT]",
        help="model to serve, repeatable; NAME alone serves fresh-init "
        "weights (smoke/testing)",
    )
    ap.add_argument(
        "--model-group", action="append", default=[],
        metavar="PREFIX=TASK[:CKPT],TASK[:CKPT],...",
        help="multi-task SeisT group: PREFIX_TASK models on ONE shared "
        "trunk, e.g. seist_s=dpk:CKPT,emg:CKPT2 — a multi-task /predict "
        "runs the trunk once and fans out (docs/SERVING.md)",
    )
    ap.add_argument(
        "--variants", default="fp32",
        help="comma-separated serving weight variants to AOT-compile at "
        "load: fp32,bf16,int8 (selected per request via options.variant; "
        "non-fp32 variants are parity-gated against fp32)",
    )
    ap.add_argument("--model-name", default="", help="single-model shorthand")
    ap.add_argument("--checkpoint", default="", help="with --model-name")
    ap.add_argument(
        "--model-version", type=int,
        default=int(os.environ.get("SEIST_MODEL_VERSION", "") or 1),
        help="monotonic version stamp for the loaded checkpoints "
        "(default: $SEIST_MODEL_VERSION or 1) — reported in every "
        "response and /healthz; the rolling-restart handle "
        "(docs/SERVING.md 'Live rollout')",
    )
    ap.add_argument("--window", type=int, default=8192)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=10.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument(
        "--buckets", default="",
        help="comma-separated batch buckets (default: powers of 2 up to "
        "--max-batch); largest must equal --max-batch",
    )
    ap.add_argument("--seed", type=int, default=0)
    # Adaptive load shedding (serve/shed.py): per-tier queue-delay
    # budgets. 'inf' disables policy shedding for a tier.
    ap.add_argument("--shed-batch-delay-ms", type=float, default=50.0,
                    help="shed 'batch' tier above this queue delay")
    ap.add_argument("--shed-interactive-delay-ms", type=float,
                    default=250.0,
                    help="shed 'interactive' tier above this queue delay")
    ap.add_argument("--shed-alert-delay-ms", type=float,
                    default=float("inf"),
                    help="shed 'alert' tier above this queue delay "
                    "(default: never — alerts ride to the 429 bound)")
    # Streaming plane (/stream): station mux capacity + cross-station
    # association (docs/SERVING.md "Streaming inference").
    ap.add_argument("--stream-max-stations", type=int, default=4096,
                    help="concurrent streaming sessions per model; new "
                    "stations past this get 429")
    ap.add_argument("--stream-idle-timeout-s", type=float, default=900.0,
                    help="reap a station's session after this much "
                    "feed silence")
    ap.add_argument("--assoc-min-stations", type=int, default=4,
                    help="distinct co-detecting stations to raise a "
                    "network alert")
    ap.add_argument("--assoc-window-s", type=float, default=30.0,
                    help="cross-station co-detection window")
    ap.add_argument("--assoc-velocity-kms", type=float, default=6.0,
                    help="P moveout velocity for origin back-projection")
    ap.add_argument("--assoc-tolerance-s", type=float, default=2.0,
                    help="origin-time coherence tolerance")
    ap.add_argument("--assoc-grid-step-deg", type=float, default=0.25,
                    help="origin grid-search resolution")
    ap.add_argument("--assoc-dedup-window-s", type=float, default=2.0,
                    help="suppress a network alert whose origin sits "
                    "within this many seconds (and dedup_dist_deg) of an "
                    "already-emitted one — the exactly-once half of the "
                    "alert WAL contract")
    ap.add_argument("--stream-journal-dir", default=None,
                    help="directory for per-station session journals + "
                    "the alert WAL; share it across a fleet to enable "
                    "failover re-homing (unset = no journaling)")
    ap.add_argument("--stream-journal-every-s", type=float, default=5.0,
                    help="min seconds between journal writes per station")
    return ap.parse_args(argv)


def parse_model_flags(args: argparse.Namespace) -> List[Tuple[str, str]]:
    entries: List[Tuple[str, str]] = []
    for spec in args.model:
        name, _, ckpt = spec.partition("=")
        entries.append((name, ckpt))
    if args.model_name:
        entries.append((args.model_name, args.checkpoint))
    if not entries and not getattr(args, "model_group", None):
        raise SystemExit(
            "serve: need --model NAME[=CKPT], --model-name or --model-group"
        )
    return entries


def parse_group_flags(
    args: argparse.Namespace,
) -> List[Tuple[str, List[Tuple[str, str]]]]:
    """--model-group PREFIX=TASK[:CKPT],... -> [(prefix, [(task, ckpt)])]."""
    groups: List[Tuple[str, List[Tuple[str, str]]]] = []
    for spec in getattr(args, "model_group", []) or []:
        prefix, sep, rest = spec.partition("=")
        if not sep or not prefix or not rest:
            raise SystemExit(
                f"serve: bad --model-group '{spec}' "
                "(want PREFIX=TASK[:CKPT],TASK[:CKPT],...)"
            )
        tasks: List[Tuple[str, str]] = []
        for part in rest.split(","):
            task, _, ckpt = part.partition(":")
            if not task:
                raise SystemExit(
                    f"serve: empty task in --model-group '{spec}'"
                )
            tasks.append((task, ckpt))
        groups.append((prefix, tasks))
    return groups


def watch_until_shutdown(
    service: ServeService,
    stop: "threading.Event",
    poll_s: float = 0.5,
) -> int:
    """Main-thread watchdog: block until ``stop`` (graceful shutdown) or
    a batcher flush thread dies. Returns the process exit code — 0 for a
    clean drain, 1 for a dead batcher. The non-zero exit is the point: a
    server whose flush thread died would otherwise sit silently while
    every request times out, and no orchestrator would restart it."""
    while not stop.is_set():
        if not service.alive():
            sick = [
                n for n, b in service._batchers.items() if not b.healthy
            ]
            reason = (
                f"batcher flush thread(s) died: {sick}"
                if sick
                else f"warm-up failed: {service._warmup_error!r}"
            )
            publish = getattr(service, "publish_state", None)
            if publish is not None:  # tests pass bare namespaces
                publish(reason)
            # The batcher's own death path already dumped with the rich
            # reason; dedup keeps this exit-side record from shadowing it.
            obs_flight.dump_on_death("serve_unhealthy", dedup_s=5.0,
                                     detail=reason)
            logger.warning(f"[serve] {reason}; exiting 1")
            return 1
        stop.wait(poll_s)
    return 0


def main(argv: Optional[List[str]] = None) -> None:
    from seist_tpu.utils.misc import enable_compile_cache
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    # Warm-up compiles dominate replica startup; the persistent cache
    # (same one cli.main_worker uses) makes a supervisor relaunch re-enter
    # rotation in seconds instead of re-paying every bucket's compile.
    enable_compile_cache()
    args = get_serve_args(argv)
    entries = parse_model_flags(args)
    config = BatcherConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue,
        buckets=(
            tuple(int(b) for b in args.buckets.split(","))
            if args.buckets
            else None
        ),
    )
    import os as _os

    from seist_tpu.obs.bus import EventLog

    shed_config = ShedConfig(
        batch_delay_ms=args.shed_batch_delay_ms,
        interactive_delay_ms=args.shed_interactive_delay_ms,
        alert_delay_ms=args.shed_alert_delay_ms,
    )
    # Replica lifecycle events (warming/ok/draining + shed decisions) go
    # to the same events stream the train worker writes — suffixed with
    # the fleet ordinal (events_r0.jsonl, ...) so N replicas sharing one
    # --logdir never interleave/clobber one file (obs/trace.replica_suffix).
    events = EventLog(_os.path.join(
        logger.logdir(), f"events{obs_trace.replica_suffix()}.jsonl"
    ))
    # Serve-plane flight recorder: request spans land in the ring via the
    # bus sink, and the serve death paths (batcher flush death, handler
    # exception, unhealthy exit) dump it exactly like the train worker's.
    obs_flight.install(obs_flight.FlightRecorder())
    # Trace-plane retention counters on the scrape surface.
    obs_trace.register_trace_collector()
    pool = ModelPool(
        entries,
        window=args.window,
        seed=args.seed,
        groups=parse_group_flags(args),
        variants=tuple(
            v.strip() for v in args.variants.split(",") if v.strip()
        ),
        version=args.model_version,
    )
    # Async warm-up: the socket (and /healthz/ready, reporting 503
    # "warming") comes up immediately; orchestrators gate traffic on
    # readiness instead of timing out their liveness probe on the compile.
    service = ServeService(
        pool, config, warmup_async=True, shed_config=shed_config,
        event_log=events,
        stream_config={
            "max_stations": args.stream_max_stations,
            "idle_timeout_s": args.stream_idle_timeout_s,
            "assoc_min_stations": args.assoc_min_stations,
            "assoc_window_s": args.assoc_window_s,
            "assoc_velocity_kms": args.assoc_velocity_kms,
            "assoc_tolerance_s": args.assoc_tolerance_s,
            "assoc_grid_step_deg": args.assoc_grid_step_deg,
            "assoc_dedup_window_s": args.assoc_dedup_window_s,
            "journal_dir": args.stream_journal_dir,
            "journal_every_s": args.stream_journal_every_s,
        },
    )
    server = start_http_server(service, args.host, args.port)
    host, port = server.server_address[:2]
    logger.info(
        f"[serve] listening on http://{host}:{port} "
        f"models={pool.names()} buckets={list(service.buckets)}"
    )

    import signal

    stop = threading.Event()
    # SIGTERM = managed preemption (orchestrator reschedule, node drain):
    # drain in-flight work, then exit PREEMPT_EXIT_CODE so the fleet
    # supervisor relaunches immediately with its retry budget untouched.
    # SIGINT = an operator stopping the process: exit 0, no relaunch.
    exit_code = {"rc": 0}

    def _term(signum, frame):
        if signum == signal.SIGTERM:
            exit_code["rc"] = PREEMPT_EXIT_CODE
        # threadlint: disable=signal-handler-unsafe -- begin_drain is a
        # plain flag store + edge-triggered publish; the interrupted main
        # thread is parked in watch_until_shutdown's stop.wait and never
        # holds service._lock, and logging's RLock is reentrant from the
        # same thread. Flipping 503s on immediately (vs at the next poll
        # tick) is what lets the load balancer route away during drain.
        service.begin_drain()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    rc = watch_until_shutdown(service, stop)
    if rc == 0:
        rc = exit_code["rc"]
        logger.info("[serve] draining...")
        service.shutdown(drain=True)
        server.shutdown()
        logger.info(f"[serve] stopped (rc={rc})")
    else:
        server.shutdown()
        service.shutdown(drain=False)
        logger.info("[serve] stopped (unhealthy)")
    events.close()
    if rc:
        raise SystemExit(rc)


if __name__ == "__main__":
    main()
