"""Online inference service: micro-batched /predict, streaming /annotate,
health + metrics — stdlib HTTP only (http.server), no new dependencies.

Layering:

* :class:`ServeService` — transport-free core (also the in-process test
  client): model pool + one MicroBatcher per model + counters. Single
  fixed-window traces go through the batcher; long records go through
  ``ops/stream.annotate`` driving the SAME warm per-bucket forward
  (``jitted=True``, ``batch_size=largest bucket``), so the expensive
  model forward never compiles after warm-up. (The lightweight
  stitch/pick programs in /annotate still compile once per new record
  length — small, host-bound, and amortized across same-length records.)
* :class:`ServeHTTPServer` + handler — a thin JSON shim: ServeError
  subclasses carry their own HTTP status (429 queue-full backpressure,
  504 deadline, 503 draining, 400/404 client errors).

Endpoints::

    POST /predict   one (window, C) trace   -> picks / regression / class
    POST /annotate  one (L >= window, C) record -> picks over the record
    GET  /healthz   liveness + model list + warm-up state
    GET  /metrics   queue depth, batch-fill ratio, latency histograms

CLI: ``python main.py serve --model seist_s_dpk=CKPT --port 8080 ...``
(see ``main()``); ``make serve-smoke`` runs the no-checkpoint smoke.
"""

from __future__ import annotations

import argparse
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher
from seist_tpu.serve.pool import ModelPool, decode_outputs
from seist_tpu.serve.protocol import (
    BadRequest,
    DeadlineExceeded,
    PredictOptions,
    ServeError,
    ShuttingDown,
    json_bytes,
    parse_body,
    parse_waveform,
)
from seist_tpu.utils.logger import logger
from seist_tpu.utils.meters import LatencyHistogram

MAX_BODY_BYTES = 64 * 1024 * 1024  # one hours-long fp32 record is ~tens of MB

_NORM_MODES = ("std", "max", "absmax", "")


class ServeService:
    """Transport-free serving core; every public method raises ServeError
    subclasses on failure and returns JSON-able dicts on success."""

    def __init__(
        self,
        pool: ModelPool,
        batcher_config: Optional[BatcherConfig] = None,
        warmup_async: bool = False,
    ):
        self.pool = pool
        self.config = batcher_config or BatcherConfig()
        self.buckets = self.config.resolved_buckets()
        self._batchers: Dict[str, MicroBatcher] = {}
        for name in pool.names():
            entry = pool.get(name)
            import jax.numpy as jnp

            fwd = entry.forward
            self._batchers[name] = MicroBatcher(
                lambda batch, _f=fwd: _f(jnp.asarray(batch)),
                self.config,
                name=name,
            )
        self._annotate_locks = {n: threading.Lock() for n in pool.names()}
        self.annotate_latency_ms = LatencyHistogram()
        self._lock = threading.Lock()
        self._requests = {"predict": 0, "annotate": 0}
        self._annotate_windows = 0
        # monotonic: _started_at only ever feeds uptime_s intervals, and a
        # wall-clock step must not make uptime jump (or go negative).
        self._started_at = time.monotonic()
        self._draining = False
        # Readiness gate: /healthz/ready reports 503 while the pool is
        # still pre-compiling (warmup_async=True lets the HTTP socket come
        # up first so orchestrators can probe during the compile) and
        # during SIGTERM drain. Requests arriving while warming are still
        # served — they just pay the compile — so readiness is advisory,
        # exactly what a load balancer wants.
        self._warming = True
        self._warmup_error: Optional[BaseException] = None
        # Metrics-bus collector (obs/bus.py): the request/annotate half
        # of metrics(); batchers self-register their own. One key per
        # service — a restarted service replaces its predecessor.
        from seist_tpu.obs.bus import BUS

        BUS.register_collector("serve", self._bus_metrics)
        if warmup_async:
            threading.Thread(
                target=self._run_warmup, name="serve-warmup", daemon=True
            ).start()
        else:
            self._run_warmup()
            if self._warmup_error is not None:
                raise self._warmup_error  # sync path keeps crashing loudly

    def _run_warmup(self) -> None:
        try:
            self.pool.warmup(self.buckets)
            self._warming = False
        except BaseException as e:  # noqa: BLE001
            # A failed warm-up (compile OOM, bad bucket, XLA error) must
            # never flip the service to ready: record it so liveness goes
            # false and the watchdog exits non-zero — the async
            # equivalent of the sync path's crash.
            self._warmup_error = e
            logger.warning(f"[serve] warm-up failed: {e!r}")

    # ----------------------------------------------------------- predict
    def predict(
        self,
        data: Any,
        model: Optional[str] = None,
        options: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """One fixed-window trace through the micro-batcher."""
        if self._draining:
            raise ShuttingDown("service is draining")
        entry = self.pool.get(model)
        opts = PredictOptions.from_dict(options)
        x = parse_waveform(data, entry.in_channels)
        if x.shape[0] > entry.window:
            raise BadRequest(
                f"trace length {x.shape[0]} > window {entry.window}; "
                "use POST /annotate for long records"
            )
        x = _normalize_trace(x, opts.norm_mode)
        n_real = x.shape[0]
        if n_real < entry.window:  # pad AFTER normalize: zeros stay zero
            pad = np.zeros((entry.window - n_real, x.shape[1]), dtype=x.dtype)
            x = np.concatenate([x, pad], axis=0)
        with self._lock:
            self._requests["predict"] += 1
        raw = self._batchers[entry.name].submit(x, timeout_ms=opts.timeout_ms)
        result = decode_outputs(entry, raw, opts)
        if n_real < entry.window:
            # The signal->zeros step at the padding boundary can fabricate
            # picks/detections inside samples the client never sent.
            _clip_picks(result, n_real, float(opts.sampling_rate))
        result["model"] = entry.name
        return result

    # ---------------------------------------------------------- annotate
    def annotate(
        self,
        data: Any,
        model: Optional[str] = None,
        options: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """A long (L >= window) record via sliding windows + stitching,
        reusing the pool's warm largest-bucket forward."""
        if self._draining:
            raise ShuttingDown("service is draining")
        entry = self.pool.get(model)
        if not entry.is_picker:
            raise BadRequest(
                f"model '{entry.name}' is not a picking model; /annotate "
                "needs (non|det, ppk, spk) outputs"
            )
        opts = PredictOptions.from_dict(options)
        record = parse_waveform(data, entry.in_channels)
        if record.shape[0] < entry.window:
            raise BadRequest(
                f"record length {record.shape[0]} < window {entry.window}; "
                "use POST /predict for single windows"
            )
        from seist_tpu.ops.stream import annotate as stream_annotate

        t0 = time.monotonic()
        lock = self._annotate_locks[entry.name]
        # One record at a time per model: annotate saturates the device by
        # itself; interleaving two would only thrash. The wait counts
        # against the request's own deadline.
        if not lock.acquire(timeout=opts.timeout_ms / 1000.0):
            raise DeadlineExceeded(
                f"/annotate queue wait exceeded {opts.timeout_ms:.0f} ms"
            )
        try:
            with self._lock:
                self._requests["annotate"] += 1
            picks = stream_annotate(
                entry.forward,
                record,
                window=entry.window,
                stride=opts.stride or None,
                batch_size=self.buckets[-1],
                sampling_rate=opts.sampling_rate,
                ppk_threshold=opts.ppk_threshold,
                spk_threshold=opts.spk_threshold,
                det_threshold=opts.det_threshold,
                min_peak_dist=opts.min_peak_dist,
                combine=opts.combine,
                max_events=opts.record_max_events or None,
                channel0=entry.channel0,
                jitted=True,
            )
        finally:
            lock.release()
        self.annotate_latency_ms.observe((time.monotonic() - t0) * 1000.0)
        fs = float(opts.sampling_rate)
        from seist_tpu.ops.stream import window_offsets

        n_windows = len(
            window_offsets(
                record.shape[0], entry.window, opts.stride or entry.window // 2
            )
        )
        with self._lock:
            self._annotate_windows += n_windows
        return {
            "model": entry.name,
            "task": "picking",
            "record_samples": int(record.shape[0]),
            "windows": int(n_windows),
            "ppk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["ppk"]
            ],
            "spk": [
                {"sample": int(i), "time_s": round(int(i) / fs, 6)}
                for i in picks["spk"]
            ],
            "det": [
                {"onset": int(a), "offset": int(b),
                 "onset_s": round(int(a) / fs, 6),
                 "offset_s": round(int(b) / fs, 6)}
                for a, b in picks["det"]
            ],
        }

    # ------------------------------------------------------ health/metrics
    def alive(self) -> bool:
        """Liveness: warm-up didn't fail and every batcher flush thread
        is still running. Neither condition can recover — the server
        watchdog exits non-zero on this so the orchestrator restarts the
        process instead of leaving a zombie that black-holes requests."""
        return self._warmup_error is None and all(
            b.healthy for b in self._batchers.values()
        )

    def ready(self) -> bool:
        """Readiness: alive, warm-compiled, and not draining."""
        return self.alive() and not self._warming and not self._draining

    def _state_str(self) -> str:
        if not self.alive():
            return "dead"
        if self._draining:
            return "draining"
        if self._warming:
            return "warming"
        return "ok"

    def healthz(self) -> Dict[str, Any]:
        return {
            "status": self._state_str(),
            "live": self.alive(),
            "ready": self.ready(),
            "models": self.pool.names(),
            "buckets": list(self.buckets),
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "warmup": self.pool.warmup_report,
        }

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            requests = dict(self._requests)
            annotate_windows = self._annotate_windows
        return {
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "requests": requests,
            "annotate": {
                "windows": annotate_windows,
                "latency_ms": self.annotate_latency_ms.summary(),
            },
            "models": {
                name: batcher.stats()
                for name, batcher in self._batchers.items()
            },
        }

    def _bus_metrics(self) -> Dict[str, Any]:
        """The bus-collector payload: everything in :meth:`metrics` except
        the per-model stats (batchers publish those themselves, labeled)."""
        m = self.metrics()
        m.pop("models", None)
        return m

    def metrics_prometheus(self) -> str:
        """Prometheus text exposition of the process bus — the serve
        process's scrape surface (``GET /metrics?format=prometheus``),
        same renderer as the train worker's --metrics-port."""
        from seist_tpu.obs.bus import BUS, render_prometheus

        return render_prometheus(BUS)

    # ----------------------------------------------------------- shutdown
    def begin_drain(self) -> None:
        """Flip to not-ready (new /predict //annotate get 503, readiness
        probe fails) without yet stopping the batchers — the signal
        handler calls this so in-flight work finishes while the load
        balancer routes away."""
        self._draining = True

    def shutdown(self, drain: bool = True) -> None:
        """Refuse new work, then (with ``drain``) serve what's queued."""
        self._draining = True
        for batcher in self._batchers.values():
            batcher.shutdown(drain=drain)
        # Mirror the batchers: a shut-down service must neither pin the
        # model pool via the bus's collector ref nor report its stale
        # request counters as live on a later scrape.
        from seist_tpu.obs.bus import BUS

        BUS.unregister_collector("serve", fn=self._bus_metrics)


def _clip_picks(result: Dict[str, Any], n_real: int, fs: float) -> None:
    """Drop decoded picking outputs that fall inside zero-padding (sample
    >= ``n_real``); detection intervals are clipped to the real extent."""
    if result.get("task") != "picking":
        return
    for kind in ("ppk", "spk"):
        if kind in result:
            result[kind] = [p for p in result[kind] if p["sample"] < n_real]
    if "det" in result:
        kept = []
        for d in result["det"]:
            if d["onset"] >= n_real:
                continue
            if d["offset"] >= n_real:
                d = dict(
                    d,
                    offset=n_real - 1,
                    offset_s=round((n_real - 1) / fs, 6),
                )
            kept.append(d)
        result["det"] = kept


def _normalize_trace(x: np.ndarray, norm_mode: str) -> np.ndarray:
    if norm_mode not in _NORM_MODES:
        raise BadRequest(
            f"norm_mode must be one of {_NORM_MODES}, got '{norm_mode}'"
        )
    from seist_tpu.data.preprocess import normalize

    # (L, C): time axis is 0.
    return np.asarray(normalize(x, norm_mode, axis=0), np.float32)


# ---------------------------------------------------------------- HTTP shim
class _Handler(BaseHTTPRequestHandler):
    server_version = "seist-serve/0.1"
    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> ServeService:
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, format: str, *args: Any) -> None:
        logger.debug(f"[serve] {self.address_string()} {format % args}")

    def _reply(self, status: int, payload: Dict[str, Any]) -> None:
        body = json_bytes(payload)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self.close_connection:
            # Tell the client, not just the socket: without the header an
            # HTTP/1.1 client assumes keep-alive and retries a dead conn.
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, text: str, ctype: str) -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            if self.path == "/healthz":
                # Combined report (back-compat); always 200 while the
                # process can answer at all.
                self._reply(200, self.service.healthz())
            elif self.path == "/healthz/live":
                live = self.service.alive()
                self._reply(
                    200 if live else 503,
                    {"status": "ok" if live else "dead"},
                )
            elif self.path == "/healthz/ready":
                ready = self.service.ready()
                self._reply(
                    200 if ready else 503,
                    {"status": self.service._state_str(), "ready": ready},
                )
            elif self.path.split("?", 1)[0] == "/metrics":
                # ?format=prometheus selects text exposition regardless
                # of other params/ordering (real scrapers append job
                # labels etc.); bare /metrics stays the back-compat JSON
                # (docs/OBSERVABILITY.md).
                from urllib.parse import parse_qs, urlparse

                query = parse_qs(urlparse(self.path).query)
                if "prometheus" in query.get("format", []):
                    self._reply_text(
                        200,
                        self.service.metrics_prometheus(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                else:
                    self._reply(200, self.service.metrics())
            else:
                self._reply(404, {"error": "not_found", "message": self.path})
        except Exception as e:  # noqa: BLE001
            self._reply(500, {"error": "internal", "message": repr(e)})

    def do_POST(self) -> None:  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_BODY_BYTES:
                # The unread body would desync this keep-alive connection
                # (its bytes would parse as the next request line) — close.
                self.close_connection = True
                self._reply(
                    413,
                    {"error": "too_large",
                     "message": f"body {length} > {MAX_BODY_BYTES} bytes"},
                )
                return
            body = parse_body(self.rfile.read(length))
            if self.path == "/predict":
                fn = self.service.predict
            elif self.path == "/annotate":
                fn = self.service.annotate
            else:
                self._reply(404, {"error": "not_found", "message": self.path})
                return
            result = fn(
                body.get("data"),
                model=body.get("model"),
                options=body.get("options"),
            )
            self._reply(200, result)
        except ServeError as e:
            self._reply(e.status, e.payload())
        except Exception as e:  # noqa: BLE001
            logger.warning(f"[serve] unhandled error: {e!r}")
            self._reply(500, {"error": "internal", "message": repr(e)})


class ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr: Tuple[str, int], service: ServeService):
        super().__init__(addr, _Handler)
        self.service = service


def start_http_server(
    service: ServeService, host: str = "127.0.0.1", port: int = 8080
) -> ServeHTTPServer:
    """Bind + serve on a daemon thread; returns the bound server (use
    ``server.server_address`` to discover an ephemeral port)."""
    server = ServeHTTPServer((host, port), service)
    thread = threading.Thread(
        target=server.serve_forever, name="serve-http", daemon=True
    )
    thread.start()
    return server


# ----------------------------------------------------------------- CLI
def get_serve_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="serve", description="seist_tpu online inference service"
    )
    ap.add_argument(
        "--model", action="append", default=[], metavar="NAME[=CKPT]",
        help="model to serve, repeatable; NAME alone serves fresh-init "
        "weights (smoke/testing)",
    )
    ap.add_argument("--model-name", default="", help="single-model shorthand")
    ap.add_argument("--checkpoint", default="", help="with --model-name")
    ap.add_argument("--window", type=int, default=8192)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=10.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument(
        "--buckets", default="",
        help="comma-separated batch buckets (default: powers of 2 up to "
        "--max-batch); largest must equal --max-batch",
    )
    ap.add_argument("--seed", type=int, default=0)
    return ap.parse_args(argv)


def parse_model_flags(args: argparse.Namespace) -> List[Tuple[str, str]]:
    entries: List[Tuple[str, str]] = []
    for spec in args.model:
        name, _, ckpt = spec.partition("=")
        entries.append((name, ckpt))
    if args.model_name:
        entries.append((args.model_name, args.checkpoint))
    if not entries:
        raise SystemExit("serve: need --model NAME[=CKPT] or --model-name")
    return entries


def watch_until_shutdown(
    service: ServeService,
    stop: "threading.Event",
    poll_s: float = 0.5,
) -> int:
    """Main-thread watchdog: block until ``stop`` (graceful shutdown) or
    a batcher flush thread dies. Returns the process exit code — 0 for a
    clean drain, 1 for a dead batcher. The non-zero exit is the point: a
    server whose flush thread died would otherwise sit silently while
    every request times out, and no orchestrator would restart it."""
    while not stop.is_set():
        if not service.alive():
            sick = [
                n for n, b in service._batchers.items() if not b.healthy
            ]
            reason = (
                f"batcher flush thread(s) died: {sick}"
                if sick
                else f"warm-up failed: {service._warmup_error!r}"
            )
            logger.warning(f"[serve] {reason}; exiting 1")
            return 1
        stop.wait(poll_s)
    return 0


def main(argv: Optional[List[str]] = None) -> None:
    from seist_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()
    args = get_serve_args(argv)
    entries = parse_model_flags(args)
    config = BatcherConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue,
        buckets=(
            tuple(int(b) for b in args.buckets.split(","))
            if args.buckets
            else None
        ),
    )
    pool = ModelPool(entries, window=args.window, seed=args.seed)
    # Async warm-up: the socket (and /healthz/ready, reporting 503
    # "warming") comes up immediately; orchestrators gate traffic on
    # readiness instead of timing out their liveness probe on the compile.
    service = ServeService(pool, config, warmup_async=True)
    server = start_http_server(service, args.host, args.port)
    host, port = server.server_address[:2]
    logger.info(
        f"[serve] listening on http://{host}:{port} "
        f"models={pool.names()} buckets={list(service.buckets)}"
    )

    import signal

    stop = threading.Event()

    # Containers stop with SIGTERM; flip to not-ready first so the load
    # balancer routes away, then drain what's queued.
    def _term(signum, frame):
        service.begin_drain()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    rc = watch_until_shutdown(service, stop)
    if rc == 0:
        logger.info("[serve] draining...")
        service.shutdown(drain=True)
        server.shutdown()
        logger.info("[serve] stopped")
    else:
        server.shutdown()
        service.shutdown(drain=False)
        logger.info("[serve] stopped (unhealthy)")
    if rc:
        raise SystemExit(rc)


if __name__ == "__main__":
    main()
