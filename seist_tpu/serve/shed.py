"""Adaptive admission control: priority tiers + queue-delay-based load
shedding, per replica.

The PR 1 overload story was a single blanket 429 once the bounded queue
filled — every client class treated alike, and by the time the queue is
full the requests inside it are already doomed to blow their deadlines.
Continuous seismic monitoring cannot afford that: a streaming-alert pick
request during an event matters more than a batch backfill request, and
the service must say so *before* the queue rots.

This module is the replica-side half of the fleet resilience plane
(docs/SERVING.md; the router in serve/router.py is the front-tier half):

* Requests carry a **priority tier** (``options.priority``):
  ``alert`` > ``interactive`` (default) > ``batch``
  (:data:`~seist_tpu.serve.protocol.PRIORITIES`).
* The overload signal is the micro-batcher's **estimated queue delay**
  (``MicroBatcher.queue_delay_ms``: head-of-line sojourn + queued flush
  waves x EWMA service time — the CoDel design, self-clocking and free
  of wall-clock/config guesswork).
* Each tier has a delay threshold; when the estimate exceeds it, that
  tier is **shed** with a 503 + ``Retry-After`` (protocol.Overloaded,
  code ``shed``) — distinct from the queue-full 429, which remains the
  last-ditch hard bounce for whatever is still admitted. Hysteresis
  (re-admit only below ``threshold * hysteresis``) keeps the decision
  from flapping at the boundary.
* Every decision is counted on the PR 6 metrics bus
  (``seist_serve_shed_*{model=,tier=}``) plus a live gauge of the
  current delay estimate and shed level.

One controller per model (each model has its own batcher, hence its own
queue delay); ``ServeService`` consults it at the top of ``predict``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from seist_tpu.serve.protocol import DEFAULT_PRIORITY, PRIORITIES, Overloaded


@dataclass(frozen=True)
class ShedConfig:
    """Per-tier queue-delay thresholds (ms). ``float('inf')`` = the tier
    is never policy-shed (it can still hit the 429 queue bound)."""

    #: shed ``batch`` backfill when the estimated delay exceeds this
    batch_delay_ms: float = 50.0
    #: shed ``interactive`` when it exceeds this
    interactive_delay_ms: float = 250.0
    #: ``alert`` is shed only above this (default: never — alerts ride
    #: the queue to the 429 bound; a missed alert is a missed event)
    alert_delay_ms: float = float("inf")
    #: re-admit a shed tier only once delay < threshold * hysteresis
    hysteresis: float = 0.5
    #: floor for the computed Retry-After (seconds)
    min_retry_after_s: float = 1.0

    def threshold_ms(self, tier: str) -> float:
        return {
            "alert": self.alert_delay_ms,
            "interactive": self.interactive_delay_ms,
            "batch": self.batch_delay_ms,
        }[tier]


@dataclass
class _TierState:
    shedding: bool = False
    admitted: int = 0
    shed: int = 0
    final_exempt: int = 0  # releasing requests admitted through a shed


class AdmissionController:
    """Tiered queue-delay admission gate for one model's batcher.

    ``admit(priority)`` either returns (request admitted; proceed to the
    batcher, which may still 429) or raises :class:`Overloaded` with a
    Retry-After derived from the current delay estimate. Thread-safe;
    the delay callable is read outside the lock (it locks the batcher
    itself)."""

    def __init__(
        self,
        delay_ms_fn: Callable[[], float],
        config: Optional[ShedConfig] = None,
        model: str = "default",
    ):
        self._delay_ms = delay_ms_fn
        self.config = config or ShedConfig()
        self.model = model
        self._lock = threading.Lock()
        self._tiers: Dict[str, _TierState] = {
            t: _TierState() for t in PRIORITIES
        }
        # Metrics-bus surface: scrape-time collector, one family with
        # model labels (the serve_batcher precedent — keyed by model so a
        # restarted service's controller replaces its predecessor).
        from seist_tpu.obs.bus import BUS

        self._collector_key = f"serve_shed:{model}"
        BUS.register_collector(
            self._collector_key, self.stats, name="serve_shed", model=model
        )

    # ------------------------------------------------------------- admit
    def admit(
        self, priority: str = DEFAULT_PRIORITY, final: bool = False
    ) -> None:
        """Admit or shed one request of tier ``priority``.

        Raises :class:`Overloaded` (503 + Retry-After) when the tier is
        shedding. The shed decision per tier is sticky (hysteresis): it
        flips on above ``threshold`` and off below ``threshold *
        hysteresis``, so one noisy estimate doesn't flap admission.

        ``final=True`` marks a request that RELEASES capacity (a
        stream's ``end=true`` close packet: one tail flush, then the
        station slot frees). Shedding those is counterproductive — the
        retry storm holds sessions open through the very overload the
        shedder is fighting — so finals update the tier's shed state but
        are always admitted."""
        if priority not in PRIORITIES:
            # Protocol validation rejects these before we're called;
            # guard against programmatic callers all the same.
            priority = DEFAULT_PRIORITY
        delay_ms = self._delay_ms()
        threshold = self.config.threshold_ms(priority)
        with self._lock:
            state = self._tiers[priority]
            if state.shedding:
                if delay_ms < threshold * self.config.hysteresis:
                    state.shedding = False
            elif delay_ms > threshold:
                state.shedding = True
            if state.shedding and final:
                state.final_exempt += 1
            elif state.shedding:
                state.shed += 1
                retry_after_s = max(
                    self.config.min_retry_after_s, 2.0 * delay_ms / 1e3
                )
                raise Overloaded(
                    f"tier '{priority}' shed: queue delay "
                    f"{delay_ms:.0f} ms > {threshold:.0f} ms budget "
                    f"(model '{self.model}')",
                    retry_after_s=retry_after_s,
                )
            state.admitted += 1

    # ------------------------------------------------------------- stats
    def shed_level(self) -> int:
        """Number of tiers currently shedding (0 = fully open; 3 = even
        alerts shed). The one-number overload gauge for dashboards."""
        with self._lock:
            return sum(1 for s in self._tiers.values() if s.shedding)

    def stats(self) -> Dict[str, Any]:
        delay_ms = self._delay_ms()
        with self._lock:
            return {
                "queue_delay_ms": round(delay_ms, 3),
                "level": sum(
                    1 for s in self._tiers.values() if s.shedding
                ),
                "tiers": {
                    t: {
                        "shedding": s.shedding,
                        "admitted": s.admitted,
                        "shed": s.shed,
                        "final_exempt": s.final_exempt,
                    }
                    for t, s in self._tiers.items()
                },
            }

    def close(self) -> None:
        """Unregister the bus collector (service shutdown); fn-guarded so
        a late close never tears down a successor's registration."""
        from seist_tpu.obs.bus import BUS

        BUS.unregister_collector(self._collector_key, fn=self.stats)
