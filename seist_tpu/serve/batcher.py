"""Micro-batcher: coalesce concurrent single-trace requests into bucketed
fixed-shape forwards.

The serving problem this solves (t5x/seqio-style compiled-program reuse +
pjit-paper device saturation, see ISSUE/PAPERS): many independent clients
each send one ``(window, C)`` trace; running one forward per request wastes
the accelerator (batch-1 forwards) and any fresh shape triggers an XLA
compile measured in seconds. So requests queue, and a single batcher
thread flushes when either

* ``max_batch`` requests are waiting (full batch), or
* the oldest request has waited ``max_delay_ms`` (latency bound), or
* the batcher is draining for shutdown.

Every flush pads the n collected traces up to the smallest *bucket*
``>= n`` (default: powers of two up to ``max_batch``) by repeating the
last trace, so every forward hits one of a handful of shapes that were
all compiled at warm-up — steady-state serving never compiles.

Backpressure: the queue is bounded (``max_queue``); a full queue rejects
immediately with :class:`~seist_tpu.serve.protocol.QueueFull` (the HTTP
layer's 429) rather than building an unbounded latency backlog. Each
request carries a deadline; requests that expire while queued are dropped
before the forward (no wasted compute) and raise
:class:`~seist_tpu.serve.protocol.DeadlineExceeded` in their caller.

The queue is *rank-ordered*, not FIFO: each request carries a rank
(serve layer: ``alert`` < ``interactive`` < ``batch``) and a flush takes
the lowest ranks first, FIFO within a rank. Without this, low-tier
requests admitted just before the shed controller trips would sit ahead
of every later alert — on a slow or contended box that backlog alone
blows the alert tier's latency SLO no matter how aggressive admission
shedding is. Starvation of low tiers under sustained overload is the
*intended* policy (those requests expire and should have been shed).

Thread model: callers (HTTP handler threads) block in :meth:`submit`;
one daemon worker owns the device. This is deliberate — JAX dispatch is
not free-threaded, and a single submission thread also serializes bucket
warm-up state. All metrics live behind the same lock as the queue.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seist_tpu.obs import trace as obs_trace
from seist_tpu.serve.protocol import (
    DeadlineExceeded,
    QueueFull,
    ServeError,
    ShuttingDown,
)
from seist_tpu.utils.meters import LatencyHistogram


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch`` (always including it): the
    classic shape-bucket ladder — at most ~2x padding waste, and only
    ``log2(max_batch)+1`` programs to compile at warm-up."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


@dataclass
class BatcherConfig:
    max_batch: int = 8
    max_delay_ms: float = 10.0
    max_queue: int = 64
    buckets: Optional[Sequence[int]] = None  # None = default_buckets

    def resolved_buckets(self) -> Tuple[int, ...]:
        if self.buckets is None:
            return default_buckets(self.max_batch)
        buckets = tuple(sorted(int(b) for b in self.buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bad buckets {self.buckets}")
        if buckets[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket {buckets[-1]} != max_batch {self.max_batch}"
            )
        return buckets


class _Pending:
    __slots__ = ("x", "enqueued_at", "deadline", "event", "result", "error",
                 "abandoned", "rank", "tasks", "trace")

    def __init__(
        self,
        x: np.ndarray,
        deadline: float,
        rank: int = 1,
        tasks: Optional[frozenset] = None,
        trace: Optional[Any] = None,
    ):
        self.x = x
        self.enqueued_at = time.monotonic()
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.abandoned = False  # caller gave up; skip at flush time
        self.rank = rank  # flush order: lower rank first, FIFO within
        self.tasks = tasks  # multi-task fan-out: heads this caller wants
        self.trace = trace  # obs.trace.RequestTrace (None = untraced)


class MicroBatcher:
    """See module docstring. ``forward`` maps a ``(B, ...)`` stacked batch
    (B always one of the buckets) to an array — or tuple of arrays — with
    leading dimension B; :meth:`submit` returns the caller's slice with a
    leading dimension of 1 (tuple outputs stay tuples)."""

    def __init__(
        self,
        forward: Callable[[np.ndarray], Any],
        config: Optional[BatcherConfig] = None,
        name: str = "default",
    ):
        self._forward = forward
        self.config = config or BatcherConfig()
        self.buckets = self.config.resolved_buckets()
        self.name = name
        self._queue: List[_Pending] = []
        self._inflight: List[_Pending] = []
        self._cond = threading.Condition()
        self._stopping = False
        # Set when the flush loop itself dies (not a per-request forward
        # error — those are caught in _run_batch). A dead flush thread
        # means every future submit would hang to its deadline; the server
        # watchdog (serve/server.py) polls `healthy` and exits non-zero.
        self._fatal: Optional[BaseException] = None
        # Counters (guarded by self._cond's lock):
        self._submitted = 0
        self._rejected = 0
        self._expired = 0
        self._completed = 0
        self._failed = 0
        self._forwards = 0
        self._batch_items = 0  # real traces forwarded
        self._batch_slots = 0  # bucket slots forwarded (incl. padding)
        self._flush_ewma_ms = 0.0  # EWMA of forward wall time per flush
        self.latency_ms = LatencyHistogram()
        # Publish on the process metrics bus (obs/bus.py): scrape-time
        # collector, so the stats stay single-sourced behind self._cond
        # and appear as seist_serve_batcher_*{model=...} in Prometheus
        # exposition (serve /metrics?format=prometheus, --metrics-port).
        # Keyed by model name ONLY: a fresh batcher replaces the one it
        # succeeds even when the old one was dropped without shutdown —
        # two registrations with identical labels would render duplicate
        # series, which Prometheus rejects for the whole scrape.
        from seist_tpu.obs.bus import BUS

        self._collector_key = f"serve_batcher:{name}"
        BUS.register_collector(
            self._collector_key, self.stats, name="serve_batcher", model=name
        )
        self._thread = threading.Thread(
            target=self._loop, name=f"batcher-{name}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- submit
    def submit(
        self,
        x: np.ndarray,
        timeout_ms: float = 5000.0,
        rank: int = 1,
        tasks: Optional[frozenset] = None,
        trace: Optional[Any] = None,
    ) -> Any:
        """Block until the trace's batch is served; returns the per-item
        output slice. Raises QueueFull / DeadlineExceeded / ShuttingDown.

        ``rank`` is the flush order under contention: lower ranks are
        taken first, FIFO within a rank (the serve layer maps priority
        tiers to ranks via ``protocol.PRIORITIES``). This is the queue
        half of the overload story — admission shedding (serve/shed.py)
        bounds how much low-tier work gets in, and rank ordering keeps
        whatever *was* admitted from standing ahead of an alert, so a
        high-tier request waits at most the in-flight flush plus its own
        tier's queue regardless of box speed or backlog.

        ``tasks`` (multi-task groups only) names the heads this caller
        wants. Requests batch by TRUNK INPUT SHAPE, not by task: a flush
        runs the shared trunk once and fans out to the UNION of its
        items' tasks — the forward is then called ``forward(batch,
        tasks)`` and must return ``{task: outputs}``; each caller's
        slice keeps every task in the union (decode picks its own).

        ``trace`` (obs/trace.RequestTrace) makes the queueing visible on
        the request's distributed trace: the flush thread records a
        ``queue_wait`` child (enqueue -> flush start, annotated with the
        flush ordinal / bucket / occupancy) and a shared ``forward``
        child carrying whatever serve/pool.py annotated on the flush
        scope (program key, AOT-hit, variant)."""
        t0 = time.monotonic()
        item = _Pending(
            np.asarray(x), deadline=t0 + timeout_ms / 1000.0, rank=rank,
            tasks=tasks, trace=trace,
        )
        with self._cond:
            if self._fatal is not None:
                raise ServeError(
                    f"batcher {self.name} flush thread died: "
                    f"{self._fatal!r}"
                )
            if self._stopping:
                raise ShuttingDown(f"batcher {self.name} is draining")
            if len(self._queue) >= self.config.max_queue:
                self._rejected += 1
                raise QueueFull(
                    f"batcher {self.name} queue full "
                    f"({self.config.max_queue} waiting)"
                )
            self._submitted += 1
            # Stable rank-ordered insert (scan from the tail: bursts are
            # overwhelmingly same-or-lower rank, so this is O(number of
            # lower-rank items behind), bounded by max_queue).
            pos = len(self._queue)
            while pos > 0 and self._queue[pos - 1].rank > item.rank:
                pos -= 1
            self._queue.insert(pos, item)
            self._cond.notify_all()
        if not item.event.wait(timeout=timeout_ms / 1000.0 + 0.05):
            # Decide success-vs-expired once, under the lock the worker
            # also counts under: either the result already landed (use it,
            # never counted expired) or we mark ourselves abandoned AND
            # expired atomically — the worker then skips the completed
            # credit, so every request lands in exactly one stats bucket.
            with self._cond:
                expired = not item.event.is_set()
                if expired:
                    item.abandoned = True
                    self._expired += 1
            if expired:
                raise DeadlineExceeded(
                    f"request not served within {timeout_ms:.0f} ms"
                )
        if item.error is not None:
            raise item.error
        self.latency_ms.observe((time.monotonic() - t0) * 1000.0)
        return item.result

    # ---------------------------------------------------------- worker
    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — record, fail fast
            with self._cond:
                self._fatal = e
                err = ServeError(f"batcher {self.name} flush thread died: {e!r}")
                # Fail everyone still waiting: the queued AND the
                # already-dequeued in-flight batch — their callers would
                # otherwise block to their full deadline on a thread that
                # no longer exists. Items whose event is already set got
                # a real result (or error) from _run_batch before the
                # crash; don't clobber it.
                for item in self._queue + self._inflight:
                    if not item.event.is_set():
                        item.error = err
                        item.event.set()
                self._queue.clear()
                self._inflight = []
            # A dead flush thread is a replica death sentence (the
            # watchdog exits 1); leave the forensic record the train
            # plane's death paths leave — no-op when no recorder is
            # installed (offline tools, bare batcher tests).
            from seist_tpu.obs import flight

            flight.dump_on_death(
                "batcher_flush_death", batcher=self.name, error=repr(e)
            )

    def _loop_inner(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._queue:
                        n = len(self._queue)
                        age = time.monotonic() - self._queue[0].enqueued_at
                        budget = self.config.max_delay_ms / 1000.0
                        if (
                            n >= self.config.max_batch
                            or age >= budget
                            or self._stopping
                        ):
                            break
                        self._cond.wait(budget - age)
                    elif self._stopping:
                        return
                    else:
                        # threadlint: disable=wait-no-timeout -- parked on
                        # an empty queue; every producer (submit) and
                        # shutdown() notifies under this same condition,
                        # and the thread is daemon so a dying process
                        # never waits on it.
                        self._cond.wait()
                take = min(len(self._queue), self.config.max_batch)
                pending = self._queue[:take]
                del self._queue[:take]
                self._inflight = pending
            self._run_batch(pending)
            with self._cond:
                self._inflight = []

    def _run_batch(self, pending: List[_Pending]) -> None:
        now = time.monotonic()
        live: List[_Pending] = []
        with self._cond:
            flush_id = self._forwards + 1
            for item in pending:
                if item.abandoned:
                    continue  # caller already raised DeadlineExceeded
                if item.deadline < now:
                    self._expired += 1
                    if item.trace is not None:
                        item.trace.add_child(
                            "queue_wait",
                            (now - item.enqueued_at) * 1e3,
                            expired=True,
                        )
                    item.error = DeadlineExceeded(
                        "expired while queued (server overloaded?)"
                    )
                    item.event.set()
                    continue
                live.append(item)
        if not live:
            return
        n = len(live)
        bucket = next(b for b in self.buckets if b >= n)
        batch = np.stack([item.x for item in live], axis=0)
        if bucket > n:  # pad by repeating the last trace: same warm shape
            batch = np.concatenate(
                [batch, np.repeat(batch[-1:], bucket - n, axis=0)], axis=0
            )
        # Multi-task fan-out: the flush serves the UNION of its items'
        # requested heads (trunk once; an extra head is ~10% of a trunk,
        # re-running the trunk per distinct task subset would cost 10x).
        task_sets = [item.tasks for item in live if item.tasks is not None]
        union: Optional[frozenset] = (
            frozenset().union(*task_sets) if task_sets else None
        )
        t_fwd0 = time.monotonic()
        # Queue-wait becomes a trace span per member: enqueue -> flush
        # start, annotated with which flush wave served it and how full
        # the bucket ran.
        for item in live:
            if item.trace is not None:
                item.trace.add_child(
                    "queue_wait",
                    (t_fwd0 - item.enqueued_at) * 1e3,
                    flush=flush_id,
                    bucket=bucket,
                    batch_n=n,
                )
        try:
            # The flush scope carries the member traces through the
            # forward so pool programs can annotate the shared span
            # (program key / AOT-hit / variant) without plumbing.
            with obs_trace.flush_scope(
                [item.trace for item in live]
            ) as scope:
                out = (
                    self._forward(batch)
                    if union is None
                    else self._forward(batch, union)
                )
        except Exception as e:  # noqa: BLE001 — must not kill the worker
            err = e if isinstance(e, ServeError) else ServeError(
                f"forward failed: {e!r}"
            )
            for item in live:
                if item.trace is not None:
                    item.trace.add_child(
                        "forward",
                        (time.monotonic() - t_fwd0) * 1e3,
                        flush=flush_id,
                        error=type(e).__name__,
                    )
            with self._cond:  # same atomicity argument as the success path
                for item in live:
                    item.error = err
                    if not item.abandoned:
                        self._failed += 1
                    item.event.set()
            return
        # Materialize device output ONCE per flush; per-item slicing below
        # then works on host arrays (np.asarray on ndarray is a no-op) —
        # without this, every item would pull the full batch across the
        # device boundary again. Multi-task forwards return {task: out}.
        out = _materialize(out)
        flush_ms = (time.monotonic() - t_fwd0) * 1e3
        for item in live:
            if item.trace is not None:
                item.trace.add_child(
                    "forward",
                    flush_ms,
                    flush=flush_id,
                    bucket=bucket,
                    occupancy=round(n / bucket, 3),
                    **scope.annotations,
                )
        with self._cond:
            self._forwards += 1
            self._batch_items += n
            self._batch_slots += bucket
            # Service-time EWMA feeding queue_delay_ms(); first flush seeds
            # it so one warm compile doesn't poison the estimate for long.
            self._flush_ewma_ms = (
                flush_ms
                if self._flush_ewma_ms == 0.0
                else 0.8 * self._flush_ewma_ms + 0.2 * flush_ms
            )
            # Count + event.set under the lock so each request is credited
            # exactly once: a caller timing out DURING the forward holds
            # this lock to mark itself abandoned/expired, and its lost-race
            # check reads the event under it too. Without the atomicity a
            # request could be counted both expired and completed,
            # breaking submitted == completed+expired+rejected+failed.
            for i, item in enumerate(live):
                item.result = _slice_outputs(out, i)
                if not item.abandoned:
                    self._completed += 1
                item.event.set()

    # ----------------------------------------------------- overload signal
    def queue_delay_ms(self) -> float:
        """Estimated queueing delay a newly admitted request would see:
        head-of-line sojourn time (the CoDel overload signal — under
        sustained overload it grows without bound, under transient bursts
        it self-clears) plus the flush waves already queued ahead priced
        at the EWMA service time. serve/shed.py sheds low tiers on this;
        an empty queue reads 0 (a lone request waits only max_delay_ms,
        which is policy, not overload)."""
        with self._cond:
            if not self._queue:
                return 0.0
            head_age_ms = (
                time.monotonic() - self._queue[0].enqueued_at
            ) * 1e3
            waves = -(-len(self._queue) // self.config.max_batch)
            return head_age_ms + waves * self._flush_ewma_ms

    # ---------------------------------------------------------- control
    def shutdown(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop accepting work; with ``drain`` the already-queued requests
        are still served (graceful), otherwise they fail ShuttingDown."""
        with self._cond:
            self._stopping = True
            if not drain:
                for item in self._queue:
                    item.error = ShuttingDown("batcher shut down")
                    item.event.set()
                self._queue.clear()
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)
        from seist_tpu.obs.bus import BUS

        # fn-guarded: if a successor batcher already took this key, the
        # old instance's shutdown must not unregister it.
        BUS.unregister_collector(self._collector_key, fn=self.stats)

    @property
    def healthy(self) -> bool:
        """False once the flush thread has died (fatal error or silent
        thread exit) — the liveness signal for the server watchdog."""
        with self._cond:
            if self._fatal is not None:
                return False
            return self._stopping or self._thread.is_alive()

    def stats(self) -> Dict[str, Any]:
        with self._cond:  # Condition wraps an RLock: `healthy` can re-enter
            slots = self._batch_slots
            return {
                "queue_depth": len(self._queue),
                "queue_delay_ms": round(self.queue_delay_ms(), 3),
                "healthy": self.healthy,
                "submitted": self._submitted,
                "completed": self._completed,
                "rejected": self._rejected,
                "expired": self._expired,
                "failed": self._failed,
                "forwards": self._forwards,
                "batch_fill_ratio": (
                    self._batch_items / slots if slots else 0.0
                ),
                "buckets": list(self.buckets),
                "latency_ms": self.latency_ms.summary(),
            }


def _materialize(out: Any) -> Any:
    """Device -> host, preserving structure (array, tuple/list of arrays,
    or a multi-task ``{task: ...}`` dict thereof)."""
    if isinstance(out, dict):
        return {k: _materialize(v) for k, v in out.items()}
    if isinstance(out, (tuple, list)):
        return type(out)(np.asarray(o) for o in out)
    return np.asarray(out)


def _slice_outputs(out: Any, i: int) -> Any:
    """Per-item slice (keeping a leading dim of 1) of an array, a
    tuple/list of arrays, or a multi-task ``{task: ...}`` dict — mirrors
    model outputs: dpk heads return one (B, L, 3) array, ditingmotion
    returns a tuple of two (B, classes), a group fan-out returns a dict
    of per-task outputs."""
    if isinstance(out, dict):
        return {k: _slice_outputs(v, i) for k, v in out.items()}
    if isinstance(out, (tuple, list)):
        return type(out)(np.asarray(o)[i : i + 1] for o in out)
    return np.asarray(out)[i : i + 1]
