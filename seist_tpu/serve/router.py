"""Front-tier replica router: health-checked registry, circuit breaking,
bounded retries, hedged requests.

The PR 1 service is one process; this module makes N of them a fleet.
A thin, model-free HTTP tier (stdlib only, no jax import — it runs in
the supervisor or on a separate box) forwards ``POST /predict`` and
``POST /annotate`` to replica processes and owns the reliability story:

* :class:`ReplicaRegistry` — the routable set. A background prober
  drives it off each replica's ``/healthz/ready`` (the PR 2 live/ready
  split): a draining or still-warming replica leaves rotation within one
  probe interval, a restarted one re-enters the same way.
  ``tools/supervise_fleet.py`` also rolls it explicitly over the
  ``POST /router/register`` / ``/router/deregister`` admin endpoints.
* :class:`CircuitBreaker`, per replica — the *fast* path around failure.
  Health probes need seconds and cannot see the worst failure mode at
  all: a black-holed replica that accepts connections (and answers
  probes) but never answers requests. The breaker sees every request
  outcome: consecutive failures (connection errors, per-attempt
  timeouts, 500s) or slow successes past ``latency_trip_ms`` OPEN the
  circuit; after a cooldown one HALF-OPEN probe request is let through;
  success CLOSEs, failure re-opens with doubled cooldown.
* **Bounded retries** — a failed attempt is retried on a *different*
  replica while the per-request retry budget (``retries``) and the
  client's own deadline allow. Replica-crash failures (SIGKILL mid
  flight) become invisible to well-formed clients; shed responses
  (503 ``shed``) are deliberately NOT retried — under fleet-wide
  overload a retry storm is fuel on the fire, so the shed verdict and
  its Retry-After pass through.
* **Hedged requests** (``hedge_ms`` > 0) — tail-latency insurance: if
  the chosen replica hasn't answered within the hedge delay, a second
  attempt races it on another replica and the first acceptable answer
  wins (arXiv:2605.25645's p99-under-SLO serving bar is exactly what
  this buys).

Error classification (drives retry + breaker):

    =====================  ========  =======  ==================
    outcome                breaker   retried  passed to client
    =====================  ========  =======  ==================
    connect/read timeout   failure   yes      504 if budget gone
    connection refused     failure   yes      502 if budget gone
    HTTP 500               failure   yes      after budget
    HTTP 429 queue_full    success   yes      after budget
    HTTP 503 shutting_down success   yes      after budget
    HTTP 503 shed          success   NO       immediately
    HTTP 504 deadline      success   NO       immediately
    HTTP 2xx/4xx           success   NO       immediately
    =====================  ========  =======  ==================

Counters land on the PR 6 metrics bus (``seist_router_*``), scraped from
the router's own ``GET /metrics``.

**Streaming (``POST /stream``) routes differently.** A stream packet is
not stateless: the replica holds the station's session (ring buffer,
picker cursors), so round-robin would shatter every session across the
fleet. :class:`StationAffinity` pins each station to one replica by
rendezvous hash over the *currently routable* set — deterministic (every
router instance computes the same placement, no coordination state),
minimally disruptive (a replica leaving re-homes only ITS stations;
survivors keep theirs). When a replica dies (breaker open, probe-down,
``mark_down``), the next packet's rendezvous simply lands on the
station's highest-ranked survivor, which restores the session from the
shared journal (seist_tpu/stream/journal.py) or re-warms through the
gap — ``seist_stream_rehome_total`` counts each adoption. Stream packets
are never hedged or shadow-mirrored: duplicating a stateful packet to a
second replica would fork the session.
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import re
import socket
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Dict, List, Optional, Set, Tuple

from seist_tpu.obs import trace as obs_trace
from seist_tpu.serve.canary import (
    CanaryBudget,
    CanaryController,
    ShadowMirror,
    decision_diff,
    serves_version,
)
from seist_tpu.utils.logger import logger

# Breaker states (also the value of the router_breaker_state gauge).
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-replica request-outcome circuit breaker.

    CLOSED —(``failures_to_open`` consecutive failures or
    too-slow successes)→ OPEN —(cooldown elapses; next ``allow`` grants
    exactly one probe)→ HALF_OPEN —(probe success)→ CLOSED, or —(probe
    failure)→ OPEN with the cooldown doubled (capped). Thread-safe; the
    clock is injectable for tests."""

    def __init__(
        self,
        failures_to_open: int = 3,
        cooldown_s: float = 2.0,
        max_cooldown_s: float = 30.0,
        latency_trip_ms: float = float("inf"),
        probe_timeout_s: float = 60.0,
        clock=time.monotonic,
    ):
        self.failures_to_open = max(1, int(failures_to_open))
        self.base_cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        self.latency_trip_ms = float(latency_trip_ms)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._cooldown_s = self.base_cooldown_s
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self._opens = 0  # lifetime open transitions (stats)

    # ------------------------------------------------------------ decisions
    def allow(self) -> bool:
        """May a request be sent now? In OPEN, the first call after the
        cooldown flips to HALF_OPEN and grants itself the single probe;
        callers that get False must route elsewhere."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self._cooldown_s:
                    self._state = HALF_OPEN
                    self._half_open_at = self._clock()
                    return True  # this caller IS the half-open probe
                return False
            # HALF_OPEN: probe already in flight — unless its outcome was
            # lost (attempt thread outliving every drain window, e.g. a
            # replica trickling bytes so each socket op resets the per-op
            # timeout). Without this escape a lost probe wedges the
            # breaker HALF_OPEN forever and the replica becomes
            # permanently unroutable; re-grant the probe slot instead.
            if self._clock() - self._half_open_at >= self.probe_timeout_s:
                self._half_open_at = self._clock()
                return True
            return False

    def record_success(self, latency_ms: float = 0.0) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                if latency_ms > self.latency_trip_ms:
                    # The probe "succeeded" but is still slower than the
                    # trip latency: the replica is still sick. Closing
                    # here would flood traffic back and reset the
                    # cooldown — keep it OPEN with escalation instead.
                    self._open_locked(escalate=True)
                else:
                    # Probe came back healthy: the replica recovered.
                    self._close_locked()
                return
            if latency_ms > self.latency_trip_ms:
                # A "success" slower than the trip latency is the
                # wedged-but-not-dead signature; count it like a failure
                # so a latency-sick replica opens too.
                self._failure_locked()
            else:
                self._consecutive = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # Probe failed: back to OPEN, longer cooldown.
                self._open_locked(escalate=True)
                return
            self._failure_locked()

    # ------------------------------------------------------------ internals
    def _failure_locked(self) -> None:
        self._consecutive += 1
        if self._state == CLOSED and self._consecutive >= self.failures_to_open:
            self._open_locked(escalate=False)

    def _open_locked(self, escalate: bool) -> None:
        if escalate:
            self._cooldown_s = min(self._cooldown_s * 2.0, self.max_cooldown_s)
        self._state = OPEN
        self._opened_at = self._clock()
        self._opens += 1

    def _close_locked(self) -> None:
        self._state = CLOSED
        self._consecutive = 0
        self._cooldown_s = self.base_cooldown_s

    # --------------------------------------------------------------- stats
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "cooldown_s": self._cooldown_s,
                "opens": self._opens,
            }


@dataclass
class RouterConfig:
    #: additional attempts after the first (per request)
    retries: int = 2
    #: per-attempt cap (seconds) — ALSO the black-hole detection time:
    #: an accepted-but-never-answered request fails after this long and
    #: feeds the breaker, so keep it a small multiple of honest p99
    request_timeout_s: float = 10.0
    #: duplicate a request onto a second replica after this long without
    #: an answer (0 = hedging off)
    hedge_ms: float = 0.0
    #: /healthz/ready probe cadence + timeout
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    #: probe failures before a replica leaves rotation
    probe_fails_down: int = 2
    #: breaker knobs (per replica)
    breaker_failures: int = 3
    breaker_cooldown_s: float = 2.0
    breaker_max_cooldown_s: float = 30.0
    breaker_latency_trip_ms: float = float("inf")


class Replica:
    """One registry entry: probe state + breaker + counters."""

    def __init__(self, url: str, config: RouterConfig):
        self.url = url.rstrip("/")
        self.breaker = CircuitBreaker(
            failures_to_open=config.breaker_failures,
            cooldown_s=config.breaker_cooldown_s,
            max_cooldown_s=config.breaker_max_cooldown_s,
            latency_trip_ms=config.breaker_latency_trip_ms,
            # A probe attempt that hasn't settled within a couple of
            # request timeouts is presumed lost (see allow()).
            probe_timeout_s=2.0 * config.request_timeout_s + 5.0,
        )
        # Optimistic start: a just-registered replica is routable until
        # the first probe says otherwise — the breaker catches a dead one
        # within failures_to_open requests, while a pessimistic start
        # would black out a healthy fleet for one probe interval.
        self.probe_ready = True
        self.probe_state = "unprobed"
        self.probe_fails = 0
        #: {model: served version}, learned from /healthz/ready payloads
        #: — the canary/rollout cohort discriminator. {} until probed.
        self.versions: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self.routed = 0
        self.failures = 0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            routed, failures = self.routed, self.failures
        return {
            "url": self.url,
            "ready": self.probe_ready,
            "probe_state": self.probe_state,
            "versions": dict(self.versions),
            "breaker": self.breaker.stats(),
            "routed": routed,
            "failures": failures,
        }

    def count(self, failure: bool) -> None:
        with self._lock:
            self.routed += 1
            if failure:
                self.failures += 1


class ReplicaRegistry:
    """The routable replica set; thread-safe. Pick order is round-robin
    over probe-ready replicas whose breaker admits traffic."""

    def __init__(self, config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig()
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._rr = 0

    def add(self, url: str) -> Replica:
        url = url.rstrip("/")
        with self._lock:
            replica = self._replicas.get(url)
            if replica is None:
                replica = Replica(url, self.config)
                self._replicas[url] = replica
                logger.info(f"[router] registered replica {url}")
            return replica

    def remove(self, url: str) -> bool:
        url = url.rstrip("/")
        with self._lock:
            gone = self._replicas.pop(url, None)
        if gone is not None:
            logger.info(f"[router] deregistered replica {url}")
        return gone is not None

    def mark_down(self, url: str, reason: str = "") -> None:
        """Immediately pull a replica from rotation (the fleet supervisor
        calls this the moment it reaps the process — faster than waiting
        out a probe interval)."""
        with self._lock:
            replica = self._replicas.get(url.rstrip("/"))
        if replica is not None:
            replica.probe_ready = False
            replica.probe_state = f"down({reason})" if reason else "down"

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def pick(
        self,
        exclude: Set[str] = frozenset(),
        versions_pred=None,
    ) -> Optional[Replica]:
        """Round-robin over ready replicas not in ``exclude`` whose
        breaker admits the request (``allow`` may consume the single
        half-open probe slot, so it is asked last, only for the
        candidate actually about to be used). ``versions_pred`` (a
        predicate over the replica's probed ``{model: version}``)
        restricts the pick to one rollout cohort — the canary/shadow
        routing hook."""
        with self._lock:
            candidates = [
                r
                for r in self._replicas.values()
                if r.probe_ready and r.url not in exclude
                and (versions_pred is None or versions_pred(r.versions))
            ]
            if not candidates:
                return None
            start = self._rr % len(candidates)
            self._rr += 1
        for i in range(len(candidates)):
            replica = candidates[(start + i) % len(candidates)]
            if replica.breaker.allow():
                return replica
        return None

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.probe_ready)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [r.snapshot() for r in self.replicas()]


class StationAffinity:
    """Rendezvous-hash station -> replica placement (for ``/stream``).

    Stateless where it can be: the hash ranks every (station, replica)
    pair deterministically, so placement is a pure function of the
    routable set — no placement table to replicate, no rebalance storm
    when a replica bounces. The only state kept is the last observed
    home per station, purely for *accounting*: when a packet lands on a
    different replica than its predecessor, that is a re-home (failover
    or fleet change) and ``seist_stream_rehome_total`` counts it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._homes: Dict[str, str] = {}
        self.rehomes = 0

    @staticmethod
    def score(station_id: str, url: str) -> int:
        """Deterministic rendezvous weight (highest wins)."""
        digest = hashlib.sha1(f"{station_id}|{url}".encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def rank(self, station_id: str, urls) -> List[str]:
        """Replica urls best-first for ``station_id`` (ties by url)."""
        return sorted(
            urls, key=lambda u: (-self.score(station_id, u), u)
        )

    def note(self, station_id: str, url: str) -> Optional[str]:
        """Record that ``station_id``'s packet was answered by ``url``;
        returns the PREVIOUS home iff it changed (a re-home)."""
        with self._lock:
            prev = self._homes.get(station_id)
            self._homes[station_id] = url
            if prev is not None and prev != url:
                self.rehomes += 1
                return prev
        return None

    def snapshot(self) -> Dict[str, Any]:
        """Placement summary published under ``/router/replicas`` — the
        chaos lane reads ``by_replica`` to find the station-heavy
        replica worth killing."""
        with self._lock:
            by_replica: Dict[str, int] = {}
            for url in self._homes.values():
                by_replica[url] = by_replica.get(url, 0) + 1
            return {
                "stations": len(self._homes),
                "rehomes": self.rehomes,
                "by_replica": by_replica,
            }


# --------------------------------------------------------------- outcomes
class _Outcome:
    """One attempt's result. ``status=0`` means a network-level failure
    (no HTTP response): ``error`` holds the reason."""

    __slots__ = ("status", "headers", "body", "error", "latency_ms")

    def __init__(
        self,
        status: int,
        headers: Dict[str, str],
        body: bytes,
        error: str = "",
        latency_ms: float = 0.0,
    ):
        self.status = status
        self.headers = headers
        self.body = body
        self.error = error
        self.latency_ms = latency_ms

    @property
    def is_net_error(self) -> bool:
        return self.status == 0

    def error_code(self) -> str:
        """The serve error taxonomy code from a JSON error body (the
        'shed' vs 'shutting_down' discriminator for 503s)."""
        if not self.body:
            return ""
        try:
            return str(json.loads(self.body.decode()).get("error", ""))
        except (ValueError, UnicodeDecodeError):
            return ""


def _classify(outcome: _Outcome) -> Tuple[bool, bool]:
    """-> (breaker_failure, retryable). See the module-docstring table."""
    if outcome.is_net_error:
        return True, True
    s = outcome.status
    if s >= 500 and s not in (503, 504):
        return True, True
    if s == 429:
        return False, True
    if s == 503:
        # 'shed' = fleet overload policy verdict: retrying elsewhere
        # amplifies the overload that caused it; pass it through.
        return False, outcome.error_code() != "shed"
    return False, False  # 2xx, 4xx, 504


def _classify_label(outcome: _Outcome) -> str:
    """Human-readable classification for the attempt's trace span —
    the module-docstring table's row name."""
    if outcome.is_net_error:
        return "net_error"
    failure, retryable = _classify(outcome)
    if outcome.status == 503 and outcome.error_code() == "shed":
        return "shed_not_retried"
    if failure:
        return "server_error"
    if retryable:
        return "backpressure_retryable"
    return "ok" if outcome.status < 400 else "relayed"


class Router:
    """Transport-free routing core (the HTTP shim below is ~50 lines):
    ``forward()`` runs the pick → attempt → classify → retry/hedge loop
    and returns ``(status, headers, body)`` ready to relay."""

    def __init__(
        self,
        registry: Optional[ReplicaRegistry] = None,
        config: Optional[RouterConfig] = None,
        bus=None,
    ):
        self.config = config or RouterConfig()
        self.registry = registry or ReplicaRegistry(self.config)
        if bus is None:
            from seist_tpu.obs.bus import BUS as bus
        self._bus = bus
        # Live-rollout traffic shifting (serve/canary.py): weighted
        # version-aware canary with auto-rollback, and shadow mirroring
        # of sampled requests to the candidate cohort.
        self.canary = CanaryController()
        self.shadow = ShadowMirror()
        # One-shot handoff: set by the (possibly drain-thread) settle
        # that trips the rollback, consumed by the next forward() so the
        # event always lands on a trace. GIL-atomic bool store.
        self._rollback_to_flag = False
        # Bounds concurrent shadow-mirror threads: a slow/black-holed
        # candidate must not accumulate one blocked thread per mirrored
        # request (overflow is dropped and counted skipped_busy).
        self._mirror_slots = threading.Semaphore(8)
        # Station -> replica placement for the stateful /stream path.
        self.affinity = StationAffinity()
        self._prober: Optional[threading.Thread] = None
        self._stop = threading.Event()
        bus.register_collector("router", self._collect)

    # ------------------------------------------------------------- probing
    def start_prober(self) -> None:
        """Start the background ``/healthz/ready`` prober (idempotent)."""
        if self._prober is not None and self._prober.is_alive():
            return
        self._stop.clear()
        self._prober = threading.Thread(
            target=self._probe_loop, name="router-prober", daemon=True
        )
        self._prober.start()

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
        self._bus.unregister_collector("router", fn=self._collect)

    def _probe_loop(self) -> None:
        # A dead prober freezes the routable set silently: drained
        # replicas would keep taking traffic and restarted ones never
        # re-enter. Survive any per-cycle surprise, and if the loop
        # machinery itself dies, say so loudly before the thread goes
        # (threadlint thread-target-raises).
        try:
            while not self._stop.is_set():
                try:
                    for replica in self.registry.replicas():
                        self._probe_one(replica)
                # a single bad probe cycle must not end probing forever
                except Exception as e:  # noqa: BLE001
                    logger.warning(f"[router] probe cycle failed: {e!r}")
                self._stop.wait(self.config.probe_interval_s)
        except BaseException:
            logger.exception(
                "[router] prober thread died — the routable set is frozen "
                "until the router restarts"
            )
            raise

    def _probe_one(self, replica: Replica) -> None:
        try:
            status, _, body = _http_request(
                replica.url,
                "GET",
                "/healthz/ready",
                timeout_s=self.config.probe_timeout_s,
            )
            replica.probe_fails = 0
            try:
                payload = json.loads(body.decode())
            except (ValueError, UnicodeDecodeError):
                payload = {}
            if not isinstance(payload, dict):
                payload = {}
            versions = payload.get("versions")
            if isinstance(versions, dict):
                # Served model versions ride the ready probe (serve
                # handler) — the canary cohort + rolling-restart
                # convergence signal, refreshed every probe interval.
                replica.versions = versions
            if status == 200:
                replica.probe_ready = True
                replica.probe_state = "ok"
            else:
                replica.probe_ready = False
                replica.probe_state = str(
                    payload.get("status", "not_ready")
                )
        except (OSError, http.client.HTTPException) as e:
            # Connection refused/reset/timeout/half-closed: the process
            # is likely gone. Two strikes before leaving rotation — one
            # lost probe packet must not drain a healthy replica.
            replica.probe_fails += 1
            if replica.probe_fails >= self.config.probe_fails_down:
                replica.probe_ready = False
                replica.probe_state = f"unreachable({type(e).__name__})"

    # ------------------------------------------------------------ forwarding
    def forward(
        self, path: str, body: bytes, traceparent: Optional[str] = None
    ) -> Tuple[int, Dict[str, str], bytes]:
        """Route one inference request; returns (status, headers, body).

        ``traceparent`` continues the client's distributed trace (the
        router mints one when the client didn't — it is the fleet edge):
        every attempt becomes a span in the router's trace ring
        (replica, breaker state, classification), retries/hedges flag
        the trace for tail retention, and the response carries the
        router's ``Server-Timing`` total plus the ``traceparent`` echo."""
        rt = obs_trace.RequestTrace(
            traceparent, name=f"router:{path}", process="router"
        )
        if path == "/stream":
            status, headers, payload = self._forward_stream(path, body, rt)
        else:
            status, headers, payload = self._forward_routed(path, body, rt)
        if self._rollback_to_flag:
            # The canary auto-rollback fired during this request's
            # routing: flag its trace (tail-retained) so the event is
            # findable from /traces, not just the bus counter.
            self._rollback_to_flag = False
            rt.flag("canary_rollback")
        if path != "/stream":
            # Never mirror a stream packet: a shadow copy would open a
            # phantom session on the candidate and fork station state.
            self._maybe_mirror(path, body, status, payload, rt.trace_id)
        total_ms = rt.finish(status)
        headers = dict(headers)
        upstream_timing = headers.pop("Server-Timing", None)
        timing = f"router;dur={total_ms:.1f}"
        headers["Server-Timing"] = (
            f"{timing}, {upstream_timing}" if upstream_timing else timing
        )
        headers[obs_trace.TRACEPARENT_HEADER] = rt.traceparent
        return status, headers, payload

    def _forward_routed(
        self, path: str, body: bytes, rt: obs_trace.RequestTrace
    ) -> Tuple[int, Dict[str, str], bytes]:
        """The pick -> attempt -> classify -> retry/hedge loop."""
        self._bus.counter("router_requests", path=path.lstrip("/")).inc()
        deadline = time.monotonic() + self._budget_s(body)
        tried: Set[str] = set()
        attempts_left = 1 + max(0, int(self.config.retries))
        last: Optional[_Outcome] = None
        while attempts_left > 0 and time.monotonic() < deadline:
            replica = self._pick(tried, first_attempt=not tried)
            if replica is None and tried:
                # Every replica tried once; a retry may reuse one (the
                # failure could have been transient) as long as its
                # breaker still admits traffic.
                replica = self._pick(frozenset(), first_attempt=False)
            if replica is None:
                break
            attempts_left -= 1
            if tried:  # anything after the first attempt is a retry
                self._bus.counter("router_retries").inc()
                rt.flag("retried")
            tried.add(replica.url)
            if self.config.hedge_ms > 0:
                outcome, replica, attempts_left, pre_settled = (
                    self._attempt_hedged(
                        replica, path, body, deadline, tried,
                        attempts_left, rt,
                    )
                )
            else:
                outcome = self._attempt(replica, path, body, deadline,
                                        rt=rt)
                pre_settled = False
            if pre_settled:
                # The hedged path already fed this outcome to its
                # replica's breaker; settling again would double-count.
                _, retryable = _classify(outcome)
            else:
                _, retryable = self._settle(replica, outcome)
            if not retryable:
                if (
                    outcome.status == 503
                    and outcome.error_code() == "shed"
                ):
                    # A relayed shed verdict is deliberate policy, not a
                    # router failure — its own retention flag.
                    rt.flag("shed")
                return self._relay(outcome)
            last = outcome
        if last is not None:
            return self._relay(last)
        self._bus.counter("router_no_replica").inc()
        rt.annotate(no_replica=True)
        return (
            503,
            {},
            json.dumps(
                {"error": "no_replica",
                 "message": "no routable replica in the registry"}
            ).encode(),
        )

    # --------------------------------------------------- stream affinity
    # Routing heuristic only (the replica re-validates): pull station.id
    # out of the raw packet without JSON-decoding the waveform body —
    # same contract as _budget_s. The station object is flat (protocol
    # parse_station fields), so a brace-free inner match suffices.
    _STATION_OBJ_RE = re.compile(rb'"station"\s*:\s*\{([^{}]*)\}')
    _STATION_ID_RE = re.compile(rb'"id"\s*:\s*"((?:[^"\\]|\\.)*)"')

    @classmethod
    def _station_id(cls, body: bytes) -> Optional[str]:
        m = cls._STATION_OBJ_RE.search(body)
        if m is None:
            return None
        m2 = cls._STATION_ID_RE.search(m.group(1))
        if m2 is None:
            return None
        try:
            # json.loads on the quoted token resolves \-escapes exactly
            # the way the replica's real parser will.
            sid = json.loads((b'"' + m2.group(1) + b'"').decode())
        except (ValueError, UnicodeDecodeError):
            return None
        return str(sid) or None

    def _pick_station(
        self, station_id: str, tried: Set[str]
    ) -> Optional[Replica]:
        """Rendezvous pick: the station's highest-ranked routable
        replica whose breaker admits the request. ``allow()`` is asked
        in rank order only until one admits (it may consume the single
        half-open probe slot, so never poll it speculatively). Canary
        cohorts are deliberately ignored — a session cannot be split
        across versions mid-record."""
        replicas = {
            r.url: r
            for r in self.registry.replicas()
            if r.probe_ready and r.url not in tried
        }
        for url in self.affinity.rank(station_id, replicas):
            if replicas[url].breaker.allow():
                return replicas[url]
        return None

    def _forward_stream(
        self, path: str, body: bytes, rt: obs_trace.RequestTrace
    ) -> Tuple[int, Dict[str, str], bytes]:
        """Affinity-routed /stream: pick by rendezvous hash, retry down
        the station's rank order (the failover re-home), never hedge."""
        self._bus.counter("router_requests", path="stream").inc()
        sid = self._station_id(body)
        if sid is None:
            # No parsable station id: fall back to the stateless loop —
            # the replica will answer 400 with the protocol's message.
            return self._forward_routed(path, body, rt)
        deadline = time.monotonic() + self._budget_s(body)
        tried: Set[str] = set()
        attempts_left = 1 + max(0, int(self.config.retries))
        last: Optional[_Outcome] = None
        while attempts_left > 0 and time.monotonic() < deadline:
            replica = self._pick_station(sid, tried)
            if replica is None and tried:
                replica = self._pick_station(sid, frozenset())
            if replica is None:
                break
            attempts_left -= 1
            if tried:
                self._bus.counter("router_retries").inc()
                rt.flag("retried")
            tried.add(replica.url)
            outcome = self._attempt(replica, path, body, deadline, rt=rt)
            _, retryable = self._settle(replica, outcome)
            if not retryable:
                if (
                    outcome.status == 503
                    and outcome.error_code() == "shed"
                ):
                    rt.flag("shed")
                if outcome.status < 500:
                    # This replica owns the station now (it answered the
                    # packet); a changed home is a re-home — the
                    # failover event the chaos lane gates on.
                    prev = self.affinity.note(sid, replica.url)
                    if prev is not None:
                        self._bus.counter("stream_rehome").inc()
                        rt.flag("rehomed")
                        rt.annotate(rehome_from=prev, station=sid)
                return self._relay(outcome)
            last = outcome
        if last is not None:
            return self._relay(last)
        self._bus.counter("router_no_replica").inc()
        rt.annotate(no_replica=True)
        return (
            503,
            {},
            json.dumps(
                {"error": "no_replica",
                 "message": "no routable replica in the registry"}
            ).encode(),
        )

    def _settle(
        self, replica: Replica, outcome: _Outcome
    ) -> Tuple[bool, bool]:
        """Feed breaker + counters + canary cohort stats; ->
        (breaker_failure, retryable). Every launched attempt settles
        exactly once (winners here, hedge losers via the drain thread),
        so the canary's cohort accounting can't double-count either."""
        failure, retryable = _classify(outcome)
        if failure:
            replica.breaker.record_failure()
        else:
            replica.breaker.record_success(outcome.latency_ms)
        replica.count(failure)
        self._observe_canary(replica, outcome, failure)
        return failure, retryable

    # ---------------------------------------------------- canary + shadow
    def _cohort_pred(
        self, cohort: str, version: int, model: Optional[str] = None
    ):
        """Registry pick predicate selecting one rollout cohort by the
        replicas' probed ``{model: version}`` maps — scoped to one model
        when the canary/shadow named one (multi-model pools: a bare
        version number would otherwise match any entry's version)."""

        def pred(versions: Dict[str, Any]) -> bool:
            is_candidate = serves_version(versions, version, model)
            return is_candidate if cohort == "candidate" else not is_candidate

        return pred

    def _pick(
        self, tried: Set[str], first_attempt: bool
    ) -> Optional[Replica]:
        """Cohort-aware replica pick: under an active canary, ``k%`` of
        first attempts go to the candidate-version cohort and ALL
        retries/hedges stay incumbent; after a rollback (and under
        shadow mode) the candidate cohort gets exactly 0% of primary
        traffic. If the selected cohort has no routable replica,
        availability beats canary fidelity: fall back to a version-blind
        pick (counted)."""
        version: Optional[int] = None
        model: Optional[str] = None
        cohort = self.canary.routing_cohort(first_attempt)
        if cohort is not None:
            version, model = self.canary.version, self.canary.model
        elif self.shadow.active:
            # Shadow serves every client request from the incumbent; the
            # candidate only ever sees mirrored copies.
            cohort, version = "incumbent", self.shadow.version
            model = self.shadow.model
        if cohort is None or version is None:
            return self.registry.pick(exclude=tried)
        replica = self.registry.pick(
            exclude=tried,
            versions_pred=self._cohort_pred(cohort, version, model),
        )
        if replica is None:
            self._bus.counter("router_canary_fallback", cohort=cohort).inc()
            replica = self.registry.pick(exclude=tried)
        return replica

    def _observe_canary(
        self, replica: Replica, outcome: _Outcome, failure: bool
    ) -> None:
        """Feed one settled attempt to the canary's cohort stats; on a
        tripped budget, drain the canary (0%) and publish the rollback
        everywhere: log, bus counter, and (via the one-shot flag) the
        next forwarded request's trace."""
        if self.canary.state != "active":
            return
        cohort = self.canary.cohort_of(replica.versions)
        self._bus.counter("router_canary_requests", cohort=cohort).inc()
        if failure:
            self._bus.counter("router_canary_errors", cohort=cohort).inc()
        latency = None if failure else outcome.latency_ms
        reason = self.canary.observe(cohort, failure, latency)
        if reason:
            self._bus.counter(
                "router_canary_rollback",
                version=str(self.canary.version),
            ).inc()
            self._rollback_to_flag = True
            logger.warning(f"[router] CANARY ROLLBACK: {reason}")

    def _maybe_mirror(
        self, path: str, body: bytes, status: int, payload: bytes,
        trace_id: str,
    ) -> None:
        """Shadow mode: mirror this (sampled, successful, /predict)
        request to a candidate-cohort replica on a background thread and
        diff the decisions into the JSONL report. The client's response
        is already on the wire — mirroring costs it nothing."""
        if (
            path != "/predict"
            or status != 200
            or not self.shadow.active
            or not self.shadow.should_mirror(trace_id)
        ):
            return
        version = self.shadow.version
        if version is None:
            return
        replica = self.registry.pick(
            versions_pred=self._cohort_pred(
                "candidate", version, self.shadow.model
            )
        )
        if replica is None:
            self.shadow.record(
                trace_id, "no_candidate",
                {"reason": "no routable candidate replica"},
            )
            return
        if not self._mirror_slots.acquire(blocking=False):
            # All mirror slots busy (slow candidate): drop this mirror
            # rather than grow an unbounded thread pile — shadow is
            # sampling, a dropped sample is accounted, not a failure.
            self.shadow.record(trace_id, "skipped_busy")
            return
        threading.Thread(
            target=self._mirror_one,
            args=(replica, path, body, payload, trace_id),
            daemon=True,
            name="router-shadow",
        ).start()

    def _mirror_one(
        self, replica: Replica, path: str, body: bytes,
        primary_payload: bytes, trace_id: str,
    ) -> None:
        # Mirrors are breaker-neutral: shadow is observation, and a sick
        # candidate must surface in the report, not destabilize routing.
        # The try covers everything — a mirror thread must never die
        # loudly into a client-visible path (threadlint
        # thread-target-raises) and must always return its mirror slot.
        try:
            status, _, mirrored = _http_request(
                replica.url, "POST", path, body=body,
                timeout_s=self.config.request_timeout_s,
            )
            if status != 200:
                self.shadow.record(
                    trace_id, "mirror_errors",
                    {"replica": replica.url, "candidate_status": status},
                )
                self._bus.counter(
                    "router_shadow_mirrors", verdict="error"
                ).inc()
                return
            diff = decision_diff(
                json.loads(primary_payload.decode()),
                json.loads(mirrored.decode()),
            )
            verdict = "match" if diff["match"] else "mismatch"
            self.shadow.record(
                trace_id, verdict, {"replica": replica.url, "diff": diff}
            )
            self._bus.counter(
                "router_shadow_mirrors", verdict=verdict
            ).inc()
        except Exception as e:  # noqa: BLE001 — observation-only thread
            self.shadow.record(trace_id, "mirror_errors",
                               {"error": repr(e)})
            self._bus.counter(
                "router_shadow_mirrors", verdict="error"
            ).inc()
        finally:
            self._mirror_slots.release()

    def _relay(self, outcome: _Outcome) -> Tuple[int, Dict[str, str], bytes]:
        if outcome.is_net_error:
            # No HTTP response to relay: surface the failure class. A
            # timeout maps to 504 (the client's wait was consumed), a
            # refused/reset connection to 502.
            status = 504 if "timeout" in outcome.error else 502
            body = json.dumps(
                {"error": "replica_unreachable", "message": outcome.error}
            ).encode()
            self._bus.counter("router_responses", status=status).inc()
            return status, {}, body
        self._bus.counter("router_responses", status=outcome.status).inc()
        return outcome.status, outcome.headers, outcome.body

    def _attempt(
        self,
        replica: Replica,
        path: str,
        body: bytes,
        deadline: float,
        rt: Optional[obs_trace.RequestTrace] = None,
        hedge: bool = False,
    ) -> _Outcome:
        timeout_s = min(
            self.config.request_timeout_s,
            max(0.05, deadline - time.monotonic()),
        )
        # The attempt's span id is minted BEFORE the request so the
        # downstream replica's server span can parent to it — the header
        # carries (trace_id, attempt_span_id); the span itself is
        # recorded once the outcome is known.
        span_id: Optional[str] = None
        headers: Optional[Dict[str, str]] = None
        breaker_state = replica.breaker.state
        if rt is not None:
            span_id = obs_trace._new_span_id()
            headers = {
                obs_trace.TRACEPARENT_HEADER: obs_trace.format_traceparent(
                    rt.trace_id, span_id
                )
            }
        t0 = time.monotonic()
        try:
            status, resp_headers, payload = _http_request(
                replica.url, "POST", path, body=body, timeout_s=timeout_s,
                headers=headers,
            )
            outcome = _Outcome(
                status,
                resp_headers,
                payload,
                latency_ms=(time.monotonic() - t0) * 1e3,
            )
        except socket.timeout:
            outcome = _Outcome(0, {}, b"", error="timeout")
        except (OSError, http.client.HTTPException) as e:
            # RemoteDisconnected/BadStatusLine are HTTPException (a
            # SIGKILLed replica's half-written response), the rest OSError.
            msg = f"{type(e).__name__}: {e}"
            if "timed out" in str(e):
                msg = f"timeout ({msg})"
            outcome = _Outcome(0, {}, b"", error=msg)
        if rt is not None:
            ann: Dict[str, Any] = {
                "replica": replica.url,
                "breaker": breaker_state,
                "class": _classify_label(outcome),
            }
            if hedge:
                ann["hedge"] = True
            if outcome.is_net_error:
                ann["error"] = outcome.error
            else:
                ann["status"] = outcome.status
            rt.add_child(
                "attempt", (time.monotonic() - t0) * 1e3,
                span_id=span_id, **ann,
            )
        return outcome

    def _attempt_hedged(
        self,
        primary: Replica,
        path: str,
        body: bytes,
        deadline: float,
        tried: Set[str],
        attempts_left: int,
        rt: Optional[obs_trace.RequestTrace] = None,
    ) -> Tuple[_Outcome, Replica, int, bool]:
        """Race the primary against a late-started hedge on another
        replica; first non-retryable outcome wins. The hedge consumes one
        unit of the retry budget (a hedge IS a speculative retry). Every
        launched attempt settles its breaker exactly once — losers and
        stragglers via a background drain, so a black-holed loser keeps
        counting. Returns ``(outcome, replica, attempts_left,
        pre_settled)``: when ``pre_settled`` the outcome was already fed
        to its breaker here and the caller must not settle it again."""
        results: "Queue[Tuple[_Outcome, Replica]]" = Queue()

        def run(replica: Replica, hedge: bool = False) -> None:
            # The waiter blocks on `results`: an attempt thread dying
            # without putting would stall the race to the full deadline,
            # so any surprise becomes a poisoned net-error outcome
            # (threadlint thread-target-raises).
            try:
                results.put((
                    self._attempt(replica, path, body, deadline, rt=rt,
                                  hedge=hedge),
                    replica,
                ))
            except BaseException as e:  # noqa: BLE001
                results.put((
                    _Outcome(0, {}, b"", error=f"attempt crashed: {e!r}"),
                    replica,
                ))

        threading.Thread(
            target=run, args=(primary,), daemon=True,
            name="router-attempt",
        ).start()
        launched = [primary]
        try:
            outcome, winner = results.get(
                timeout=self.config.hedge_ms / 1000.0
            )
            return outcome, winner, attempts_left, False
        except Empty:
            pass
        # A hedge is a speculative retry: under a canary it stays on the
        # incumbent cohort like every other retry (first_attempt=False).
        hedge = (
            self._pick(tried, first_attempt=False)
            if attempts_left > 0 else None
        )
        if hedge is not None:
            attempts_left -= 1
            tried.add(hedge.url)
            self._bus.counter("router_hedges").inc()
            if rt is not None:
                rt.flag("hedged")
            threading.Thread(
                target=run, args=(hedge, True), daemon=True,
                name="router-hedge",
            ).start()
            launched.append(hedge)

        def drain_pending(seen_n: int) -> None:
            if seen_n < len(launched):
                threading.Thread(
                    target=self._drain_loser,
                    args=(results, len(launched) - seen_n),
                    daemon=True,
                    name="router-hedge-drain",
                ).start()

        seen = 0
        best: Optional[Tuple[_Outcome, Replica]] = None
        while seen < len(launched):
            remaining = max(0.05, deadline - time.monotonic())
            try:
                outcome, replica = results.get(timeout=remaining)
            except Empty:
                break
            seen += 1
            _, retryable = _classify(outcome)
            if not retryable:
                # Acceptable answer: forward() settles the winner; the
                # straggler is accounted when it eventually lands.
                drain_pending(seen)
                return outcome, replica, attempts_left, False
            # Failed retryably: settle its breaker now and keep waiting
            # for the other attempt (if any).
            self._settle(replica, outcome)
            best = (outcome, replica)
        # Deadline ran out. Whatever came back was settled above
        # (pre_settled=True keeps forward() from double-counting it);
        # whatever is still in flight settles via the drain.
        drain_pending(seen)
        if best is not None:
            return best[0], best[1], attempts_left, True
        # Neither attempt returned before the deadline: synthesize a
        # timeout for relay. The real outcomes settle via the drain, so
        # the synthetic one must not touch any breaker.
        return (
            _Outcome(0, {}, b"", error="timeout"),
            primary,
            attempts_left,
            True,
        )

    def _drain_loser(self, results: Queue, n: int) -> None:
        # Best-effort breaker accounting for hedge losers; a surprise here
        # must not die silently mid-drain (threadlint
        # thread-target-raises) — log it, the breaker just misses one
        # sample.
        try:
            for _ in range(n):
                try:
                    outcome, replica = results.get(
                        timeout=self.config.request_timeout_s + 1.0
                    )
                except Empty:
                    return
                self._settle(replica, outcome)
        except Exception as e:  # noqa: BLE001 — accounting-only thread
            logger.warning(f"[router] hedge drain failed: {e!r}")

    _TIMEOUT_MS_RE = re.compile(rb'"timeout_ms"\s*:\s*([0-9eE.+-]+)')

    def _budget_s(self, body: bytes) -> float:
        """Total routing budget: the client's own options.timeout_ms plus
        slack when findable, else enough for every attempt to time out.
        This is a routing heuristic, not protocol validation (the replica
        re-validates), so a regex scan suffices at every size: the front
        tier must not decode a waveform payload (a 256-sample /predict is
        already ~20 KB, hours-long /annotate records run to tens of MB)
        just to read one scalar, and the quoted key cannot appear inside
        the numeric arrays."""
        fallback = self.config.request_timeout_s * (
            1 + max(0, int(self.config.retries))
        )
        m = self._TIMEOUT_MS_RE.search(body)
        try:
            timeout_ms = float(m.group(1)) if m else 0.0
        except ValueError:
            return fallback
        if timeout_ms <= 0:
            return fallback
        return timeout_ms / 1000.0 + 0.5

    # ------------------------------------------------------------- metrics
    _CANARY_STATE_CODES = {"inactive": 0, "active": 1, "rolled_back": 2}

    def _collect(self) -> Dict[str, Any]:
        replicas = self.registry.snapshot()
        affinity = self.affinity.snapshot()
        return {
            "replicas": len(replicas),
            "replicas_ready": sum(1 for r in replicas if r["ready"]),
            "stream_stations": affinity["stations"],
            "stream_rehomes": affinity["rehomes"],
            "breakers_open": sum(
                1 for r in replicas if r["breaker"]["state"] != CLOSED
            ),
            "canary_percent": self.canary.percent,
            "canary_state_code": self._CANARY_STATE_CODES.get(
                self.canary.state, 0
            ),
        }

    def status(self) -> Dict[str, Any]:
        return {
            "replicas": self.registry.snapshot(),
            "ready": self.registry.ready_count(),
            "stream": self.affinity.snapshot(),
            "canary": self.canary.status(),
            "shadow": self.shadow.status(),
            "config": {
                "retries": self.config.retries,
                "hedge_ms": self.config.hedge_ms,
                "request_timeout_s": self.config.request_timeout_s,
            },
        }


# ----------------------------------------------------------- http plumbing
def _http_request(
    base_url: str,
    method: str,
    path: str,
    body: Optional[bytes] = None,
    timeout_s: float = 10.0,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, Dict[str, str], bytes]:
    """One HTTP exchange against ``base_url`` (``host:port`` or
    ``http://host:port``); returns (status, headers, body). Raises
    OSError subclasses (incl. socket.timeout) on network failure.
    ``headers`` adds request headers (trace propagation)."""
    hostport = base_url.split("://", 1)[-1].rstrip("/")
    conn = http.client.HTTPConnection(hostport, timeout=timeout_s)
    try:
        send_headers = {"Content-Type": "application/json"} if body else {}
        send_headers.update(headers or {})
        conn.request(method, path, body=body, headers=send_headers)
        resp = conn.getresponse()
        payload = resp.read()
        keep = {}
        # Server-Timing/traceparent relay the replica's breakdown + trace
        # identity through the router to the client.
        for k in ("Content-Type", "Retry-After", "Server-Timing",
                  "traceparent"):
            v = resp.getheader(k)
            if v is not None:
                keep[k] = v
        return resp.status, keep, payload
    finally:
        conn.close()


# ----------------------------------------------------------------- HTTP shim
MAX_BODY_BYTES = 64 * 1024 * 1024  # match serve/server.py


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "seist-router/0.1"
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> Router:
        return self.server.router  # type: ignore[attr-defined]

    def log_message(self, format: str, *args: Any) -> None:
        logger.debug(f"[router] {self.address_string()} {format % args}")

    def _reply(
        self,
        status: int,
        body: bytes,
        ctype: str = "application/json",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            if k.lower() != "content-type":
                self.send_header(k, v)
        if self.close_connection:
            # Tell the client, not just the socket: without the header an
            # HTTP/1.1 client assumes keep-alive and retries a dead conn
            # (same contract as serve/server.py's _reply).
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, status: int, payload: Any) -> None:
        self._reply(status, json.dumps(payload).encode())

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                ready = self.router.registry.ready_count()
                self._reply_json(
                    200 if ready else 503,
                    {"status": "ok" if ready else "no_replicas",
                     "ready_replicas": ready},
                )
            elif path == "/router/replicas":
                self._reply_json(200, self.router.status())
            elif path == "/router/canary":
                self._reply_json(200, self.router.canary.status())
            elif path == "/router/shadow":
                self._reply_json(200, self.router.shadow.status())
            elif path == "/metrics":
                from seist_tpu.obs.bus import render_prometheus

                self._reply(
                    200,
                    render_prometheus(self.router._bus).encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/metrics.json":
                self._reply_json(200, self.router._bus.snapshot())
            elif path.startswith("/traces"):
                routed = obs_trace.handle_traces_path(self.path)
                if routed is None:
                    self._reply_json(404, {"error": "not_found",
                                           "message": self.path})
                else:
                    self._reply_json(*routed)
            elif path in ("/fleet/metrics", "/fleet/metrics.json"):
                # Fleet aggregation pane (obs/fleet.py), attached by the
                # fleet supervisor; a bare router has no fleet view.
                fleet = getattr(self.server, "fleet", None)
                if fleet is None:
                    self._reply_json(
                        404,
                        {"error": "no_fleet",
                         "message": "no fleet aggregator attached "
                         "(run under tools/supervise_fleet.py)"},
                    )
                elif path == "/fleet/metrics.json":
                    self._reply_json(200, fleet.merged())
                else:
                    self._reply(
                        200,
                        fleet.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
            else:
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
        except Exception as e:  # noqa: BLE001 — a handler bug must 500,
            # not kill the connection thread mid-response
            self._reply_json(500, {"error": "internal", "message": repr(e)})

    def do_POST(self) -> None:  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length") or 0)
            if length > MAX_BODY_BYTES:
                self.close_connection = True
                self._reply_json(
                    413,
                    {"error": "too_large",
                     "message": f"body {length} > {MAX_BODY_BYTES} bytes"},
                )
                return
            body = self.rfile.read(length)
            path = self.path.split("?", 1)[0]
            if path in ("/predict", "/annotate", "/stream"):
                status, headers, payload = self.router.forward(
                    path, body,
                    traceparent=self.headers.get(
                        obs_trace.TRACEPARENT_HEADER
                    ),
                )
                self._reply(status, payload, headers=headers)
            elif path == "/router/register":
                url = self._admin_url(body)
                if url:
                    self.router.registry.add(url)
                    self._reply_json(200, {"registered": url})
            elif path == "/router/deregister":
                url = self._admin_url(body)
                if url:
                    removed = self.router.registry.remove(url)
                    self._reply_json(
                        200 if removed else 404, {"deregistered": removed}
                    )
            elif path == "/router/canary":
                # {"version": V, "percent": k, "max_error_delta"?,
                #  "max_latency_delta_ms"?, "min_requests"?};
                # percent 0 (or missing version) clears the canary.
                self._admin_canary(body)
            elif path == "/router/shadow":
                # {"version": V, "sample": 0.1, "report"?: path};
                # sample 0 (or missing version) clears shadow mode.
                self._admin_shadow(body)
            else:
                self._reply_json(404, {"error": "not_found",
                                       "message": self.path})
        except Exception as e:  # noqa: BLE001 — same contract as do_GET
            logger.warning(f"[router] unhandled error: {e!r}")
            self._reply_json(500, {"error": "internal", "message": repr(e)})

    def _admin_canary(self, body: bytes) -> None:
        try:
            spec = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            spec = None
        if not isinstance(spec, dict):
            self._reply_json(400, {"error": "bad_request",
                                   "message": "body must be a JSON object"})
            return
        try:
            percent = float(spec.get("percent", 0) or 0)
            if percent <= 0 or spec.get("version") is None:
                self._reply_json(200, self.router.canary.stop())
                return
            budget = CanaryBudget(
                max_error_delta=float(
                    spec.get("max_error_delta",
                             CanaryBudget.max_error_delta)
                ),
                max_latency_delta_ms=float(
                    spec.get("max_latency_delta_ms",
                             CanaryBudget.max_latency_delta_ms)
                ),
                min_requests=int(
                    spec.get("min_requests", CanaryBudget.min_requests)
                ),
            )
            self._reply_json(
                200,
                self.router.canary.start(
                    int(spec["version"]), percent, budget,
                    model=str(spec["model"]) if spec.get("model") else None,
                ),
            )
        except (TypeError, ValueError) as e:
            self._reply_json(400, {"error": "bad_request",
                                   "message": str(e)})

    def _admin_shadow(self, body: bytes) -> None:
        try:
            spec = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            spec = None
        if not isinstance(spec, dict):
            self._reply_json(400, {"error": "bad_request",
                                   "message": "body must be a JSON object"})
            return
        try:
            sample = float(spec.get("sample", 0) or 0)
            if sample <= 0 or spec.get("version") is None:
                self._reply_json(200, self.router.shadow.stop())
                return
            self._reply_json(
                200,
                self.router.shadow.start(
                    int(spec["version"]), sample,
                    str(spec.get("report", "") or ""),
                    model=str(spec["model"]) if spec.get("model") else None,
                ),
            )
        except (TypeError, ValueError) as e:
            self._reply_json(400, {"error": "bad_request",
                                   "message": str(e)})

    def _admin_url(self, body: bytes) -> Optional[str]:
        try:
            url = json.loads(body.decode()).get("url", "")
        except (ValueError, UnicodeDecodeError, AttributeError):
            url = ""
        if not isinstance(url, str) or not url:
            self._reply_json(400, {"error": "bad_request",
                                   "message": "body must be {'url': ...}"})
            return None
        return url


class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # socketserver's default listen backlog is 5: under an open-loop
    # connection burst (every bench/ops client opens a conn per request)
    # SYNs overflow the backlog and get silently dropped, and the client
    # kernel retries at 1/3/7/15/31 s — which shows up as latency
    # *clusters* at exactly those values while the service itself is
    # idle. A front tier must absorb accept bursts; overload policy
    # belongs to the shed/429 tiers, not the kernel's SYN queue.
    request_queue_size = 1024

    #: obs/fleet.FleetAggregator when running under the fleet supervisor
    #: (serves /fleet/metrics); None on a bare router.
    fleet = None

    def __init__(self, addr: Tuple[str, int], router: Router):
        super().__init__(addr, _RouterHandler)
        self.router = router


def start_router_server(
    router: Router, host: str = "127.0.0.1", port: int = 8080
) -> RouterHTTPServer:
    """Bind + serve on a daemon thread (ephemeral port via ``port=0``);
    also starts the health prober."""
    server = RouterHTTPServer((host, port), router)
    thread = threading.Thread(
        target=server.serve_forever, name="router-http", daemon=True
    )
    thread.start()
    router.start_prober()
    return server


# ----------------------------------------------------------------- CLI
def get_router_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(
        prog="router",
        description="seist_tpu serving front tier: replica router",
    )
    ap.add_argument(
        "--replica", action="append", default=[], metavar="HOST:PORT",
        help="replica base address, repeatable (more can be registered "
        "at runtime via POST /router/register)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--retries", type=int, default=2)
    ap.add_argument("--request-timeout-s", type=float, default=10.0)
    ap.add_argument("--hedge-ms", type=float, default=0.0)
    ap.add_argument("--probe-interval-s", type=float, default=1.0)
    ap.add_argument("--breaker-failures", type=int, default=3)
    ap.add_argument("--breaker-cooldown-s", type=float, default=2.0)
    ap.add_argument("--breaker-latency-trip-ms", type=float,
                    default=float("inf"))
    return ap.parse_args(argv)


def router_from_args(args: argparse.Namespace) -> Router:
    config = RouterConfig(
        retries=args.retries,
        request_timeout_s=args.request_timeout_s,
        hedge_ms=args.hedge_ms,
        probe_interval_s=args.probe_interval_s,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        breaker_latency_trip_ms=args.breaker_latency_trip_ms,
    )
    router = Router(config=config)
    for url in args.replica:
        router.registry.add(url)
    return router


def main(argv: Optional[List[str]] = None) -> None:
    args = get_router_args(argv)
    router = router_from_args(args)
    obs_trace.register_trace_collector()
    server = start_router_server(router, args.host, args.port)
    host, port = server.server_address[:2]
    logger.info(
        f"[router] listening on http://{host}:{port} "
        f"replicas={[r.url for r in router.registry.replicas()]}"
    )
    stop = threading.Event()
    import signal

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    # threadlint: disable=wait-no-timeout -- main thread parked until the
    # signal handler (the only setter) fires; CPython wakes an untimed
    # main-thread Event.wait to run handlers, so no wakeup can be lost.
    stop.wait()
    server.shutdown()
    router.stop()
    logger.info("[router] stopped")


if __name__ == "__main__":
    main()
