"""Model pool: load ``(model_name, checkpoint)`` pairs, pre-compile every
serving shape, decode per-task outputs.

The pool owns exactly one jitted forward per model — a closure over the
restored variables, so jax's compile cache keys only on the input shape.
``warmup()`` runs that forward once per batch bucket (and once through the
default postprocess) before the server accepts traffic: the t5x/seqio
lesson (PAPERS.md) that a service must pay all its XLA compiles at
startup, never on a customer request.

``load_model_entry`` is also the single checkpoint-loading path for
offline tools (tools/predict.py) — loader logic lives here exactly once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seist_tpu.serve.batcher import _slice_outputs
from seist_tpu.serve.protocol import (
    BadRequest,
    PredictOptions,
    ServeError,
    UnknownModel,
)
from seist_tpu.utils.logger import logger


@dataclass
class ModelEntry:
    """One servable model: everything needed to forward + decode."""

    name: str
    model: Any
    variables: Dict[str, Any]
    spec: Any  # taskspec.TaskSpec
    window: int
    in_channels: int
    channel0: Optional[str]  # 'non'/'det' for picking heads, else None
    forward: Callable[[Any], Any]  # jitted, (B, window, C) -> outputs
    apply: Callable[[Any], Any]  # same, unjitted (for jax.jit composition)

    @property
    def is_picker(self) -> bool:
        return self.channel0 is not None


def load_model_entry(
    model_name: str,
    checkpoint: str = "",
    *,
    window: int = 8192,
    seed: int = 0,
) -> ModelEntry:
    """Create + restore one model for inference.

    Without ``checkpoint`` the model serves freshly-initialized weights
    (tests / smoke runs); with one, params (+ BN stats when present) are
    restored the same way demo_predict.py and tools/predict.py always did
    — that logic now lives only here.
    """
    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api

    seist_tpu.load_all()
    spec = taskspec.get_task_spec(model_name)
    in_channels = taskspec.get_num_inchannels(model_name)
    model = api.create_model(
        model_name, in_channels=in_channels, in_samples=window
    )
    if checkpoint:
        from seist_tpu.train.checkpoint import load_checkpoint

        restored = load_checkpoint(checkpoint)
        variables = {"params": restored["params"]}
        if restored.get("batch_stats"):  # omit entirely for BN-less models
            variables["batch_stats"] = restored["batch_stats"]
    else:
        variables = api.init_variables(
            model, seed=seed, in_samples=window, in_channels=in_channels
        )

    first = spec.labels[0]
    channel0 = (
        tuple(first)[0]
        if isinstance(first, (tuple, list))
        and len(first) == 3
        and tuple(first)[0] in ("non", "det")
        else None
    )

    def apply_fn(x):
        return model.apply(variables, x, train=False)

    import jax

    return ModelEntry(
        name=model_name,
        model=model,
        variables=variables,
        spec=spec,
        window=window,
        in_channels=in_channels,
        channel0=channel0,
        forward=jax.jit(apply_fn),
        apply=apply_fn,
    )


class ModelPool:
    """Loaded entries keyed by model name + the warm-up that compiles all
    serving shapes up front."""

    def __init__(
        self,
        entries: Sequence[Tuple[str, str]],
        *,
        window: int = 8192,
        seed: int = 0,
    ):
        if not entries:
            raise ValueError("ModelPool needs at least one (name, checkpoint)")
        self._entries: Dict[str, ModelEntry] = {}
        for name, ckpt in entries:
            if name in self._entries:
                raise ValueError(f"duplicate model '{name}' in pool")
            self._entries[name] = load_model_entry(
                name, ckpt, window=window, seed=seed
            )
        self.warmup_report: List[Dict[str, Any]] = []

    def names(self) -> List[str]:
        return list(self._entries)

    def get(self, name: Optional[str]) -> ModelEntry:
        if name is None:
            if len(self._entries) == 1:
                return next(iter(self._entries.values()))
            raise BadRequest(
                f"'model' is required when several are loaded: {self.names()}"
            )
        try:
            return self._entries[name]
        except KeyError:
            raise UnknownModel(
                f"model '{name}' not loaded; available: {self.names()}"
            ) from None

    def warmup(self, buckets: Sequence[int]) -> List[Dict[str, Any]]:
        """Compile every (bucket, window, C) forward + the default decode
        for every entry; returns per-shape compile timings (also kept on
        ``self.warmup_report`` for /healthz)."""
        from seist_tpu.utils.profiling import stopwatch

        report = []
        for entry in self._entries.values():
            # jaxlint: disable=host-sync-hot-path -- one-shot warm-up
            # coercion of a tiny host-side bucket list, not a request path
            for b in sorted(set(int(b) for b in buckets)):
                x = np.zeros((b, entry.window, entry.in_channels), np.float32)
                with stopwatch() as elapsed:
                    out = entry.forward(x)
                    _block(out)
                report.append(
                    {"model": entry.name, "batch": b, "seconds": elapsed()}
                )
                logger.info(
                    f"[serve] warm {entry.name} batch={b} "
                    f"({elapsed()*1000:.0f} ms)"
                )
            # Warm the postprocess programs too (pick_peaks/detect_events
            # jit on static topk/min_peak_dist — defaults compiled here).
            with stopwatch() as elapsed:
                decode_outputs(
                    entry, _slice_outputs(out, 0), PredictOptions()
                )
            report.append(
                {"model": entry.name, "batch": "decode", "seconds": elapsed()}
            )
        self.warmup_report = report
        return report


def decode_outputs(
    entry: ModelEntry, outputs: Any, opts: PredictOptions
) -> Dict[str, Any]:
    """One request's raw model outputs (leading dim 1) -> JSON-able result.

    Picking heads route through ops/postprocess (same programs the eval
    loop uses); VALUE heads go through the task spec's results transform
    (e.g. magnet's mean-only, baz's (cos,sin)->degrees decode); ONEHOT
    heads report argmax class + raw scores.
    """
    from seist_tpu import taskspec
    from seist_tpu.ops.postprocess import process_outputs

    spec = entry.spec
    if entry.is_picker:
        res = process_outputs(
            outputs,
            spec.labels,
            opts.sampling_rate,
            ppk_threshold=opts.ppk_threshold,
            spk_threshold=opts.spk_threshold,
            det_threshold=opts.det_threshold,
            min_peak_dist=opts.min_peak_dist,
            max_detect_event_num=opts.max_events,
        )
        import jax

        # ONE device->host round trip for every head (the Metrics.to_dict
        # batched-get idiom): the per-kind np.asarray calls below then
        # slice plain host arrays instead of paying a sync each.
        res = jax.device_get(res)
        fs = float(opts.sampling_rate)
        out: Dict[str, Any] = {"task": "picking"}
        for kind in ("ppk", "spk"):
            # jaxlint: disable=host-sync-hot-path -- host numpy; already
            # device_get'd above in one batched transfer
            idxs = np.asarray(res[kind])[0]
            idxs = idxs[idxs >= 0]
            out[kind] = [
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                {"sample": int(i), "time_s": round(i / fs, 6)} for i in idxs
            ]
        if "det" in res:
            pairs = np.asarray(res["det"])[0].reshape(-1, 2)
            pairs = pairs[pairs[:, 1] >= pairs[:, 0]]
            out["det"] = [
                {"onset": int(a), "offset": int(b),
                 "onset_s": round(a / fs, 6), "offset_s": round(b / fs, 6)}
                for a, b in pairs
            ]
        return out

    import jax

    transform = spec.outputs_transform_for_results
    outs = transform(outputs) if transform else outputs
    outs_list = outs if isinstance(outs, (tuple, list)) else [outs]
    # One batched transfer for every label's head output; the np.asarray
    # in the loop below is then a host-side no-op.
    outs_list = jax.device_get(list(outs_list))
    if len(outs_list) != len(spec.labels):
        # Server-side model/spec mismatch, not a client error — 500.
        raise ServeError(
            f"model '{entry.name}' produced {len(outs_list)} outputs for "
            f"{len(spec.labels)} labels"
        )
    out = {"task": "regression"}
    for name, arr in zip(spec.labels, outs_list):
        # jaxlint: disable=host-sync-hot-path -- host numpy; already
        # device_get'd above in one batched transfer
        arr = np.asarray(arr)
        if name in taskspec.IO_ITEMS and taskspec.get_kind(name) == taskspec.ONEHOT:
            out["task"] = "classification"
            scores = arr.reshape(-1)
            out[name] = {
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                "class": int(np.argmax(scores)),
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                "scores": [float(s) for s in scores],
            }
        else:
            # jaxlint: disable=host-sync-hot-path -- host numpy; already
            # device_get'd above
            out[name] = float(arr.reshape(-1)[0])
    return out


def _block(out: Any) -> None:
    """Wait for device completion so warm-up timings mean something."""
    for o in out if isinstance(out, (tuple, list)) else [out]:
        getattr(o, "block_until_ready", lambda: None)()
