"""Model pool: load servable entries, AOT-compile every serving shape,
decode per-task outputs.

Two kinds of entry:

* :class:`ModelEntry` — one single-task model (phasenet, eqtransformer,
  any registered name): the PR 1 shape, unchanged on the wire.
* :class:`MultiTaskEntry` — one SeisT task GROUP (e.g. ``seist_s`` =
  dpk+emg+dis): ONE shared trunk (models/seist.py ``mode='backbone'``)
  plus per-task heads. A multi-task request runs the trunk ONCE per
  trace and fans its features out to every requested head — the ~90%
  FLOPs the paper's five heads share is paid once instead of per task.

``warmup()`` AOT-compiles (serve/aot.py: ``jax.jit(fn).lower().compile()``)
every warm bucket shape x program x enabled variant before the server
accepts traffic — the t5x/seqio lesson (PAPERS.md) that a service must
pay all its XLA compiles at startup, never on a customer request, now
enforced by construction: the request path calls shape-specialized
executables that cannot trace. Quantized variants (bf16 / int8
weight-only) are parity-gated at load against fp32.

``load_model_entry`` is also the single checkpoint-loading path for
offline tools (tools/predict.py) — loader logic lives here exactly once.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from seist_tpu.obs import trace as obs_trace
from seist_tpu.serve import aot
from seist_tpu.serve.batcher import _slice_outputs
from seist_tpu.serve.protocol import (
    BadRequest,
    IncompatibleCheckpoint,
    ParityGateFailed,
    PredictOptions,
    ReloadFailed,
    ServeError,
    UnknownModel,
)
from seist_tpu.utils.logger import logger

#: The five SeisT task heads (PAPER.md): detection+picking, first-motion
#: polarity, magnitude, back-azimuth, epicentral distance. A task group
#: ``<prefix>`` serves ``<prefix>_<task>`` heads on one shared trunk.
TASKS = ("dpk", "pmp", "emg", "baz", "dis")


def _load_parts(
    model_name: str, checkpoint: str, *, window: int, seed: int
) -> Tuple[Any, Dict[str, Any], Any, int, Optional[str]]:
    """Create + restore one model: (model, variables, spec, in_channels,
    channel0). The shared loader behind single entries AND group heads.

    Restored checkpoints are structurally validated against the model
    config BEFORE anything serves (or swaps) them: a wrong-architecture
    checkpoint raises :class:`IncompatibleCheckpoint` naming the first
    mismatching tree path instead of surfacing as a deep flax apply
    traceback on the first request."""
    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api

    seist_tpu.load_all()
    spec = taskspec.get_task_spec(model_name)
    in_channels = taskspec.get_num_inchannels(model_name)
    model = api.create_model(
        model_name, in_channels=in_channels, in_samples=window
    )
    if checkpoint:
        from seist_tpu.train.checkpoint import load_checkpoint

        restored = load_checkpoint(checkpoint)
        variables = {"params": restored["params"]}
        if restored.get("batch_stats"):  # omit entirely for BN-less models
            variables["batch_stats"] = restored["batch_stats"]
        expected = api.param_shapes(
            model, in_samples=window, in_channels=in_channels
        )
        validate_checkpoint_tree(
            expected, variables, model_name=model_name, checkpoint=checkpoint
        )
    else:
        variables = api.init_variables(
            model, seed=seed, in_samples=window, in_channels=in_channels
        )

    first = spec.labels[0]
    channel0 = (
        tuple(first)[0]
        if isinstance(first, (tuple, list))
        and len(first) == 3
        and tuple(first)[0] in ("non", "det")
        else None
    )
    return model, variables, spec, in_channels, channel0


def validate_checkpoint_tree(
    expected: Any, restored: Any, *, model_name: str, checkpoint: str
) -> None:
    """Structurally diff a restored checkpoint against the model config's
    expected variable tree (``api.param_shapes`` — shape-only, no
    compute) and raise :class:`IncompatibleCheckpoint` naming the FIRST
    mismatching path. Checked before any serving/swap: the reload path's
    "disable, don't serve wrong" ladder starts here.

    An expected collection that is empty (no BN -> no batch_stats) is
    optional; everything else must match key-for-key in structure, shape
    and dtype."""

    def fail(kind: str, path: str, detail: str = "") -> None:
        raise IncompatibleCheckpoint(
            f"checkpoint '{checkpoint}' does not fit model "
            f"'{model_name}': {kind} at '{path}'"
            + (f" ({detail})" if detail else "")
        )

    def walk(exp: Any, got: Any, path: str) -> None:
        exp_map = isinstance(exp, Mapping)
        got_map = isinstance(got, Mapping)
        if exp_map != got_map:
            fail(
                "subtree/leaf mismatch", path,
                f"expected {'subtree' if exp_map else 'array'}, "
                f"checkpoint has {'subtree' if got_map else 'array'}",
            )
        if exp_map:
            for k in sorted(exp):
                if k not in got:
                    fail("missing key", f"{path}/{k}" if path else str(k))
            for k in sorted(got):
                if k not in exp:
                    fail("unexpected key", f"{path}/{k}" if path else str(k))
            for k in sorted(exp):
                walk(exp[k], got[k], f"{path}/{k}" if path else str(k))
            return
        exp_shape = tuple(getattr(exp, "shape", ()))
        got_shape = tuple(getattr(got, "shape", ()))
        if exp_shape != got_shape:
            fail("shape mismatch", path,
                 f"model wants {exp_shape}, checkpoint has {got_shape}")
        exp_dt = np.dtype(getattr(exp, "dtype", np.float32))
        got_dt = np.dtype(getattr(got, "dtype", np.float32))
        if exp_dt != got_dt:
            fail("dtype mismatch", path,
                 f"model wants {exp_dt}, checkpoint has {got_dt}")

    exp_cols = {
        k: v for k, v in dict(expected).items() if not (
            isinstance(v, Mapping) and not v  # empty col = optional
        )
    }
    got_cols = dict(restored)
    for col in sorted(exp_cols):
        if col not in got_cols:
            fail("missing collection", col)
    for col in sorted(got_cols):
        if col not in exp_cols:
            fail("unexpected collection", col)
    for col in sorted(exp_cols):
        walk(exp_cols[col], got_cols[col], col)


@dataclass
class ModelEntry:
    """One servable single-task model: everything needed to forward +
    decode. After ``warmup`` the request path dispatches to AOT
    executables via :meth:`run`; ``forward`` (live jit) stays as the
    pre-warm / odd-shape fallback and the offline-tools entry point."""

    name: str
    model: Any
    variables: Dict[str, Any]
    spec: Any  # taskspec.TaskSpec
    window: int
    in_channels: int
    channel0: Optional[str]  # 'non'/'det' for picking heads, else None
    forward: Callable[[Any], Any]  # jitted, (B, window, C) -> outputs
    apply: Callable[[Any], Any]  # same, unjitted (for jax.jit composition)
    #: monotonic model version (stamped into every response + /healthz);
    #: a hot reload (ModelPool.reload) installs a higher one.
    version: int = 1
    #: checkpoint path this entry was restored from ("" = fresh init) —
    #: the reload default when the caller only bumps the version.
    checkpoint: str = ""
    variants: Tuple[str, ...] = ("fp32",)
    # variant -> bucket -> AotProgram (filled by build_programs)
    programs: Dict[str, Dict[int, aot.AotProgram]] = field(
        default_factory=dict
    )
    # variant -> parity-gate verdict (fp32 implicitly True)
    variant_ok: Dict[str, bool] = field(default_factory=dict)
    _fallbacks: Dict[str, Callable] = field(default_factory=dict)
    _flock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def is_picker(self) -> bool:
        return self.channel0 is not None

    @property
    def is_group(self) -> bool:
        return False

    def resolve_tasks(self, tasks: Optional[Sequence[str]]) -> None:
        if tasks is not None:
            raise BadRequest(
                f"model '{self.name}' is single-task; 'tasks' is only "
                "valid for multi-task groups (serve --model-group)"
            )
        return None

    def supported_variants(
        self, tasks: Optional[Sequence[str]] = None
    ) -> List[str]:
        return ["fp32"] + [
            v for v in self.variants
            if v != "fp32" and self.variant_ok.get(v)
        ]

    def _fallback(self, variant: str) -> Callable[[Any], Any]:
        """Live-jitted per-variant forward: serves requests that arrive
        before warm-up finished (readiness is advisory) or at shapes no
        program was built for. fp32 reuses the entry's own jit."""
        if variant == "fp32":
            return self.forward
        with self._flock:
            fn = self._fallbacks.get(variant)
            if fn is None:
                import jax

                fn = jax.jit(
                    aot.make_variant_apply(
                        lambda v, x: self.model.apply(v, x, train=False),
                        self.variables,
                        variant,
                    )
                )
                self._fallbacks[variant] = fn
            return fn

    def run(self, batch: np.ndarray, variant: str = "fp32") -> Any:
        """The request-path forward: AOT executable when one matches the
        batch shape (zero tracing), live-jit fallback otherwise. Inside a
        batcher flush the served program + AOT-hit land on the flush's
        shared trace span (obs/trace.annotate_flush — no-op otherwise)."""
        prog = self.programs.get(variant, {}).get(int(batch.shape[0]))
        if prog is not None:
            obs_trace.annotate_flush(
                program=prog.key, aot=True, variant=variant
            )
            return prog(batch)
        obs_trace.annotate_flush(
            program=f"{self.name}/full/b{int(batch.shape[0])}/{variant}:jit",
            aot=False, variant=variant,
        )
        import jax.numpy as jnp

        return self._fallback(variant)(jnp.asarray(batch))

    # ------------------------------------------------------------ warm-up
    def build_programs(
        self, buckets: Sequence[int], report: List[Dict[str, Any]]
    ) -> None:
        import jax.numpy as jnp

        apply2 = lambda v, x: self.model.apply(v, x, train=False)  # noqa: E731
        shape = lambda b: [((b, self.window, self.in_channels), jnp.float32)]  # noqa: E731
        for variant in self.variants:
            fn = aot.make_variant_apply(apply2, self.variables, variant)
            progs = self.programs.setdefault(variant, {})
            for b in buckets:
                prog = aot.aot_compile(
                    f"{self.name}/full/b{b}/{variant}", fn, shape(b),
                    model=self.name,
                )
                progs[b] = prog
                report.append({
                    "model": self.name, "batch": b, "variant": variant,
                    "seconds": prog.compile_ms / 1e3, "program": prog.key,
                })
                logger.info(
                    f"[serve] aot {prog.key} ({prog.compile_ms:.0f} ms, "
                    f"{prog.flops:.3g} flops)"
                )
        self._gate_variants(buckets[0])

    def _gate_variants(self, probe_bucket: int) -> None:
        if all(v == "fp32" for v in self.variants):
            return
        probe = _probe_input(probe_bucket, self.window, self.in_channels)
        ref = np.asarray(
            _first_leaf(self.run(probe, "fp32")), np.float32
        )
        kind, _ = aot.parity_kind(self.spec)
        scale = float(getattr(self.model, "head_scale", 1.0) or 1.0)
        for variant in self.variants:
            if variant == "fp32":
                continue
            # jaxlint: disable=host-sync-hot-path -- one-shot load-time
            # parity gate (one probe per variant), not a request path
            out = np.asarray(
                _first_leaf(self.run(probe, variant)), np.float32
            )
            ok, err = aot.variant_parity(
                ref, out, variant, kind=kind, scale=scale
            )
            self.variant_ok[variant] = ok
            logger.info(
                f"[serve] variant gate {self.name}/{variant}: "
                f"{'ok' if ok else 'DISABLED'} (err={err:.2g}, {kind})"
            )


@dataclass
class TaskHead:
    """One task head of a group: duck-types the slice of ModelEntry that
    ``decode_outputs`` reads (name/spec/channel0/is_picker)."""

    task: str
    name: str  # underlying model name, e.g. seist_s_dpk
    model: Any
    variables: Dict[str, Any]  # merged: shared trunk leaves + own head
    spec: Any
    channel0: Optional[str]
    head_scale: float = 1.0

    @property
    def is_picker(self) -> bool:
        return self.channel0 is not None


@dataclass
class MultiTaskEntry:
    """One SeisT task group: shared trunk + per-task heads, fanned out.

    ``fanout`` is the request-path forward: trunk ONCE on the batch,
    then each requested head on the trunk features. Trunk weights are
    the FIRST listed task's (heads share the arrays — one trunk in
    memory regardless of head count). Counters: ``serve_trunk_runs``,
    ``serve_head_runs{task=}`` and ``serve_trunk_flops_saved`` (the
    amortized trunk FLOPs a per-task serving stack would have re-paid)
    on the obs bus, mirrored in :meth:`fanout_stats`."""

    name: str
    window: int
    in_channels: int
    tasks: Tuple[str, ...]
    heads: Dict[str, TaskHead]
    trunk_model: Any
    trunk_variables: Dict[str, Any]
    #: monotonic model version (see ModelEntry.version)
    version: int = 1
    #: per-task checkpoint paths this group was restored from — the
    #: reload defaults for tasks the caller doesn't re-point.
    task_checkpoints: Dict[str, str] = field(default_factory=dict)
    variants: Tuple[str, ...] = ("fp32",)
    # (variant, 'trunk'|task, bucket) -> AotProgram
    programs: Dict[Tuple[str, str, int], aot.AotProgram] = field(
        default_factory=dict
    )
    # variant -> tuple of parity-ok tasks (fp32 -> all)
    variant_tasks: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    _fallbacks: Dict[Tuple[str, str], Callable] = field(default_factory=dict)
    _flock: threading.Lock = field(default_factory=threading.Lock)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _trunk_runs: int = 0
    _head_runs: Dict[str, int] = field(default_factory=dict)
    _flops_saved: float = 0.0

    def __post_init__(self):
        self.variant_tasks.setdefault("fp32", tuple(self.tasks))

    @property
    def is_group(self) -> bool:
        return True

    @property
    def is_picker(self) -> bool:
        """/annotate support: the group can stream-pick iff it serves the
        dpk head (channel0 comes from it)."""
        return "dpk" in self.heads and self.heads["dpk"].is_picker

    @property
    def channel0(self) -> Optional[str]:
        return self.heads["dpk"].channel0 if "dpk" in self.heads else None

    @property
    def spec(self) -> Any:
        """A group has no single spec; decode goes through per-task
        heads. Kept as an explicit error to catch misuse early."""
        raise ServeError(
            f"group '{self.name}' has per-task specs; decode via heads[task]"
        )

    # --------------------------------------------------------- resolution
    def resolve_tasks(self, tasks: Optional[Sequence[str]]) -> Tuple[str, ...]:
        if tasks is None:
            return tuple(self.tasks)
        unknown = [t for t in tasks if t not in self.heads]
        if unknown:
            raise BadRequest(
                f"group '{self.name}' does not serve tasks {unknown}; "
                f"available: {list(self.tasks)}"
            )
        return tuple(tasks)

    def supported_variants(
        self, tasks: Optional[Sequence[str]] = None
    ) -> List[str]:
        tasks = tuple(tasks) if tasks is not None else self.tasks
        out = []
        for v in self.variants:
            ok = self.variant_tasks.get(v)
            if ok is not None and all(t in ok for t in tasks):
                out.append(v)
        return out

    # ------------------------------------------------------------ forward
    def _fallback(self, kind: str, variant: str) -> Callable:
        """Live-jitted trunk/head programs for pre-warm traffic."""
        key = (kind, variant)
        with self._flock:
            fn = self._fallbacks.get(key)
            if fn is None:
                import jax

                fn = jax.jit(self._make_fn(kind, variant))
                self._fallbacks[key] = fn
            return fn

    def _make_fn(self, kind: str, variant: str) -> Callable:
        """Raw (unjitted) trunk or head program for ``variant``.

        The in-trace variant conventions live in ONE place —
        ``aot.variant_compute`` / ``aot.head_variant_compute`` (shared
        with tools/irlint's manifest, so the audited program cannot
        drift from the shipped one); weight transforms (bf16 cast / int8
        pack) happen HERE, eagerly, so executables really do hold
        bf16/int8 weights at rest. The trunk keeps its features in the
        variant's compute dtype (``cast_outputs=False``); heads cast
        their outputs to fp32 so decode is variant-blind."""
        from seist_tpu.models.seist import backbone_apply

        if kind == "trunk":
            return aot.make_variant_apply(
                lambda v, x: backbone_apply(self.trunk_model, v, x),
                self.trunk_variables,
                variant,
                cast_outputs=False,  # bf16 features flow to bf16 heads
            )
        head = self.heads[kind]
        compute = aot.head_variant_compute(head.model, variant)
        hv = aot.transform_variables(head.variables, variant)
        return lambda feats, x: compute(hv, feats, x)

    def fanout(
        self,
        batch: np.ndarray,
        tasks: Sequence[str],
        variant: str = "fp32",
        *,
        account: bool = True,
    ) -> Dict[str, Any]:
        """Trunk once, requested heads on its features. Returns
        {task: raw head outputs} with leading dim == batch.

        ``account=False`` for load-time callers (warm-up, parity-gate
        probes): the trunk_runs / flops-saved counters measure SERVED
        traffic — probe runs inflating them would overstate the
        amortization in /metrics and bench_serve's JSON."""
        b = int(batch.shape[0])
        trunk_prog = self.programs.get((variant, "trunk", b))
        if trunk_prog is not None:
            feats = trunk_prog(batch)
            trunk_flops = trunk_prog.flops
        else:
            import jax.numpy as jnp

            feats = self._fallback("trunk", variant)(jnp.asarray(batch))
            trunk_flops = 0.0
        outs: Dict[str, Any] = {}
        aot_heads = True
        for t in tasks:
            head_prog = self.programs.get((variant, t, b))
            if head_prog is not None:
                outs[t] = head_prog(feats, batch)
            else:
                aot_heads = False
                outs[t] = self._fallback(t, variant)(feats, batch)
        # Inside a batcher flush: the trunk-once fan-out becomes visible
        # on every member request's trace (no-op otherwise).
        obs_trace.annotate_flush(
            program=(
                trunk_prog.key
                if trunk_prog is not None
                else f"{self.name}/trunk/b{b}/{variant}:jit"
            ),
            aot=trunk_prog is not None and aot_heads,
            variant=variant,
            heads=",".join(tasks),
        )
        if account:
            self._account(tuple(tasks), trunk_flops)
        return outs

    def picker_forward(self, x: Any) -> Any:
        """(N, window, C) -> (N, window, 3) dpk probabilities — the warm
        forward ops/stream.annotate drives for /annotate on a group.
        ``x`` may be a device array (stream feeds jnp chunks); fanout
        only reads its shape, so no host round-trip happens here."""
        return self.fanout(x, ("dpk",), "fp32")["dpk"]

    def _account(self, tasks: Tuple[str, ...], trunk_flops: float) -> None:
        saved = trunk_flops * max(len(tasks) - 1, 0)
        with self._lock:
            self._trunk_runs += 1
            for t in tasks:
                self._head_runs[t] = self._head_runs.get(t, 0) + 1
            self._flops_saved += saved
        from seist_tpu.obs.bus import BUS

        BUS.counter("serve_trunk_runs", model=self.name).inc()
        for t in tasks:
            BUS.counter("serve_head_runs", model=self.name, task=t).inc()
        if saved:
            BUS.counter("serve_trunk_flops_saved", model=self.name).inc(saved)

    def fanout_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "trunk_runs": self._trunk_runs,
                "head_runs": dict(self._head_runs),
                "trunk_flops_saved": self._flops_saved,
                "tasks": list(self.tasks),
                "variants": {
                    v: list(self.variant_tasks.get(v, ()))
                    for v in self.variants
                },
            }

    # ------------------------------------------------------------ warm-up
    def build_programs(
        self, buckets: Sequence[int], report: List[Dict[str, Any]]
    ) -> None:
        import jax
        import jax.numpy as jnp

        for variant in self.variants:
            trunk_fn = self._make_fn("trunk", variant)
            for b in buckets:
                xs = jax.ShapeDtypeStruct(
                    (b, self.window, self.in_channels), jnp.float32
                )
                prog = aot.aot_compile(
                    f"{self.name}/trunk/b{b}/{variant}",
                    trunk_fn,
                    [(xs.shape, xs.dtype)],
                    model=self.name,
                )
                self.programs[(variant, "trunk", b)] = prog
                report.append({
                    "model": self.name, "batch": b, "variant": variant,
                    "seconds": prog.compile_ms / 1e3, "program": prog.key,
                })
                feats_struct = jax.eval_shape(trunk_fn, xs)
                for t in self.tasks:
                    hp = aot.aot_compile(
                        f"{self.name}/head:{t}/b{b}/{variant}",
                        self._make_fn(t, variant),
                        [
                            (feats_struct.shape, feats_struct.dtype),
                            (xs.shape, xs.dtype),
                        ],
                        model=self.name,
                    )
                    self.programs[(variant, t, b)] = hp
                    report.append({
                        "model": self.name, "batch": b, "variant": variant,
                        "seconds": hp.compile_ms / 1e3, "program": hp.key,
                    })
                logger.info(
                    f"[serve] aot {self.name} b={b} {variant}: trunk+"
                    f"{len(self.tasks)} heads compiled"
                )
        self._gate_variants(buckets[0])

    def _gate_variants(self, probe_bucket: int) -> None:
        probe = _probe_input(probe_bucket, self.window, self.in_channels)
        ref = self.fanout(probe, self.tasks, "fp32", account=False)
        for variant in self.variants:
            if variant == "fp32":
                continue
            out = self.fanout(probe, self.tasks, variant, account=False)
            ok_tasks = []
            for t in self.tasks:
                head = self.heads[t]
                kind, _ = aot.parity_kind(head.spec)
                ok, err = aot.variant_parity(
                    _first_leaf(ref[t]),
                    _first_leaf(out[t]),
                    variant,
                    kind=kind,
                    # jaxlint: disable=host-sync-hot-path -- host-side
                    # python float config, one-shot load-time gate
                    scale=float(head.head_scale or 1.0),
                )
                if ok:
                    ok_tasks.append(t)
                logger.info(
                    f"[serve] variant gate {self.name}/{t}/{variant}: "
                    f"{'ok' if ok else 'DISABLED'} (err={err:.2g}, {kind})"
                )
            self.variant_tasks[variant] = tuple(ok_tasks)


def _probe_input(b: int, window: int, in_channels: int) -> np.ndarray:
    """Deterministic parity-gate probe: unit-variance noise, the same
    distribution /predict feeds after std normalization."""
    rng = np.random.default_rng(0)
    return rng.standard_normal((b, window, in_channels)).astype(np.float32)


def _first_leaf(out: Any) -> Any:
    """Parity gates compare the primary output (tuple heads: the first —
    e.g. ditingmotion's polarity)."""
    return out[0] if isinstance(out, (tuple, list)) else out


def load_model_entry(
    model_name: str,
    checkpoint: str = "",
    *,
    window: int = 8192,
    seed: int = 0,
    variants: Sequence[str] = ("fp32",),
) -> ModelEntry:
    """Create + restore one model for inference.

    Without ``checkpoint`` the model serves freshly-initialized weights
    (tests / smoke runs); with one, params (+ BN stats when present) are
    restored the same way demo_predict.py and tools/predict.py always did
    — that logic now lives only here.
    """
    model, variables, spec, in_channels, channel0 = _load_parts(
        model_name, checkpoint, window=window, seed=seed
    )

    def apply_fn(x):
        return model.apply(variables, x, train=False)

    import jax

    return ModelEntry(
        name=model_name,
        model=model,
        variables=variables,
        spec=spec,
        window=window,
        in_channels=in_channels,
        channel0=channel0,
        forward=jax.jit(apply_fn),
        apply=apply_fn,
        checkpoint=checkpoint,
        variants=_check_variants(variants),
    )


def load_group_entry(
    group_name: str,
    task_entries: Sequence[Tuple[str, str]],
    *,
    window: int = 8192,
    seed: int = 0,
    variants: Sequence[str] = ("fp32",),
) -> MultiTaskEntry:
    """Build one shared-trunk task group: ``group_name`` is the SeisT
    size prefix (e.g. ``seist_s``); each (task, checkpoint) loads
    ``<group_name>_<task>``. Trunk weights come from the FIRST listed
    task's checkpoint (heads trained against a common trunk per the
    paper's design); every head's variable tree shares those arrays."""
    from seist_tpu.models.seist import supports_trunk_split

    if not task_entries:
        raise ValueError(f"group '{group_name}' needs at least one task")
    heads: Dict[str, TaskHead] = {}
    order: List[str] = []
    trunk_model = None
    trunk_vars: Dict[str, Any] = {}
    in_channels = None
    for task, ckpt in task_entries:
        if task not in TASKS:
            raise ValueError(
                f"unknown task '{task}' in group '{group_name}'; "
                f"tasks are {list(TASKS)}"
            )
        if task in heads:
            raise ValueError(f"duplicate task '{task}' in '{group_name}'")
        model_name = f"{group_name}_{task}"
        model, variables, spec, chans, channel0 = _load_parts(
            model_name, ckpt, window=window, seed=seed
        )
        if not supports_trunk_split(model):
            raise ValueError(
                f"model '{model_name}' has no trunk/head split; groups "
                "support the SeisT family only"
            )
        if in_channels is None:
            in_channels = chans
        elif chans != in_channels:
            raise ValueError(
                f"group '{group_name}': task '{task}' wants {chans} input "
                f"channels, group has {in_channels}"
            )
        if trunk_model is None:
            trunk_model = model
            trunk_vars = {
                col: {k: v for k, v in tree.items() if k != "out_head"}
                for col, tree in variables.items()
            }
        merged: Dict[str, Any] = {}
        for col in set(variables) | set(trunk_vars):
            base = dict(trunk_vars.get(col, {}))
            own = variables.get(col, {})
            if "out_head" in own:
                base["out_head"] = own["out_head"]
            merged[col] = base
        heads[task] = TaskHead(
            task=task,
            name=model_name,
            model=model,
            variables=merged,
            spec=spec,
            channel0=channel0,
            # jaxlint: disable=host-sync-hot-path -- module-attribute
            # float, one-shot load-time coercion
            head_scale=float(getattr(model, "head_scale", 1.0) or 1.0),
        )
        order.append(task)
    return MultiTaskEntry(
        name=group_name,
        window=window,
        in_channels=int(in_channels),
        tasks=tuple(order),
        heads=heads,
        trunk_model=trunk_model,
        trunk_variables=trunk_vars,
        task_checkpoints={task: ckpt for task, ckpt in task_entries},
        variants=_check_variants(variants),
    )


def _check_variants(variants: Sequence[str]) -> Tuple[str, ...]:
    out = tuple(dict.fromkeys(variants))  # dedup, keep order
    bad = [v for v in out if v not in aot.VARIANTS]
    if bad:
        raise ValueError(f"unknown variants {bad}; use {list(aot.VARIANTS)}")
    if "fp32" not in out:
        out = ("fp32",) + out  # fp32 is the reference; always served
    return out


class ModelPool:
    """Loaded entries keyed by model/group name + the warm-up that
    AOT-compiles all serving programs up front. :meth:`reload` hot-swaps
    one entry for a new checkpoint after the candidate passes the same
    load-time gates."""

    def __init__(
        self,
        entries: Sequence[Tuple[str, str]] = (),
        *,
        window: int = 8192,
        seed: int = 0,
        groups: Optional[
            Sequence[Tuple[str, Sequence[Tuple[str, str]]]]
        ] = None,
        variants: Sequence[str] = ("fp32",),
        version: int = 1,
    ):
        if not entries and not groups:
            raise ValueError(
                "ModelPool needs at least one (name, checkpoint) entry "
                "or one task group"
            )
        self._window = window
        self._seed = seed
        self._variants = tuple(variants)
        self._reload_lock = threading.Lock()  # one candidate at a time
        # Guards the entry dict + warmup_report only (microseconds): the
        # request path reads under it on every lookup, so the minutes of
        # candidate compiles in reload() must happen OUTSIDE it — the
        # swap itself is the only write it covers.
        self._entries_lock = threading.Lock()
        self._entries: Dict[str, Any] = {}
        for name, ckpt in entries:
            if name in self._entries:
                raise ValueError(f"duplicate model '{name}' in pool")
            self._entries[name] = load_model_entry(
                name, ckpt, window=window, seed=seed, variants=variants
            )
        for group_name, task_entries in groups or ():
            if group_name in self._entries:
                raise ValueError(f"duplicate model '{group_name}' in pool")
            self._entries[group_name] = load_group_entry(
                group_name, task_entries, window=window, seed=seed,
                variants=variants,
            )
        version = int(version)
        for entry in self._entries.values():
            entry.version = version
        self.warmup_report: List[Dict[str, Any]] = []
        self._publish_versions()

    def _publish_versions(self) -> None:
        """The served version per entry as a scrapeable gauge — the fleet
        aggregator (and anyone watching a roll converge) reads
        ``serve_model_version{model=}`` instead of grepping logs."""
        from seist_tpu.obs.bus import BUS

        for name, version in self.versions().items():
            BUS.gauge("serve_model_version", model=name).set(version)

    def names(self) -> List[str]:
        with self._entries_lock:
            return list(self._entries)

    def get(self, name: Optional[str]) -> Any:
        with self._entries_lock:
            if name is None:
                if len(self._entries) == 1:
                    return next(iter(self._entries.values()))
                names = list(self._entries)
            else:
                entry = self._entries.get(name)
                if entry is not None:
                    return entry
                names = list(self._entries)
        if name is None:
            raise BadRequest(
                f"'model' is required when several are loaded: {names}"
            )
        raise UnknownModel(
            f"model '{name}' not loaded; available: {names}"
        )

    def versions(self) -> Dict[str, int]:
        """{entry name: served model version} — the /healthz/ready
        payload the router's prober reads for canary cohorts and the
        fleet supervisor polls during a rolling restart."""
        with self._entries_lock:
            entries = dict(self._entries)
        return {name: entry.version for name, entry in entries.items()}

    def warm_entry(
        self, entry: Any, buckets: Sequence[int]
    ) -> List[Dict[str, Any]]:
        """AOT-compile one entry's (bucket x program x variant) table +
        warm its decode programs; returns the per-program compile report.
        Shared by start-up :meth:`warmup` and :meth:`reload` (a candidate
        passes the SAME gates the boot path does)."""
        from seist_tpu.utils.profiling import stopwatch

        report: List[Dict[str, Any]] = []
        buckets = sorted(set(int(b) for b in buckets))
        entry.build_programs(buckets, report)
        # Warm the postprocess programs too (pick_peaks/detect_events
        # jit on static topk/min_peak_dist — defaults compiled here),
        # and prove every executable answers end to end.
        x = np.zeros(
            (buckets[-1], entry.window, entry.in_channels), np.float32
        )
        if entry.is_group:
            outs = entry.fanout(x, entry.tasks, "fp32", account=False)
            _block(list(outs.values()))
            for t in entry.tasks:
                with stopwatch() as elapsed:
                    decode_outputs(
                        entry.heads[t],
                        _slice_outputs(outs[t], 0),
                        PredictOptions(),
                    )
                report.append({
                    "model": entry.name, "batch": f"decode:{t}",
                    "seconds": elapsed(),
                })
        else:
            out = entry.run(x, "fp32")
            _block(out)
            with stopwatch() as elapsed:
                decode_outputs(
                    entry, _slice_outputs(out, 0), PredictOptions()
                )
            report.append({
                "model": entry.name, "batch": "decode",
                "seconds": elapsed(),
            })
        return report

    def warmup(self, buckets: Sequence[int]) -> List[Dict[str, Any]]:
        """AOT-compile every (bucket, program, variant) for every entry +
        warm the default decode programs; returns per-program compile
        timings (also kept on ``self.warmup_report`` for /healthz)."""
        with self._entries_lock:
            entries = list(self._entries.values())
        report: List[Dict[str, Any]] = []
        for entry in entries:
            report.extend(self.warm_entry(entry, buckets))
        with self._entries_lock:
            self.warmup_report = report
        return report

    # ------------------------------------------------------------- reload
    def reload(
        self,
        name: Optional[str],
        *,
        buckets: Sequence[int],
        checkpoint: Optional[str] = None,
        checkpoints: Optional[Mapping[str, str]] = None,
        version: Optional[int] = None,
        force_gate_failure: bool = False,
    ) -> Tuple[Any, List[Dict[str, Any]]]:
        """Hot-swap one entry for a new checkpoint, zero downtime.

        The candidate is loaded BESIDE the incumbent, then must clear the
        full gate ladder before any traffic shifts:

        1. checkpoint structural compatibility (``_load_parts`` →
           :class:`IncompatibleCheckpoint` naming the first bad path);
        2. the PR 10 AOT compile of every (bucket x program x variant)
           plus decode warm-up — any build/compile crash is a
           :class:`ReloadFailed`, never a half-swapped pool;
        3. variant parity gates re-run against the NEW weights; every
           variant (and, for groups, every task x variant) the incumbent
           currently serves must pass — a reload must not silently shrink
           the served surface (:class:`ParityGateFailed`);
        4. an fp32 finite-output probe (a checkpoint of NaNs compiles
           fine; it must still not serve).

        Only full success swaps the pool entry — atomically, under the
        entry dict's single-assignment semantics, so requests in flight
        keep the incumbent and the next batcher flush picks up the
        candidate. Any failure leaves the incumbent serving, unchanged,
        and raises the structured error (the PR 10 "disable, don't serve
        wrong" contract extended to reload).

        ``force_gate_failure`` is the SEIST_FAULT_SERVE_BAD_CANDIDATE
        chaos hook: the fully-built candidate is rejected at step 4, so
        rollback paths are exercisable on demand.
        """
        from seist_tpu.obs.bus import BUS

        with self._reload_lock:
            incumbent = self.get(name)
            name = incumbent.name
            target = int(version) if version is not None else (
                incumbent.version + 1
            )
            if target <= incumbent.version:
                raise BadRequest(
                    f"version must be > the served version "
                    f"{incumbent.version}, got {target} (versions are "
                    "monotonic)"
                )
            try:
                candidate = self._build_candidate(
                    incumbent, checkpoint, checkpoints
                )
                report = self.warm_entry(candidate, buckets)
            except ServeError:
                raise
            except Exception as e:  # noqa: BLE001 — incumbent must survive
                # Anything the candidate build throws (compile OOM, XLA
                # error, bad file) dies HERE, beside the incumbent — the
                # request path never saw the candidate.
                raise ReloadFailed(
                    f"candidate build failed for '{name}': {e!r}"
                ) from e
            self._gate_candidate(incumbent, candidate, force_gate_failure)
            candidate.version = target
            with self._entries_lock:  # the atomic swap
                self._entries[name] = candidate
                # The swapped-out generation's rows leave with it: a
                # replica hot-reloading for weeks must not grow its
                # /healthz payload (or mix long-gone versions into it).
                self.warmup_report = [
                    r for r in self.warmup_report
                    if r.get("model") != name
                ] + [dict(r, reload_version=target) for r in report]
            BUS.gauge("serve_model_version", model=name).set(target)
            logger.info(
                f"[serve] reload '{name}': version {incumbent.version} -> "
                f"{target} ({len(report)} programs rebuilt)"
            )
            return candidate, report

    def _build_candidate(
        self,
        incumbent: Any,
        checkpoint: Optional[str],
        checkpoints: Optional[Mapping[str, str]],
    ) -> Any:
        if incumbent.is_group:
            if checkpoint is not None:
                raise BadRequest(
                    f"'{incumbent.name}' is a task group; use "
                    "'checkpoints': {task: ckpt} instead of 'checkpoint'"
                )
            ckpts = dict(incumbent.task_checkpoints)
            for task, ckpt in (checkpoints or {}).items():
                if task not in ckpts:
                    raise BadRequest(
                        f"group '{incumbent.name}' does not serve task "
                        f"'{task}'; serves {list(incumbent.tasks)}"
                    )
                ckpts[task] = ckpt
            return load_group_entry(
                incumbent.name,
                [(t, ckpts[t]) for t in incumbent.tasks],
                window=self._window, seed=self._seed,
                variants=self._variants,
            )
        if checkpoints is not None:
            raise BadRequest(
                f"'{incumbent.name}' is single-task; use 'checkpoint', "
                "not 'checkpoints'"
            )
        ckpt = checkpoint if checkpoint is not None else incumbent.checkpoint
        return load_model_entry(
            incumbent.name, ckpt, window=self._window, seed=self._seed,
            variants=self._variants,
        )

    def _gate_candidate(
        self, incumbent: Any, candidate: Any, force_gate_failure: bool
    ) -> None:
        """Reload acceptance: the candidate must serve at least the
        incumbent's variant surface and answer finite fp32 outputs."""
        if candidate.is_group:
            for variant in incumbent.variants:
                served = set(incumbent.variant_tasks.get(variant, ()))
                cand = set(candidate.variant_tasks.get(variant, ()))
                missing = sorted(served - cand)
                if missing:
                    raise ParityGateFailed(
                        f"candidate for group '{incumbent.name}' failed "
                        f"the '{variant}' parity gate for task(s) "
                        f"{missing} the incumbent serves"
                    )
        else:
            served = set(incumbent.supported_variants())
            cand = set(candidate.supported_variants())
            missing = sorted(served - cand)
            if missing:
                raise ParityGateFailed(
                    f"candidate for '{incumbent.name}' failed the parity "
                    f"gate for variant(s) {missing} the incumbent serves"
                )
        probe = _probe_input(1, candidate.window, candidate.in_channels)
        if candidate.is_group:
            outs = candidate.fanout(
                probe, candidate.tasks, "fp32", account=False
            )
            finite = all(
                aot.outputs_finite(outs[t]) for t in candidate.tasks
            )
        else:
            finite = aot.outputs_finite(candidate.run(probe, "fp32"))
        if not finite:
            raise ParityGateFailed(
                f"candidate for '{incumbent.name}' produced non-finite "
                "fp32 probe outputs — refusing to serve it"
            )
        if force_gate_failure:
            raise ParityGateFailed(
                f"candidate for '{incumbent.name}' rejected by injected "
                "fault (SEIST_FAULT_SERVE_BAD_CANDIDATE)"
            )


def decode_outputs(
    entry: Any, outputs: Any, opts: PredictOptions
) -> Dict[str, Any]:
    """One request's raw model outputs (leading dim 1) -> JSON-able result.

    ``entry`` is a ModelEntry or a group's TaskHead (same duck type:
    name/spec/is_picker). Picking heads route through ops/postprocess
    (same programs the eval loop uses); VALUE heads go through the task
    spec's results transform (e.g. magnet's mean-only, baz's
    (cos,sin)->degrees decode); ONEHOT heads report argmax class + raw
    scores.
    """
    from seist_tpu import taskspec
    from seist_tpu.ops.postprocess import process_outputs

    spec = entry.spec
    if entry.is_picker:
        res = process_outputs(
            outputs,
            spec.labels,
            opts.sampling_rate,
            ppk_threshold=opts.ppk_threshold,
            spk_threshold=opts.spk_threshold,
            det_threshold=opts.det_threshold,
            min_peak_dist=opts.min_peak_dist,
            max_detect_event_num=opts.max_events,
        )
        import jax

        # ONE device->host round trip for every head (the Metrics.to_dict
        # batched-get idiom): the per-kind np.asarray calls below then
        # slice plain host arrays instead of paying a sync each.
        res = jax.device_get(res)
        fs = float(opts.sampling_rate)
        out: Dict[str, Any] = {"task": "picking"}
        for kind in ("ppk", "spk"):
            # jaxlint: disable=host-sync-hot-path -- host numpy; already
            # device_get'd above in one batched transfer
            idxs = np.asarray(res[kind])[0]
            idxs = idxs[idxs >= 0]
            out[kind] = [
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                {"sample": int(i), "time_s": round(i / fs, 6)} for i in idxs
            ]
        if "det" in res:
            pairs = np.asarray(res["det"])[0].reshape(-1, 2)
            pairs = pairs[pairs[:, 1] >= pairs[:, 0]]
            out["det"] = [
                {"onset": int(a), "offset": int(b),
                 "onset_s": round(a / fs, 6), "offset_s": round(b / fs, 6)}
                for a, b in pairs
            ]
        return out

    import jax

    transform = spec.outputs_transform_for_results
    outs = transform(outputs) if transform else outputs
    outs_list = outs if isinstance(outs, (tuple, list)) else [outs]
    # One batched transfer for every label's head output; the np.asarray
    # in the loop below is then a host-side no-op.
    outs_list = jax.device_get(list(outs_list))
    if len(outs_list) != len(spec.labels):
        # Server-side model/spec mismatch, not a client error — 500.
        raise ServeError(
            f"model '{entry.name}' produced {len(outs_list)} outputs for "
            f"{len(spec.labels)} labels"
        )
    out = {"task": "regression"}
    for name, arr in zip(spec.labels, outs_list):
        # jaxlint: disable=host-sync-hot-path -- host numpy; already
        # device_get'd above in one batched transfer
        arr = np.asarray(arr)
        if name in taskspec.IO_ITEMS and taskspec.get_kind(name) == taskspec.ONEHOT:
            out["task"] = "classification"
            scores = arr.reshape(-1)
            out[name] = {
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                "class": int(np.argmax(scores)),
                # jaxlint: disable=host-sync-hot-path -- host numpy;
                # already device_get'd above
                "scores": [float(s) for s in scores],
            }
        else:
            # jaxlint: disable=host-sync-hot-path -- host numpy; already
            # device_get'd above
            out[name] = float(arr.reshape(-1)[0])
    return out


def _block(out: Any) -> None:
    """Wait for device completion so warm-up timings mean something."""
    for o in out if isinstance(out, (tuple, list)) else [out]:
        if isinstance(o, (tuple, list)):
            _block(o)
        else:
            getattr(o, "block_until_ready", lambda: None)()
