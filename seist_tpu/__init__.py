"""seist_tpu — a TPU-native (JAX/XLA/pjit) seismic-monitoring deep-learning
framework with the capabilities of senli1073/SeisT.

Layout:
    seist_tpu.taskspec   task specs + io-item catalog   (replaces config.py)
    seist_tpu.registry   model/dataset registries       (replaces _factory.py x2)
    seist_tpu.data       datasets, preprocessing, input pipeline
    seist_tpu.models     Flax model zoo + losses + checkpointing
    seist_tpu.ops        on-device postprocess (picking/trigger) + metrics
    seist_tpu.parallel   mesh construction, sharding, multi-host init
    seist_tpu.train      jitted train/eval loops, LR schedules
    seist_tpu.serve      online inference service (micro-batching + HTTP)
    seist_tpu.utils      logger, meters, misc
"""

__version__ = "0.1.0"

#: Package-root namespaces resolved lazily (PEP 562). An eager import
#: here would pull jax into EVERY process that touches any seist_tpu
#: submodule — including the model-free serving front tier
#: (serve/router.py, serve/shed.py, tools/supervise_fleet.py), which
#: must start on boxes with no accelerator stack installed at all.
_LAZY_SUBMODULES = ("registry", "taskspec")


def __getattr__(name: str):
    if name in _LAZY_SUBMODULES:
        import importlib

        module = importlib.import_module(f"seist_tpu.{name}")
        globals()[name] = module
        return module
    raise AttributeError(f"module 'seist_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY_SUBMODULES))


def load_all(validate: bool = True) -> None:
    """Import all model/dataset modules (running their registrations) and
    validate task specs — the counterpart of the reference's import-time
    ``Config.check_and_init()`` (config.py:435)."""
    import seist_tpu.models  # noqa: F401
    import seist_tpu.data  # noqa: F401

    if validate:
        from seist_tpu import taskspec

        taskspec.validate()
