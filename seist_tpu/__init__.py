"""seist_tpu — a TPU-native (JAX/XLA/pjit) seismic-monitoring deep-learning
framework with the capabilities of senli1073/SeisT.

Layout:
    seist_tpu.taskspec   task specs + io-item catalog   (replaces config.py)
    seist_tpu.registry   model/dataset registries       (replaces _factory.py x2)
    seist_tpu.data       datasets, preprocessing, input pipeline
    seist_tpu.models     Flax model zoo + losses + checkpointing
    seist_tpu.ops        on-device postprocess (picking/trigger) + metrics
    seist_tpu.parallel   mesh construction, sharding, multi-host init
    seist_tpu.train      jitted train/eval loops, LR schedules
    seist_tpu.serve      online inference service (micro-batching + HTTP)
    seist_tpu.utils      logger, meters, misc
"""

__version__ = "0.1.0"

from seist_tpu import registry, taskspec  # noqa: F401


def load_all(validate: bool = True) -> None:
    """Import all model/dataset modules (running their registrations) and
    validate task specs — the counterpart of the reference's import-time
    ``Config.check_and_init()`` (config.py:435)."""
    import seist_tpu.models  # noqa: F401
    import seist_tpu.data  # noqa: F401

    if validate:
        taskspec.validate()
