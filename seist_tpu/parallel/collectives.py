"""Collective-traffic accounting from compiled HLO.

The reference's distributed story is NCCL calls whose traffic is invisible
until profiled on a cluster (ref utils/misc.py:103-172). Here the entire
communication schedule is decided by XLA at compile time, so the per-step
collective payload — what will ride the ICI links — can be read directly
off the optimized HLO of the compiled train step, with no hardware at all.

``collective_stats`` parses an ``xla_computation.as_text()`` /
``compiled.as_text()`` dump and returns, per collective kind
(all-reduce, all-gather, reduce-scatter, collective-permute, all-to-all),
the op count and the summed payload bytes (output-shape bytes of each
collective op; ``-start``/``-done`` async pairs are counted once at the
start op). These are payload bytes; actual link traffic per chip for a
ring all-reduce of payload P over N devices is 2*(N-1)/N * P.

Counts are STATIC: a collective inside a ``while``/``scan`` body is
counted once, not per trip — e.g. ring attention's collective-permute
executes axis_size-1 times per step but appears as x1 here. For loop-
carried collectives multiply by the trip count yourself (the DP train
step's gradient/BN all-reduces are loop-free, so its numbers are exact).
"""

from __future__ import annotations

import math
import re
from typing import Dict

_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "s16": 2,
    "u16": 2,
    "f16": 2,
    "bf16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
    "c64": 8,
    "c128": 16,
}

_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# `%x = f32[8,128]{1,0} all-reduce(...)` or tuple-shaped async starts with
# TPU tiled layouts: `%x = (f32[388778]{0:T(1024)}, f32[388778]{0:T(1024)})
# all-gather-start(...)` — the lhs is matched lazily up to the op keyword
# because layout annotations nest parentheses.
_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")
_OP_RE = re.compile(
    r"=\s*(?P<lhs>[^=\n]*?)\s*"
    r"(?P<kind>" + "|".join(_KINDS) + r")(?P<suffix>-start|-done)?\("
)


def _payload_bytes(lhs: str) -> int:
    """Payload of one collective = the LARGEST shape on its lhs.

    Async ``-start`` ops (and TPU sync tuples) carry aliased input/output
    copies of the same buffer in a tuple — summing all elements would
    double-count, and collective-permute-start adds u32 context scalars.
    The largest single shape is the transferred buffer for every kind
    (all-gather's output, all-reduce's buffer, permute's block).
    """
    best = 0
    for dtype, dims in _SHAPE_RE.findall(lhs):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            n = math.prod(int(d) for d in dims.split(",") if d)
        best = max(best, n * _DTYPE_BYTES[dtype])
    return best


def collective_stats(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Per-kind ``{count, bytes}`` for every collective in an HLO dump."""
    stats: Dict[str, Dict[str, int]] = {}
    for m in _OP_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue  # counted at the paired -start
        kind = m.group("kind")
        entry = stats.setdefault(kind, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _payload_bytes(m.group("lhs"))
    return stats


def format_collective_stats(stats: Dict[str, Dict[str, int]]) -> str:
    if not stats:
        return "no collectives"
    parts = [
        f"{kind} x{s['count']} {s['bytes'] / 1e6:.2f} MB"
        for kind, s in sorted(stats.items())
    ]
    total = sum(s["bytes"] for s in stats.values())
    return ", ".join(parts) + f" (total {total / 1e6:.2f} MB/step payload)"
