"""Collective-traffic accounting from compiled HLO.

The reference's distributed story is NCCL calls whose traffic is invisible
until profiled on a cluster (ref utils/misc.py:103-172). Here the entire
communication schedule is decided by XLA at compile time, so the per-step
collective payload — what will ride the ICI links — can be read directly
off the optimized HLO of the compiled train step, with no hardware at all.

``collective_stats`` parses an ``xla_computation.as_text()`` /
``compiled.as_text()`` dump and returns, per collective kind
(all-reduce, all-gather, reduce-scatter, collective-permute, all-to-all),
the op count and the summed payload bytes. Payload of one op = the sum of
its output-shape bytes: XLA's all-reduce combiner merges many gradient
tensors into ONE tuple-shaped op (``(f32[a], f32[b], ...) all-reduce``)
whose elements are all distinct transferred buffers (round 3 counted only
the largest element, undercounting combined gradient all-reduces ~50x —
VERDICT r3 #6). Async ``-start`` ops are the exception: their tuple
repeats the buffer as (aliased input, output, context scalars), so only
the largest element is counted there; ``-done`` pairs are skipped.
These are payload bytes; actual link traffic per chip for a ring
all-reduce of payload P over N devices is 2*(N-1)/N * P.

``collective_ops`` returns the per-op detail (kind, payload, shapes, the
tracing ``op_name`` metadata) so callers can attribute bytes — e.g.
tools/collective_report.py splits gradient all-reduces (tuple elements
matching model param shapes, batch-independent) from activation
gathers/others (batch-dependent).

Counts are STATIC: a collective inside a ``while``/``scan`` body is
counted once, not per trip — e.g. ring attention's collective-permute
executes axis_size-1 times per step but appears as x1 here. For loop-
carried collectives multiply by the trip count yourself (the DP train
step's gradient/BN all-reduces are loop-free, so its numbers are exact).
"""

from __future__ import annotations

import math
import re
from typing import Dict

_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "s16": 2,
    "u16": 2,
    "f16": 2,
    "bf16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
    "c64": 8,
    "c128": 16,
}

_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# `%x = f32[8,128]{1,0} all-reduce(...)` or tuple-shaped async starts with
# TPU tiled layouts: `%x = (f32[388778]{0:T(1024)}, f32[388778]{0:T(1024)})
# all-gather-start(...)`. HLO text is one instruction per line; the lhs is
# everything from the FIRST `=` on the line to the op keyword. (An earlier
# `[^=\n]*?` lhs silently truncated combined-tuple lhs at the `=` inside
# XLA's `/*index=5*/` tuple comments, dropping most gradient tensors from
# combined all-reduces — do not "simplify" this back.)
_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")
_OP_RE = re.compile(
    r"^[^=\n]*=\s*(?P<lhs>.*?)\s*"
    r"(?P<kind>" + "|".join(_KINDS) + r")(?P<suffix>-start|-done)?\(",
    re.M,
)


def _shapes(lhs: str):
    """(dtype, dims-tuple, bytes) for every array shape on an op's lhs."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(lhs):
        if dtype not in _DTYPE_BYTES:
            continue
        d = tuple(int(x) for x in dims.split(",") if x) if dims else ()
        out.append((dtype, d, math.prod(d or (1,)) * _DTYPE_BYTES[dtype]))
    return out


def _payload_bytes(lhs: str, kind: str = "", is_start: bool = False) -> int:
    """Payload of one collective op (see module docstring).

    Sync ops: SUM of lhs shapes — a combined all-reduce's tuple elements
    are distinct transferred buffers. Async ``-start`` ops alias each
    transferred buffer as (input, output) in their lhs tuple:

    * ``all-reduce-start`` — input and output shapes are identical, so
      the payload is exactly SUM/2. This holds for the combined form too
      (``((f32[a], f32[b]), (f32[a], f32[b])) all-reduce-start``), which
      the max rule would undercount the same ~50x way the sync combiner
      bug did.
    * other ``-start`` kinds — the LARGEST shape (all-gather's output /
      reduce-scatter's input / permute's block; their tuples also carry
      non-equal shards and u32 context scalars, so neither sum nor sum/2
      is right). A *combined* async gather/scatter would be undercounted
      here; none appears in this framework's programs today.
    """
    sizes = [b for _, _, b in _shapes(lhs)]
    if not sizes:
        return 0
    if is_start:
        if kind == "all-reduce":
            # The SUM/2 rule assumes the TPU tuple form: the lhs aliases
            # every transferred buffer as (inputs..., outputs...), so the
            # second half of the shape list mirrors the first. Some XLA
            # paths (observed on GPU) emit the start with the bare result
            # only — single shape, or a combined non-aliased tuple — and
            # halving those is a 2x undercount. Only halve when the
            # aliasing structure is actually present. (A bare combined
            # tuple of two identical-size buffers is indistinguishable
            # from the aliased form and is halved; the TPU programs this
            # parser targets always use the aliased form.)
            k = len(sizes) // 2
            if k and len(sizes) % 2 == 0 and sizes[:k] == sizes[k:]:
                return sum(sizes) // 2
            return sum(sizes)
        return max(sizes)
    return sum(sizes)


_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def collective_ops(hlo_text: str):
    """Per-op detail: ``[{kind, bytes, shapes, op_name}]`` for every
    collective (async pairs counted once at the ``-start``)."""
    ops = []
    for m in _OP_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue
        line_end = hlo_text.find("\n", m.end())
        rest = hlo_text[m.end() : line_end if line_end != -1 else len(hlo_text)]
        name = _OPNAME_RE.search(rest)
        is_start = m.group("suffix") == "-start"
        ops.append(
            {
                "kind": m.group("kind"),
                "bytes": _payload_bytes(
                    m.group("lhs"), m.group("kind"), is_start
                ),
                "shapes": [
                    f"{dt}{list(d)}" for dt, d, _ in _shapes(m.group("lhs"))
                ],
                "shape_dims": [d for _, d, _ in _shapes(m.group("lhs"))],
                "op_name": name.group(1) if name else "",
            }
        )
    return ops


def collective_stats(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Per-kind ``{count, bytes}`` for every collective in an HLO dump."""
    stats: Dict[str, Dict[str, int]] = {}
    for op in collective_ops(hlo_text):
        entry = stats.setdefault(op["kind"], {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += op["bytes"]
    return stats


def format_collective_stats(stats: Dict[str, Dict[str, int]]) -> str:
    if not stats:
        return "no collectives"
    parts = [
        f"{kind} x{s['count']} {s['bytes'] / 1e6:.2f} MB"
        for kind, s in sorted(stats.items())
    ]
    total = sum(s["bytes"] for s in stats.values())
    return ", ".join(parts) + f" (total {total / 1e6:.2f} MB/step payload)"
