"""Parallelism layer: device mesh, shardings, multi-host helpers."""

from seist_tpu.parallel.collectives import (  # noqa: F401
    collective_stats,
    format_collective_stats,
)
from seist_tpu.parallel.dist import (  # noqa: F401
    barrier,
    broadcast_object,
    init_distributed_mode,
    is_dist_avail_and_initialized,
    is_main_process,
    process_count,
    process_index,
)
from seist_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    MESH_AXES,
    batch_sharding,
    batch_spec,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
    shard_stacked_batch,
)
