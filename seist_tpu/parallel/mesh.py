"""Device mesh + sharding helpers.

TPU-native replacement for the reference's NCCL/DDP layer
(/root/reference/utils/misc.py:103-172, training/train.py:367-374). Instead of
wrapping the model in DDP and hand-placing collectives, we declare a
`jax.sharding.Mesh` and annotate data/parameter shardings; XLA inserts the
gradient all-reduce (over ICI intra-slice, DCN across slices) when the train
step is jit-compiled.

Axis convention (fixed, in this order):

* ``data``  — batch (data parallel). The only axis the SeisT-scale models
  *need* (the reference implements exactly one strategy, DDP — SURVEY §2.4).
* ``model`` — tensor-parallel axis, size 1 by default. Kept first-class so
  channel-sharded variants can be added without re-plumbing.
* ``seq``   — sequence/context-parallel axis, size 1 by default. Ring
  attention / sequence sharding for very long waveforms rides this axis
  (see seist_tpu/ops/ring_attention.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
MESH_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_SEQ)

# Trace-time active mesh: models consult this to route through
# mesh-axis-aware paths (e.g. SeisT attention -> ring attention when
# ``seq`` > 1, --seq-shards). Set once by the worker (set_active_mesh) or
# scoped in tests (use_mesh).
#
# CAVEAT: this is read at TRACE time and is NOT part of any jit cache key.
# A function jitted under one mesh keeps that routing even if the active
# mesh changes later — always (re)build/jit step functions AFTER setting
# the mesh, as train_worker/test_worker do. Don't reuse a jitted step
# across different active meshes.
_ACTIVE_MESH: list = [None]


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    _ACTIVE_MESH[0] = mesh


def active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH[0]


from contextlib import contextmanager  # noqa: E402


@contextmanager
def use_mesh(mesh: Optional[Mesh]):
    old = _ACTIVE_MESH[0]
    _ACTIVE_MESH[0] = mesh
    try:
        yield
    finally:
        _ACTIVE_MESH[0] = old


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a ``(data, model, seq)`` mesh over ``devices``.

    ``data=None`` consumes all remaining devices. On real TPU slices
    ``mesh_utils.create_device_mesh`` lays the axes onto the physical torus so
    the heaviest-traffic axis rides ICI neighbors.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None:
        if n % (model * seq):
            raise ValueError(f"{n} devices not divisible by model*seq={model * seq}")
        data = n // (model * seq)
    if data * model * seq != n:
        raise ValueError(
            f"mesh shape {(data, model, seq)} != device count {n}"
        )
    dev_mesh = mesh_utils.create_device_mesh(
        (data, model, seq), devices=np.asarray(devices)
    )
    return Mesh(dev_mesh, MESH_AXES)


def batch_spec(extra_axes: int = 0) -> P:
    """PartitionSpec sharding the leading (batch) axis over ``data``."""
    return P(AXIS_DATA, *([None] * extra_axes))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS_DATA))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch: Any, spec: Optional[P] = None) -> Any:
    """Place a host batch pytree with the leading axis sharded on ``data``
    (the `DistributedSampler`-equivalent placement). Single-process: a plain
    sharded device_put of the full batch. Multi-host: each host passes its
    *local* shard and the global array is assembled without gathering.
    ``spec`` overrides the partitioning (default ``P('data', ...)``)."""
    sharding = (
        NamedSharding(mesh, spec) if spec is not None else batch_sharding(mesh)
    )
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
            batch,
        )
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def shard_stacked_batch(mesh: Mesh, batch: Any) -> Any:
    """:func:`shard_batch` for k-stacked micro-batches ``(k, B, ...)``:
    axis 0 is the micro-step axis (replicated), axis 1 is the batch axis
    (sharded on ``data``). Used by the --steps-per-call train path."""
    return shard_batch(mesh, batch, spec=P(None, AXIS_DATA))


def to_local(x: Any) -> np.ndarray:
    """Materialize this host's rows of a batch-sharded array as numpy.

    Single-process: the whole array. Multi-host: the addressable shards in
    global-index order — the same rows (same order) this host fed in via
    :func:`shard_batch` / the input pipeline. Replication over other mesh
    axes (model/seq) makes several local devices hold the same row range —
    deduped by range start so each row appears once.
    """
    if isinstance(x, np.ndarray):
        return x
    if jax.process_count() <= 1 or not hasattr(x, "addressable_shards"):
        return np.asarray(x)
    by_start = {}
    for s in x.addressable_shards:
        start = s.index[0].start or 0
        by_start.setdefault(start, s)
    shards = [by_start[k] for k in sorted(by_start)]
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


def replicate(mesh: Mesh, tree: Any) -> Any:
    """Fully replicate a pytree (params/optimizer state) over the mesh."""
    sharding = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
