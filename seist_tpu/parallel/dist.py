"""Multi-host process-group helpers.

Replaces the reference's torchrun/NCCL rendezvous
(/root/reference/utils/misc.py:143-172): `jax.distributed.initialize` reads
the coordinator address + process count from the environment (or TPU metadata)
and wires the hosts into one JAX runtime; collectives then ride ICI/DCN via
the compiled programs — there is no user-visible process group object.

Rank-0-only conventions (printing, checkpoint writes, result CSVs) mirror the
reference's `is_main_process` guards (misc.py:73-100, train.py:192,288,407).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def init_distributed_mode(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the multi-host runtime if a multi-host env is detected.

    Env contract mirrors the reference's env-var rendezvous
    (misc.py:143-152): set ``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``,
    ``PROCESS_ID`` (or pass explicitly). On Cloud TPU pods all three resolve
    automatically from metadata, so a bare call works too.

    Returns True when distributed mode was initialized.
    """
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    explicit = coordinator_address is not None
    tpu_hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    auto_tpu = len([h for h in tpu_hosts.split(",") if h]) > 1
    if not (explicit or auto_tpu):
        return False

    # No silent fallback: both trigger conditions (explicit coordinator, or
    # >1 worker in TPU metadata) mean a genuinely multi-host launch, and a
    # host that degrades to single-process would strand the others inside
    # initialize() and corrupt shared checkpoint dirs.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_dist_avail_and_initialized() -> bool:
    return jax.process_count() > 1


def is_main_process() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until all hosts reach this point (ref: dist.barrier())."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def broadcast_object(obj: Any) -> Any:
    """Broadcast any picklable host-side python object from process 0 to all
    (ref: misc.py:134-140 broadcast_object_list).

    ``multihost_utils.broadcast_one_to_all`` only moves numeric arrays, so
    the object is pickled to a uint8 buffer; the length is broadcast first so
    every host allocates the same padded shape.
    """
    if jax.process_count() <= 1:
        return obj
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    length = int(
        multihost_utils.broadcast_one_to_all(np.int64(payload.size))
    )
    buf = np.zeros(length, dtype=np.uint8)
    if jax.process_index() == 0:
        buf[: payload.size] = payload
    buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return pickle.loads(buf.tobytes())
