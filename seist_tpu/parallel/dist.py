"""Multi-host process-group helpers.

Replaces the reference's torchrun/NCCL rendezvous
(/root/reference/utils/misc.py:143-172): `jax.distributed.initialize` reads
the coordinator address + process count from the environment (or TPU metadata)
and wires the hosts into one JAX runtime; collectives then ride ICI/DCN via
the compiled programs — there is no user-visible process group object.

Rank-0-only conventions (printing, checkpoint writes, result CSVs) mirror the
reference's `is_main_process` guards (misc.py:73-100, train.py:192,288,407).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def init_distributed_mode(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the multi-host runtime if a multi-host env is detected.

    Env contract mirrors the reference's env-var rendezvous
    (misc.py:143-152): set ``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``,
    ``PROCESS_ID`` (or pass explicitly). On Cloud TPU pods all three resolve
    automatically from metadata, so a bare call works too.

    Returns True when distributed mode was initialized.
    """
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    explicit = coordinator_address is not None
    tpu_hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    auto_tpu = len([h for h in tpu_hosts.split(",") if h]) > 1
    if not (explicit or auto_tpu):
        return False

    # No silent fallback: both trigger conditions (explicit coordinator, or
    # >1 worker in TPU metadata) mean a genuinely multi-host launch, and a
    # host that degrades to single-process would strand the others inside
    # initialize() and corrupt shared checkpoint dirs.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_dist_avail_and_initialized() -> bool:
    return jax.process_count() > 1


def is_main_process() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until all hosts reach this point (ref: dist.barrier())."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


#: broadcast_object call ordinal — every process calls broadcast_object
#: in the same program order (it is a collective), so a per-process
#: counter yields matching KV keys without any extra coordination.
_broadcast_seq = 0
_BROADCAST_TIMEOUT_MS = 300_000


def _coordination_client():
    """The jax distributed coordination-service client (the same KV store
    ``jax.distributed.initialize`` rendezvouses through), or None outside
    an initialized multi-process runtime."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — private API; any change = fallback
        return None


def broadcast_object(obj: Any) -> Any:
    """Broadcast any picklable host-side python object from process 0 to all
    (ref: misc.py:134-140 broadcast_object_list).

    Transport is the coordination-service KV store, NOT an XLA collective:
    process 0 publishes the pickle under a sequenced key, everyone else
    blocks on that key. Host-side control data (checkpoint paths, eval
    verdicts) has no business riding device allreduces — and on the CPU
    backend it must not: jaxlib 0.4.37's gloo allreduce intermittently
    returns a zero-prefixed buffer when two differently-shaped broadcasts
    run back-to-back (the seed test_multihost failure's second act; an
    artificial delay between the collectives masks it, which is how it
    escaped notice upstream). The legacy two-phase broadcast_one_to_all
    path remains only for runtimes where the private client API is gone.
    """
    if jax.process_count() <= 1:
        return obj
    import pickle

    client = _coordination_client()
    if client is not None:
        global _broadcast_seq
        key = f"seist_tpu/broadcast_object/{_broadcast_seq}"
        _broadcast_seq += 1
        if jax.process_index() == 0:
            client.key_value_set_bytes(key, pickle.dumps(obj))
            result = obj
        else:
            result = pickle.loads(
                client.blocking_key_value_get_bytes(
                    key, _BROADCAST_TIMEOUT_MS
                )
            )
        # Barrier-then-delete: once every process has read the value,
        # process 0 removes the key. Keys must not outlive the call —
        # they would accumulate over a long run, and a relaunched
        # incarnation restarting its sequence at 0 against a still-live
        # coordinator would read the PREVIOUS run's value for the wrong
        # program point.
        client.wait_at_barrier(key + "/read", _BROADCAST_TIMEOUT_MS)
        if jax.process_index() == 0:
            client.key_value_delete(key)
        return result

    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    length = int(
        multihost_utils.broadcast_one_to_all(np.int64(payload.size))
    )
    buf = np.zeros(length, dtype=np.uint8)
    if jax.process_index() == 0:
        buf[: payload.size] = payload
    buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return pickle.loads(buf.tobytes())
