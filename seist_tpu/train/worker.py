"""Train / validate / test orchestration.

Counterpart of the reference's L6 layer (training/train.py:182-484,
training/validate.py:10-134, training/test.py:10-88), redesigned around one
jitted step over a device mesh:

* no DDP wrap, no SyncBatchNorm conversion, no explicit collectives — the
  batch is sharded on the mesh's ``data`` axis and XLA emits gradient/BN
  reductions over ICI;
* the epoch structure, best-val-loss checkpointing, patience early stop,
  per-step cyclic LR, TensorBoard scalars, loss-curve ``.npy`` dumps and
  test-time CSV results all mirror the reference's workflow contract.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from seist_tpu import obs, taskspec
from seist_tpu.data import io_guard, pipeline
from seist_tpu.models import api
from seist_tpu.ops import Metrics, ResultSaver, process_outputs
from seist_tpu.parallel import mesh as mesh_lib
from seist_tpu.train import (
    PREEMPT_EXIT_CODE,
    TrainCheckpointManager,
    build_cyclic_schedule,
    build_optimizer,
    create_train_state,
    jit_cached_call,
    jit_device_aug_step,
    jit_eval_step,
    jit_multi_step,
    jit_step,
    load_checkpoint,
    make_accum_train_step,
    make_cached_train_call,
    make_device_aug_train_step,
    make_eval_step,
    make_multi_train_step,
    make_train_step,
    restore_into_state,
)
from seist_tpu.utils import faults as faults_lib
from seist_tpu.utils import profiling
from seist_tpu.utils.logger import logger
from seist_tpu.utils.meters import AverageMeter, ProgressMeter
from seist_tpu.utils.misc import (
    count_params,
    get_safe_path,
    get_time_str,
    strftimedelta,
)
from seist_tpu.utils.tb import ScalarWriter


def is_main_process() -> bool:
    return jax.process_index() == 0


class _PreemptionHandler:
    """SIGTERM -> checkpoint-at-next-step-boundary -> exit(75).

    The handler only flips a flag; the train loop polls it at step
    boundaries (between jitted dispatches), saves a final checkpoint, and
    exits with :data:`~seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE` so
    tools/supervise.py relaunches immediately without burning its retry
    budget. Cluster managers deliver SIGTERM to every host's process, so
    the collective orbax save finds all participants.

    Install/uninstall is a context manager; outside the main thread (e.g.
    a test harness driving train_worker from a worker thread) signal
    handlers cannot be installed and the guard degrades to inert.
    """

    def __init__(self):
        self.triggered = False
        self._prev = None
        self._installed = False

    def __enter__(self) -> "_PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            def _on_term(signum, frame):
                self.triggered = True
                # threadlint: disable=signal-handler-unsafe -- best-effort
                # operator notice; logging's RLock is reentrant from the
                # interrupted main thread (worst case: interleaved output,
                # never a deadlock), and the flag above is already set so
                # the preempt proceeds even if this line dies.
                logger.warning(
                    "SIGTERM received: will checkpoint at the next step "
                    f"boundary and exit {PREEMPT_EXIT_CODE}"
                )
            self._prev = signal.signal(signal.SIGTERM, _on_term)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev)
            self._installed = False


class _BadUpdateMonitor:
    """Host-side consecutive-skipped-update tracking for the bad-update
    guard (train/step.py ``guard=True``).

    Fetching the per-step finite flag immediately would serialize JAX's
    async dispatch (the same stall the worker avoids for losses), so
    flags are evaluated ``lag`` calls late: by then the device has long
    finished that step and the host read costs nothing. The rollback
    decision is therefore delayed by at most ``lag`` extra bad updates —
    all of which the guard already prevented from touching the params.

    Every host computes the same flags (they derive from the all-reduced
    gradients), so rollback decisions cannot diverge across hosts.
    """

    def __init__(self, max_bad: int, lag: int = 2):
        self.max_bad = int(max_bad)
        self.lag = max(0, int(lag))
        self.bad_run = 0  # consecutive skipped updates at the tail
        self.total_skipped = 0
        self._pending: "collections.deque" = collections.deque()

    def push(self, applied_dev) -> bool:
        """Queue one call's applied flag (scalar 0/1) or per-micro-step
        applied mask (ordered (k,) array from the scanned paths); returns
        True when the consecutive-bad run has reached ``max_bad``
        (rollback needed)."""
        self._pending.append(applied_dev)
        while len(self._pending) > self.lag:
            self._eval(self._pending.popleft())
        return self.exceeded

    def flush(self) -> bool:
        while self._pending:
            self._eval(self._pending.popleft())
        return self.exceeded

    def reset(self) -> None:
        self.bad_run = 0
        self._pending.clear()

    @property
    def exceeded(self) -> bool:
        return bool(self.max_bad) and self.bad_run >= self.max_bad

    def _eval(self, applied_dev) -> None:
        mask = np.atleast_1d(np.asarray(jax.device_get(applied_dev)))
        skipped = int(mask.size - mask.sum())
        self.total_skipped += skipped
        if skipped == 0:
            self.bad_run = 0
        else:
            # Only the TRAILING skips extend a consecutive run: a call
            # ending in a successful update (e.g. [skip, skip, ok] on the
            # packed paths) breaks the run regardless of earlier skips.
            trailing = 0
            for v in mask[::-1]:
                if v:
                    break
                trailing += 1
            if trailing == mask.size:
                self.bad_run += trailing
            else:
                self.bad_run = trailing
        if skipped > 0:
            logger.warning(
                f"Bad-update guard: skipped {skipped} non-finite update(s) "
                f"(consecutive run: {self.bad_run})"
            )


def _mixture_temperature(args: Any, mode: str) -> float:
    """--mixture-temperature applies to TRAIN sampling only: evaluation
    walks every source's split plainly so per-source metrics stay
    comparable across temperature settings."""
    if mode != "train":
        return 0.0
    return float(getattr(args, "mixture_temperature", 0.0) or 0.0)


def _build_loader(args: Any, spec: taskspec.TaskSpec, mode: str) -> pipeline.Loader:
    sds = pipeline.from_task_spec(
        spec,
        args.dataset_name,
        mode,
        seed=args.seed,
        data_dir=args.data,
        in_samples=args.in_samples,
        augmentation=args.augmentation,
        shuffle=args.shuffle,
        data_split=args.data_split,
        train_size=args.train_size,
        val_size=args.val_size,
        max_event_num=args.max_event_num,
        min_snr=args.min_snr,
        p_position_ratio=args.p_position_ratio,
        coda_ratio=args.coda_ratio,
        norm_mode=args.norm_mode,
        add_event_rate=args.add_event_rate,
        add_noise_rate=args.add_noise_rate,
        add_gap_rate=args.add_gap_rate,
        drop_channel_rate=args.drop_channel_rate,
        scale_amplitude_rate=args.scale_amplitude_rate,
        pre_emphasis_rate=args.pre_emphasis_rate,
        pre_emphasis_ratio=args.pre_emphasis_ratio,
        generate_noise_rate=args.generate_noise_rate,
        shift_event_rate=args.shift_event_rate,
        mask_percent=args.mask_percent,
        noise_percent=args.noise_percent,
        min_event_gap_sec=args.min_event_gap,
        soft_label_shape=args.label_shape,
        label_width=args.label_width,
        dataset_kwargs=getattr(args, "dataset_kwargs", None),
        # Forwarded only when set: SeismicDataset owns the single default,
        # and an explicit 0 means zero tolerance (abort on the first
        # quarantined sample) so no `or`-coercion.
        **(
            {"max_quarantine_frac": float(args.max_quarantine_frac)}
            if getattr(args, "max_quarantine_frac", None) is not None
            else {}
        ),
    )
    return pipeline.Loader(
        sds,
        batch_size=args.batch_size,
        shuffle=(mode == "train" and args.shuffle),
        drop_last=(mode == "train"),
        num_workers=args.workers,
        # Process workers only where the throughput matters: a second
        # resident pool (each child holding a full dataset copy) for the
        # occasional eval pass is pure memory cost.
        worker_processes=(
            int(getattr(args, "loader_processes", 0) or 0)
            if mode == "train"
            else 0
        ),
        seed=args.seed,
        num_shards=jax.process_count(),
        shard_index=jax.process_index(),
        mixture_temperature=_mixture_temperature(args, mode),
    )


def _make_metrics(args: Any, tasks: List[str], fs: int) -> Dict[str, Metrics]:
    return {
        task: Metrics(
            task=task,
            metric_names=taskspec.get_metrics(task),
            sampling_rate=fs,
            time_threshold=args.time_threshold,
            num_samples=args.in_samples,
        )
        for task in tasks
    }


def _postprocess_batch(
    args: Any,
    spec: taskspec.TaskSpec,
    outputs,
    fs: int,
):
    if spec.outputs_transform_for_results is not None:
        outputs = spec.outputs_transform_for_results(outputs)
    return process_outputs(
        outputs,
        spec.labels,
        sampling_rate=fs,
        ppk_threshold=args.ppk_threshold,
        spk_threshold=args.spk_threshold,
        det_threshold=args.det_threshold,
        min_peak_dist=args.min_peak_dist,
        max_detect_event_num=args.max_detect_event_num,
    )


def _update_task_metrics(
    metrics_merged: Dict[str, Metrics],
    batch_metrics: Dict[str, Metrics],
    results: Dict[str, Any],
    metrics_targets: Dict[str, np.ndarray],
    valid: int,
) -> None:
    """Feed one batch into fresh per-batch metrics + running accumulators
    (ref train.py:144-164). ``valid`` trims eval tail padding. Results may be
    globally-sharded device arrays — ``to_local`` keeps only this host's
    rows, which line up with the host-local metrics_targets."""
    for task, m in batch_metrics.items():
        tgt = mesh_lib.to_local(metrics_targets[task])[:valid]
        prd = mesh_lib.to_local(results[task])[:valid]
        if prd.ndim < 2:
            prd = prd[:, None]
        m.compute(tgt, prd)
        metrics_merged[task].add(m)


def validate(
    args: Any,
    state,
    eval_step,
    spec: taskspec.TaskSpec,
    val_loader: pipeline.Loader,
    mesh,
    *,
    testing: bool = False,
    save_results: bool = False,
    watchdog: Optional[io_guard.StallWatchdog] = None,
) -> Tuple[float, Dict[str, Metrics]]:
    """Eval loop (ref validate.py:10-134): loss + per-task metrics; at test
    time optionally accumulate the results CSV. ``watchdog`` (the train
    worker's data-plane stall watchdog) is armed while blocked on val
    batches — a wedged val loader preempts instead of hanging the run."""
    tasks = list(spec.eval)
    fs = val_loader.dataset.sampling_rate()
    metrics_merged = _make_metrics(args, tasks, fs)
    loss_meter = AverageMeter("loss", ":.4e")
    saver = (
        ResultSaver(item_names=tasks) if (save_results and is_main_process()) else None
    )

    for step, batch in enumerate(
        io_guard.watch(
            pipeline.prefetch_to_device(iter(val_loader), mesh), watchdog
        )
    ):
        loss, outputs = eval_step(
            state, batch.inputs, batch.loss_targets, batch.mask
        )
        valid = int(mesh_lib.to_local(batch.mask).sum())
        # Weight by the GLOBAL valid count so every host's running val loss
        # is identical — checkpoint/early-stop decisions must not diverge
        # across hosts (tail padding lives on one host's shard only).
        # jaxlint: disable=host-sync-item-loop -- one scalar per VAL batch; the running meter (and the float(loss) next line) needs it now
        global_valid = int(np.asarray(jax.device_get(batch.mask.sum())))
        loss_meter.update(float(loss), max(global_valid, 1))
        results = _postprocess_batch(args, spec, outputs, fs)
        batch_metrics = _make_metrics(args, tasks, fs)
        _update_task_metrics(
            metrics_merged, batch_metrics, results, batch.metrics_targets, valid
        )
        if saver is not None:
            import json as _json

            metas = [_json.loads(m) for m in batch.meta[:valid]]
            meta_cols = {k: [m[k] for m in metas] for k in metas[0]} if metas else {}
            saver.append(
                meta_cols,
                {
                    t: mesh_lib.to_local(batch.metrics_targets[t])[:valid]
                    for t in tasks
                },
                {t: mesh_lib.to_local(results[t])[:valid] for t in tasks},
            )

    for m in metrics_merged.values():
        m.synchronize_between_processes()

    if saver is not None:
        # No-clobber contract (ref validate.py:130 get_safe_path): test mode
        # reusing an existing log dir must not overwrite prior results.
        out_csv = get_safe_path(
            os.path.join(
                logger.logdir(), f"test_results_{val_loader.dataset.name()}.csv"
            )
        )
        saver.save_as_csv(out_csv)
        logger.info(f"Test results saved: {out_csv}")

    phase = "test" if testing else "val"
    for task, m in metrics_merged.items():
        logger.info(f"[{phase}] {args.model_name} {task}: {m}")
    return loss_meter.avg, metrics_merged


# Cleanup callbacks registered by the running worker (its _obs_close);
# drained by _dump_flight_on_exception's finally so EVERY exit path —
# return, sys.exit, uncaught exception — tears the telemetry plane down
# (os._exit hard deaths skip it; the process is gone anyway).
_OBS_CLEANUP: List[Any] = []


def _dump_flight_on_exception(fn):
    """Any uncaught exception in the wrapped worker leaves a flight-
    recorder dump (reason ``exception``) before propagating — the crash
    path that ISN'T one of the managed deaths (rollback/preempt/stall/
    quarantine) still gets its forensic record. Deduped: a managed path
    that already dumped seconds earlier doesn't leave a second file.
    The finally drains _OBS_CLEANUP, so a crashed run cannot leak the
    metrics HTTP port, the events fd, or the SIGUSR2 handler into the
    process's next run."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        try:
            return fn(*a, **k)
        except Exception as e:
            obs.flight.dump_on_death("exception", dedup_s=5.0, error=repr(e))
            raise
        finally:
            while _OBS_CLEANUP:
                cb = _OBS_CLEANUP.pop()
                try:
                    cb()
                except Exception:  # noqa: BLE001 - teardown must not mask
                    # the real exception propagating out of the worker
                    pass

    return wrapper


@_dump_flight_on_exception
def train_worker(args: Any) -> str:
    """Full training run; returns the best checkpoint path
    (ref train.py:182-484)."""
    spec = taskspec.get_task_spec(args.model_name)
    loss_fn = spec.loss()
    seq_shards = int(getattr(args, "seq_shards", 1) or 1)
    mesh = mesh_lib.make_mesh(seq=seq_shards)
    mesh_lib.set_active_mesh(mesh)
    logger.info(
        f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
        f"process {jax.process_index()}/{jax.process_count()}"
    )
    if seq_shards > 1:
        logger.info(
            f"Sequence parallelism: ring attention over {seq_shards} shards "
            f"(--seq-shards); dropout semantics match dense training"
        )
    data_axis = mesh.shape[mesh_lib.AXIS_DATA]
    if (args.batch_size * jax.process_count()) % data_axis:
        raise ValueError(
            f"global batch size {args.batch_size * jax.process_count()} must "
            f"be divisible by the mesh 'data' axis ({data_axis} devices)"
        )

    train_loader = _build_loader(args, spec, "train")
    val_loader = _build_loader(args, spec, "val")
    fs = train_loader.dataset.sampling_rate()

    steps_per_epoch = len(train_loader)
    if steps_per_epoch == 0:
        raise ValueError("Train split is empty — check data_dir / split sizes")
    # `--steps > 0` overrides epochs (ref train.py:250-253).
    epochs = args.epochs
    if args.steps > 0:
        epochs = max(1, int(np.ceil(args.steps / steps_per_epoch)))
    total_steps = steps_per_epoch * epochs

    # Gradient accumulation: k loader batches -> ONE optimizer update
    # (step.py make_accum_train_step). state.step counts UPDATES and the
    # LR schedule follows it, so the schedule length shrinks by k.
    gas = max(1, int(getattr(args, "grad_accum_steps", 1) or 1))
    if gas > 1:
        if steps_per_epoch // gas == 0:
            raise ValueError(
                f"--grad-accum-steps {gas} exceeds steps_per_epoch "
                f"{steps_per_epoch}: every epoch would apply ZERO updates"
            )
        total_steps = (steps_per_epoch // gas) * epochs

    # Model + optimizer + state.
    in_channels = taskspec.get_num_inchannels(args.model_name)
    model = api.create_model(
        args.model_name, in_channels=in_channels, in_samples=args.in_samples
    )
    variables = api.init_variables(
        model, seed=args.seed, in_samples=args.in_samples, in_channels=in_channels
    )
    logger.info(f"{args.model_name} params: {count_params(variables['params']):,}")

    if args.use_lr_scheduler:
        schedule = build_cyclic_schedule(
            base_lr=args.base_lr,
            max_lr=args.max_lr,
            total_steps=total_steps,
            warmup_steps=args.warmup_steps,
            down_steps=args.down_steps,
            mode=args.lr_scheduler_mode,
        )
    else:
        schedule = args.max_lr
    l1_kernel = getattr(args, "conv_kernel_l1_alpha", 0.0)
    l1_bias = getattr(args, "conv_bias_l1_alpha", 0.0)
    l1_mask_fn = None
    if l1_kernel or l1_bias:
        # Reference scope: these L1 grad hooks exist only on EQTransformer's
        # encoder/decoder convs (ref eqtransformer.py:43-51,388-396).
        if args.model_name != "eqtransformer":
            raise ValueError(
                "--conv-{kernel,bias}-l1-alpha apply only to eqtransformer "
                f"(got --model-name {args.model_name})"
            )
        from seist_tpu.models.eqtransformer import l1_param_mask

        l1_mask_fn = l1_param_mask
    tx = build_optimizer(
        args.optim,
        schedule,
        weight_decay=args.weight_decay,
        momentum=args.momentum,
        l1_kernel_alpha=l1_kernel,
        l1_bias_alpha=l1_bias,
        l1_mask_fn=l1_mask_fn,
    )
    state = create_train_state(model, variables, tx)

    start_epoch = args.start_epoch
    start_batch = 0  # mid-epoch resume offset (batches already consumed)
    if args.checkpoint:
        restored = load_checkpoint(args.checkpoint, state)
        state = restore_into_state(state, restored)
        meta = restored["meta"]
        if "data_epoch" in meta:
            # Step-granular checkpoint: continue mid-epoch from the exact
            # data position — no replayed, no skipped samples.
            start_epoch = int(meta["data_epoch"])
            start_batch = int(meta["data_batch_offset"])
            if start_batch >= steps_per_epoch:
                start_epoch += 1
                start_batch = 0
            # The shuffle order is a pure function of (seed, epoch) and
            # the batch offset is expressed in the saving run's batch
            # geometry: resuming mid-epoch with a different seed or batch
            # size would replay some samples and skip others — the exact
            # failure this machinery exists to prevent.
            for field, current in (
                ("seed", int(args.seed)),
                ("steps_per_epoch", steps_per_epoch),
                ("batch_size", int(args.batch_size)),
            ):
                saved_v = int(meta.get(field, 0) or current)
                if saved_v == current:
                    continue
                if start_batch > 0:
                    raise ValueError(
                        f"{field} {current} does not match the "
                        f"checkpoint's {field} {saved_v}; a mid-epoch "
                        f"resume (batch offset {start_batch}) would "
                        "replay/skip data. Relaunch with the original "
                        f"{field}."
                    )
                logger.warning(
                    f"{field} {current} differs from the checkpoint's "
                    f"{saved_v}: epoch boundaries/shuffles will not "
                    "match the original run"
                )
        else:
            # Legacy epoch checkpoint: next epoch from scratch.
            start_epoch = int(meta["epoch"]) + 1
        logger.info(
            f"Resumed from {args.checkpoint} (epoch {start_epoch}, "
            f"batch offset {start_batch}, loss {float(meta['loss']):.4f}, "
            f"update step {int(state.step)})"
        )

    dtype = getattr(args, "dtype", "fp32")
    # Bad-update guard: detect non-finite loss/grad-norm inside the jitted
    # step, skip the poisoned update, and after max_bad_steps consecutive
    # skips roll back to the last checkpoint (train/step.py
    # _guarded_update; docs/FAULT_TOLERANCE.md).
    guard_on = bool(getattr(args, "bad_step_guard", True))
    max_bad = int(getattr(args, "max_bad_steps", 3) or 0)
    # steps_per_call <= 0 means "auto" (CLI default): 1 on the host path,
    # raised high under --device-aug cached. An EXPLICIT 1 is honored there
    # (per-step save/preempt granularity costs throughput but is a choice).
    spc_raw = int(getattr(args, "steps_per_call", 0) or 0)
    spc_auto = spc_raw <= 0
    spc = max(1, spc_raw)
    if spc > 1 and gas > 1:
        raise ValueError(
            "--steps-per-call and --grad-accum-steps are mutually "
            "exclusive (both scan stacked micro-batches, with different "
            "update semantics)"
        )

    # -- device-side augmentation (--device-aug; docs/DATA_PIPELINE.md) ----
    # 'step': raw rows cross the host per step, augmentation + label
    # synthesis run inside the jitted step. 'cached': whole raw epochs
    # live in HBM and a scan executor consumes (k, B) index arrays — zero
    # per-step host stacking. Unsupported configs fall back to the host
    # path; an over-budget 'cached' falls back to 'step' (both logged).
    device_req = str(getattr(args, "device_aug", "off") or "off")
    device_mode = "off"
    dev_store = dev_cache = None
    sds_train = train_loader.dataset
    # --ingest: how raw rows reach the device on the device-aug step path.
    # 'auto' takes the direct shard->staging->device fast path whenever
    # the dataset is packed (data/ingest.py), 'host' forces the resident
    # RawStore upload, 'direct' demands the fast path and errors when the
    # prerequisites are missing instead of degrading silently.
    ingest_req = str(getattr(args, "ingest", "auto") or "auto")
    if ingest_req not in ("auto", "direct", "host"):
        raise ValueError(
            f"--ingest must be auto|direct|host, got '{ingest_req}'"
        )
    if ingest_req == "direct" and device_req == "off":
        raise ValueError(
            "--ingest direct feeds the device-aug step path; run with "
            "--device-aug step (docs/DATA.md)"
        )
    mixture_t = _mixture_temperature(args, "train")
    src_ids_logical = sds_train.source_ids() if mixture_t > 0 else None
    if device_req != "off":
        from seist_tpu.data import device_aug as da

        if gas > 1:
            raise ValueError(
                "--device-aug is incompatible with --grad-accum-steps "
                "(accumulation scans stacked host batches)"
            )
        reasons = da.unsupported_reasons(
            sds_train.preprocessor, sds_train.input_names,
            sds_train.label_names,
        )
        budget = da.hbm_budget_bytes(
            float(getattr(args, "device_aug_hbm_gb", 0.0) or 0.0)
        )
        # The cache shards its sample axis over the mesh 'data' axis, so
        # the budget comparison is PER-DEVICE bytes vs per-device HBM —
        # comparing the raw total would downgrade a 40 GiB dataset on an
        # 8-chip mesh (5 GiB/chip) that actually fits.
        est = 0
        if not reasons:
            try:
                est = pipeline.RawStore.estimate_bytes(
                    sds_train
                ) // max(data_axis, 1)
            except ValueError as e:
                # The size probe reads raw sample 0 through the guarded
                # path; a permanently-corrupt sample refuses the device
                # store — same fallback as a build-time refusal: host
                # path, whose quarantine machinery handles it.
                reasons = [str(e)]
        device_mode, why = da.select_device_aug_mode(
            device_req, est, budget, reasons
        )
        if device_mode != device_req:
            logger.warning(f"--device-aug {device_req} -> {device_mode}: {why}")
        if ingest_req == "direct" and device_mode != "step":
            # The ONE resolved-mode guard for --ingest direct (the
            # pre-flight check above already rejected --device-aug off;
            # a non-packed dataset is rejected by the build below).
            raise ValueError(
                "--ingest direct requires the device-aug step path; the "
                f"run resolved --device-aug to '{device_mode}' ({why})"
            )
        if device_mode != "off":
            from seist_tpu.data import ingest as ingest_lib

            # Direct shard->device ingest: on a packed dataset the step
            # path streams staging batches straight off the shard memmaps
            # — no Event decode, no resident waveform upload. The cached
            # mode keeps the RawStore (its whole point is HBM residency).
            direct = device_mode == "step" and ingest_req != "host" and (
                ingest_req == "direct"
                or ingest_lib.packed_dataset_of(sds_train) is not None
            )
            if direct:
                try:
                    dev_store = ingest_lib.PackedRawStore.build(
                        sds_train, batch_size=args.batch_size
                    )
                    logger.info(ingest_lib.describe(dev_store))
                except ValueError as e:
                    if ingest_req == "direct":
                        raise
                    logger.warning(
                        f"packed direct ingest unavailable ({e}); "
                        "uploading a resident RawStore instead"
                    )
                    direct = False
            if not direct:
                try:
                    dev_store = pipeline.RawStore.build(sds_train)
                except ValueError as e:
                    logger.warning(f"--device-aug {device_mode} -> off: {e}")
                    device_mode = "off"
        if device_mode == "step" and spc > 1:
            # Explicit 'step' + packing is a config error; but a 'cached'
            # request that FELL BACK to 'step' must not crash on its
            # now-meaningless packing flag.
            if device_req == "step":
                raise ValueError(
                    "--steps-per-call > 1 requires --device-aug cached "
                    "(the step mode feeds one raw batch per dispatch)"
                )
            logger.warning(
                f"--steps-per-call {spc} ignored on the device-aug step "
                "fallback path"
            )
            spc = 1
        if (
            device_mode != "off"
            and faults_lib.FaultInjector.from_env().plan.nan_step >= 0
        ):
            raise ValueError(
                "SEIST_FAULT_NAN_STEP corrupts host-fed input batches, "
                "which the device-aug paths never materialize; use "
                "--device-aug off for NaN-injection runs (process-level "
                "faults — SIGTERM/kill/slow — work on every path)"
            )

    if device_mode != "off":
        from seist_tpu.data import device_aug as da

        dev_cfg = da.AugConfig.from_preprocessor(
            sds_train.preprocessor,
            seed=args.seed,
            raw_len=dev_store.raw_len,
            phase_slots=dev_store.phase_slots,
        )
        dev_proc_args = (
            dev_cfg, sds_train.input_names, sds_train.label_names
        )
    if device_mode == "cached":
        # steps_per_call defaults HIGH here: with epochs resident there is
        # no host work to overlap, so the only per-step cost left is the
        # dispatch — amortize it.
        if spc_auto:
            spc = max(1, min(32, steps_per_epoch))
        if steps_per_epoch // spc == 0:
            raise ValueError(
                f"--steps-per-call {spc} exceeds steps_per_epoch "
                f"{steps_per_epoch}: every epoch would train ZERO steps "
                f"(trailing part-groups are dropped)"
            )
        dev_cache = pipeline.DeviceEpochCache(dev_store, mesh)
        logger.info(
            f"device-aug cached: {len(dev_store)} epoch samples resident "
            f"({dev_cache.nbytes / 2**20:.1f} MiB HBM), "
            f"steps_per_call={spc}"
        )
        train_step = jit_cached_call(
            make_cached_train_call(
                spec, loss_fn,
                da.make_cache_processor(
                    *dev_proc_args,
                    n_raw=dev_store.n_raw,
                    augmentation=dev_store.augmentation,
                ),
                steps_per_call=spc, compute_dtype=dtype, guard=guard_on,
            ),
            mesh,
            dev_cache.arrays,
        )
        if steps_per_epoch % spc:
            logger.warning(
                f"steps_per_call={spc} drops {steps_per_epoch % spc} "
                f"trailing batch(es) per epoch ({steps_per_epoch} steps)"
            )
    elif device_mode == "step":
        logger.info(
            "device-aug step: augmentation + labels inside the jitted "
            "step; host feeds raw rows only"
        )
        train_step = jit_device_aug_step(
            make_device_aug_train_step(
                spec, loss_fn,
                da.make_row_processor(*dev_proc_args),
                compute_dtype=dtype, guard=guard_on,
            ),
            mesh,
        )
    elif gas > 1:
        # One update from gas micro-batch gradients, scanned in one jitted
        # program; stacked-batch layout shares jit_multi_step's sharding.
        if steps_per_epoch % gas:
            logger.warning(
                f"grad_accum_steps={gas} drops {steps_per_epoch % gas} "
                f"trailing batch(es) per epoch ({steps_per_epoch} steps)"
            )
        train_step = jit_multi_step(
            make_accum_train_step(
                spec, loss_fn, compute_dtype=dtype, accum_steps=gas,
                guard=guard_on,
            ),
            mesh,
        )
        logger.info(
            f"grad_accum_steps={gas}: effective batch "
            f"{args.batch_size * gas * jax.process_count()}, "
            f"{steps_per_epoch // gas} updates/epoch"
        )
    elif spc > 1:
        # k updates scanned inside one jitted program (dispatch
        # amortization; step.py make_multi_train_step). Per-step output
        # metrics are skipped on this path — the scan returns no
        # per-micro-step outputs.
        if steps_per_epoch // spc == 0:
            raise ValueError(
                f"--steps-per-call {spc} exceeds steps_per_epoch "
                f"{steps_per_epoch}: every epoch would train ZERO steps "
                f"(trailing part-groups are dropped)"
            )
        if steps_per_epoch % spc:
            logger.warning(
                f"steps_per_call={spc} drops {steps_per_epoch % spc} "
                f"trailing batch(es) per epoch ({steps_per_epoch} steps)"
            )
        train_step = jit_multi_step(
            make_multi_train_step(
                spec, loss_fn, compute_dtype=dtype, steps_per_call=spc,
                guard=guard_on,
            ),
            mesh,
        )
        logger.info(f"steps_per_call={spc}: scanned multi-step training")
    else:
        train_step = jit_step(
            make_train_step(spec, loss_fn, compute_dtype=dtype, guard=guard_on),
            mesh,
        )
    eval_step = jit_eval_step(
        make_eval_step(spec, loss_fn, compute_dtype=dtype), mesh
    )
    base_rng = jax.random.PRNGKey(args.seed)

    writer = (
        ScalarWriter(os.path.join(logger.logdir(), "tensorboard"))
        if (args.use_tensorboard and is_main_process())
        else None
    )
    ckpt_dir = os.path.join(logger.logdir(), "checkpoints")
    save_every = int(getattr(args, "save_interval_steps", 0) or 0)
    ckpt_mgr = TrainCheckpointManager(
        ckpt_dir, keep_last=int(getattr(args, "keep_checkpoints", 3) or 3)
    )
    if args.checkpoint:
        # Manual rollback (resume from an older step while newer step
        # dirs exist): saves that re-reach those exact steps are SKIPPED
        # (overwrite refused), so the stale lineage would shadow this
        # one. Make the operator decide.
        resume_gstep = start_epoch * steps_per_epoch + start_batch
        stale = [s for s in ckpt_mgr.all_steps() if s > resume_gstep]
        if stale:
            logger.warning(
                f"Checkpoint dir has steps {stale} AHEAD of the resume "
                f"position ({resume_gstep}); saves re-reaching them will "
                "be skipped, and resume tooling may prefer them. Delete "
                "them if this resume supersedes that lineage."
            )
    faults = faults_lib.FaultInjector.from_env()
    if faults.enabled:
        logger.warning(f"Fault injection ACTIVE: {faults.plan}")

    # Data-plane stall watchdog (--data-watchdog-sec; data/io_guard.py):
    # armed only while the loop is blocked waiting for a host batch
    # (io_guard.watch), so step compute, jit compiles, validation and
    # checkpoint saves never count toward the budget. A trip dumps every
    # thread's stack and hard-exits with the clean-preempt code —
    # tools/supervise.py relaunches from the newest checkpoint instead of
    # the run hanging forever.
    wd_timeout = float(getattr(args, "data_watchdog_sec", 0.0) or 0.0)
    watchdog = (
        io_guard.StallWatchdog(wd_timeout).start() if wd_timeout > 0 else None
    )

    # -- telemetry plane (docs/OBSERVABILITY.md) --------------------------
    # Flight recorder: always on (a deque append per step — priced in
    # BENCH step_breakdown.telemetry); every death path below dumps it.
    # Any --flight-steps <= 0 falls back to the documented default
    # rather than crashing the run at startup.
    fsteps = int(getattr(args, "flight_steps", 0) or 0)
    recorder = obs.FlightRecorder(capacity=fsteps if fsteps > 0 else 256)
    obs.flight.install(recorder)
    obs.register_default_collectors()
    events = (
        obs.EventLog(os.path.join(logger.logdir(), "events.jsonl"))
        if is_main_process()
        else None
    )
    # Opt-in Prometheus endpoint (--metrics-port; obs/http.py): >0 binds
    # that loopback port, -1 an ephemeral one (logged), 0 disables.
    profile_trigger = obs.ProfileTrigger()
    metrics_server = None
    mport = int(getattr(args, "metrics_port", 0) or 0)
    if mport and is_main_process():
        metrics_server = obs.start_metrics_server(
            max(mport, 0), profile_trigger=profile_trigger
        )
    # SIGUSR2 -> on-demand profiler capture at the next step boundary
    # (same window machinery as --profile-steps and POST /profile).
    prev_usr2 = None
    if (
        threading.current_thread() is threading.main_thread()
        and hasattr(signal, "SIGUSR2")
    ):
        def _on_usr2(signum, frame):
            # threadlint: disable=signal-handler-unsafe -- request() is a
            # single lock-free GIL-atomic deque append (ProfileTrigger is
            # deliberately lockless for exactly this call site: the
            # interrupted main thread may be inside consume()).
            profile_trigger.request()
            # threadlint: disable=signal-handler-unsafe -- best-effort
            # notice; logging's RLock is reentrant from the interrupted
            # main thread, worst case interleaved output.
            logger.info(
                "[obs] SIGUSR2: profiler capture requested "
                f"({obs.http.DEFAULT_PROFILE_STEPS} steps)"
            )
        prev_usr2 = signal.signal(signal.SIGUSR2, _on_usr2)

    obs_closed = [False]

    def _obs_close() -> None:
        """Tear down the telemetry plane. Idempotent; runs on the normal
        return, the preempt exit, AND — via _OBS_CLEANUP drained in the
        _dump_flight_on_exception finally — every exception/SystemExit
        path, so a crashed run cannot leave the metrics port bound or
        the events fd open for the process's next run. Uninstalling the
        recorder also unhooks its bus span sink, so back-to-back runs in
        one process never stack sinks."""
        if obs_closed[0]:
            return
        obs_closed[0] = True
        obs.flight.install(None)
        if events is not None:
            events.close()
        if metrics_server is not None:
            metrics_server.shutdown()
            metrics_server.server_close()  # release the listening port
        if prev_usr2 is not None:
            try:
                signal.signal(signal.SIGUSR2, prev_usr2)
            except ValueError:  # not the main thread anymore
                pass

    _OBS_CLEANUP.append(_obs_close)

    def _emit_event(kind: str, **fields) -> None:
        recorder.record_event(kind, **fields)
        if events is not None:
            events.emit(kind, **fields)

    def _step_out(ret):
        """Normalize (state, loss, outputs[, diag]) across guard on/off."""
        if len(ret) == 4:
            return ret
        s, l, o = ret
        return s, l, o, None

    def _interval_save(state, epoch, batches_done, gstep, wait=False):
        """Step-granular async save at a --save-interval-steps boundary
        (also the preempt-exit save, with ``wait=True``). The recorded
        data position is the NEXT batch to consume."""
        if batches_done >= steps_per_epoch:
            d_epoch, d_off = epoch + 1, 0
        else:
            d_epoch, d_off = epoch, batches_done
        with obs.BUS.span("checkpoint_save"):
            ckpt_mgr.save(
                gstep,
                state,
                epoch=epoch,
                data_epoch=d_epoch,
                data_batch_offset=d_off,
                seed=args.seed,
                steps_per_epoch=steps_per_epoch,
                batch_size=int(args.batch_size),
                on_exists="skip",  # resume/rollback may re-reach a step
                wait=wait,
            )
        return d_epoch, d_off

    def _rollback(state):
        """Bad-update-guard rollback: restore the last checkpoint (params
        + optimizer) and continue from the CURRENT data position."""
        ckpt_mgr.wait()
        step_r = ckpt_mgr.latest_step()
        if step_r is None:
            raise RuntimeError(
                f"{monitor.bad_run} consecutive non-finite updates and no "
                "checkpoint to roll back to — aborting (enable "
                "--save-interval-steps for rollback coverage)"
            )
        logger.warning(
            f"Bad-update guard: {monitor.bad_run} consecutive non-finite "
            f"updates; rolling back to checkpoint step {step_r}"
        )
        # The run survives a rollback, but the steps leading into it are
        # exactly what a post-mortem wants — snapshot them now, before
        # the ring rolls past (docs/OBSERVABILITY.md).
        _emit_event(
            "bad_update_rollback",
            rollback_to_step=int(step_r),
            consecutive_bad=int(monitor.bad_run),
        )
        # arm_dedup=False: this dump is non-fatal (the run continues) and
        # must never suppress the record of a crash seconds later.
        obs.flight.dump_on_death(
            "bad_update_rollback", arm_dedup=False,
            rollback_to_step=int(step_r),
        )
        restored = ckpt_mgr.restore(state, step=step_r)
        monitor.reset()
        return restore_into_state(state, restored)

    def _preempt_exit(state, epoch, batches_done, gstep, hard=False):
        """Step-boundary preemption: make the final checkpoint durable
        (wait=True barriers the async write), then exit with the
        documented preempt code for tools/supervise.py.

        ``hard=True`` (the loader-death path) ends in ``os._exit``: the
        data plane is known-wedged and its pool threads are non-daemon,
        so ``sys.exit`` would hang forever in ``threading._shutdown``
        joining a thread stuck inside a dead read — the exact hang this
        machinery exists to eliminate. The watchdog is left armed as the
        escalation if even the final save wedges."""
        if watchdog is not None and not hard:
            watchdog.stop()
        d_epoch, d_off = _interval_save(
            state, epoch, batches_done, gstep, wait=True
        )
        logger.warning(
            f"Preempted: checkpoint step {gstep} durable "
            f"(data position {d_epoch}:{d_off}); exiting {PREEMPT_EXIT_CODE}"
        )
        _emit_event(
            "preempt", gstep=int(gstep), data_epoch=int(d_epoch),
            data_batch_offset=int(d_off), hard=bool(hard),
        )
        obs.flight.dump_on_death("preempt", gstep=int(gstep))
        if writer is not None:
            writer.close()
        train_loader.close()
        val_loader.close()
        ckpt_mgr.close()
        _obs_close()
        if hard:
            io_guard.hard_exit(PREEMPT_EXIT_CODE)
        sys.exit(PREEMPT_EXIT_CODE)

    def _loader_death_exit(e, state, epoch, batches_done):
        """Loader-thread death (data/io_guard.py LoaderDeathError): the
        device and params are healthy — checkpoint the current position
        and preempt-exit so the supervisor relaunches with a fresh data
        plane rather than the run dying opaquely (or, pre-watchdog,
        hanging forever)."""
        logger.error(
            f"Loader worker death: {e}; dumping thread stacks and "
            "preempt-exiting for supervised relaunch"
        )
        io_guard.dump_thread_stacks()
        if watchdog is not None:
            # Escalation: the data plane is wedged; if the final save
            # below hangs too, the watchdog's os._exit still gets us out.
            watchdog.arm()
        _preempt_exit(
            state, epoch, batches_done,
            epoch * steps_per_epoch + batches_done,
            hard=True,
        )

    best_loss = float("inf")
    best_ckpt_path = ""
    patience_counter = 0
    tasks = list(spec.eval)
    train_losses: List[float] = []
    val_losses: List[float] = []
    epoch_times: List[float] = []

    # --profile-steps N: capture a jax.profiler trace of N steady-state
    # OPTIMIZER steps (skipping compile/warmup) in the first trained epoch.
    # Counted in optimizer steps regardless of the packed path (each loop
    # iteration advances `updates_per_call` of them). Later captures are
    # re-armed on demand: SIGUSR2 or POST /profile on --metrics-port.
    profile_steps = int(getattr(args, "profile_steps", 0) or 0)
    # Batches consumed per loop iteration on the packed path (steps-per-call
    # runs kpack updates/call; grad accumulation runs ONE update over kpack
    # micro-batches) — vs optimizer UPDATES per iteration, which is what
    # _maybe_trace counts.
    kpack = gas if gas > 1 else spc
    updates_per_call = 1 if gas > 1 else spc
    profile_from = 2 * updates_per_call  # skip the first two loop iterations
    tracing = False
    trace_dir = ""

    def _trace_dir() -> str:
        # Unique per supervise attempt AND per capture window
        # (timestamp + pid + no-clobber suffix): a relaunched run must
        # never overwrite the previous attempt's trace.
        return get_safe_path(
            os.path.join(
                logger.logdir(), "profile",
                f"{get_time_str()}_p{os.getpid()}",
            )
        )

    monitor = _BadUpdateMonitor(max_bad)
    preempt = _PreemptionHandler()
    preempt.__enter__()  # uninstalled after the epoch loop (normal path)

    kernel_status_logged = False

    def _log_kernel_status_once() -> None:
        # After the first step the attention-kernel health probes have run
        # (they fire at trace time); surface the outcome so a silent Mosaic
        # rejection -> einsum fallback is visible in every train run's log
        # (VERDICT r3 #4).
        nonlocal kernel_status_logged
        if kernel_status_logged or not is_main_process():
            return
        kernel_status_logged = True
        from seist_tpu.ops.pallas_attention import kernel_status_summary

        status = kernel_status_summary()
        if status["signatures"]:
            logger.info(f"attention kernel status: {status}")

    def _maybe_trace(opt_step: int, loss) -> None:
        """``opt_step``: optimizer steps completed before this iteration."""
        nonlocal tracing, profile_steps, profile_from, trace_dir
        if not is_main_process():
            return
        if not tracing:
            # On-demand capture (SIGUSR2 / POST /profile): open the
            # window at the next step boundary. Consume ONLY when idle —
            # a request arriving mid-capture stays in the trigger box and
            # opens its own window once this one closes.
            req = profile_trigger.consume()
            if req:
                profile_steps = req
                profile_from = opt_step + updates_per_call
                _emit_event("profile_requested", steps=req)
        if not profile_steps:
            return
        if not tracing and opt_step >= profile_from:
            trace_dir = _trace_dir()
            profiling.trace_start(trace_dir)
            tracing = True
        elif tracing and opt_step >= profile_from + profile_steps:
            jax.block_until_ready(loss)
            profiling.trace_stop()
            tracing = False
            profile_steps = 0  # one-shot; the trigger re-arms it
            logger.info(f"Profiler trace saved: {trace_dir}")

    # Bus handles resolved once (a per-step gauge set is then one lock,
    # no registry lookup). All interval clocks below are obs spans on the
    # shared monotonic source: an NTP step or suspend must not corrupt
    # ETA/throughput math on a days-long run; time.time() remains only
    # where a real timestamp is reported.
    g_loss = obs.BUS.gauge("train_loss")
    g_wps = obs.BUS.gauge("waveforms_per_sec")
    g_epoch = obs.BUS.gauge("epoch")
    g_gstep = obs.BUS.gauge("global_step")

    for epoch in range(start_epoch, epochs):
        epoch_span = obs.BUS.begin("train_epoch")
        g_epoch.set(epoch)
        train_loader.set_epoch(epoch)
        skip = start_batch if epoch == start_epoch else 0
        if skip and kpack > 1 and skip % kpack:
            # Packed paths consume kpack batches per call; a checkpoint
            # from the single-step path may sit off a call boundary.
            logger.warning(
                f"Resume offset {skip} is not a multiple of the packed "
                f"group {kpack}; rounding down (re-trains {skip % kpack} "
                "batch(es))"
            )
            skip = (skip // kpack) * kpack
        if skip:
            train_loader.set_start_batch(skip)
            logger.info(f"Mid-epoch resume: epoch {epoch} from batch {skip}")
        epoch_rng = jax.random.fold_in(base_rng, epoch)

        # -- train epoch (ref train.py:20-179) --------------------------------
        loss_meter = AverageMeter("loss", ":.4e")
        wps_meter = AverageMeter("wave/s", ":.1f")
        metrics_merged = _make_metrics(args, tasks, fs)
        progress = ProgressMeter(
            steps_per_epoch, [loss_meter, wps_meter], prefix=f"Epoch[{epoch}] "
        )
        # Log-interval clock for wave/s: span begin/end pairs replace the
        # old ad-hoc time.monotonic() bookkeeping.
        rate_span = obs.BUS.begin("log_interval")
        # Device->host transfers are confined to every --log-step steps:
        # pulling loss/outputs every step serializes JAX's async dispatch
        # and stalls the chip on host postprocess (the per-step numbers are
        # only diagnostics — TB scalars and the progress line). Per-step
        # losses are kept as device scalars and fetched once per epoch.
        deferred_losses: List[Any] = []
        global_bs = args.batch_size * jax.process_count()
        # Loader-death handling (io_guard.watch on_death): checkpoint at
        # the last completed batch and preempt-exit. `batches_done` is
        # kept current by every loop body; the closure reads the latest
        # `state` at fire time.
        batches_done = skip

        def _on_loader_death(e: io_guard.LoaderDeathError) -> None:
            _loader_death_exit(e, state, epoch, batches_done)

        if device_mode == "cached":
            # HBM-resident path: one jitted call = kpack scanned updates;
            # the ONLY per-call host->device traffic is the (k, B) int32
            # index array. Loss/save/preempt bookkeeping mirrors the
            # packed host path.
            import jax.numpy as jnp

            for call, idx_k in enumerate(
                dev_cache.epoch_index_chunks(
                    epoch,
                    seed=args.seed,
                    shuffle=args.shuffle,
                    batch_size=args.batch_size,
                    steps_per_call=kpack,
                    start_batch=skip,
                    num_shards=jax.process_count(),
                    shard_index=jax.process_index(),
                    source_ids=src_ids_logical,
                    mixture_temperature=mixture_t,
                ),
                start=skip // kpack,
            ):
                gstep = epoch * steps_per_epoch + call * kpack
                # Record BEFORE the spans of this step end, so the
                # recorder tags them with the step that is actually
                # running — the dying step's spans must carry its number.
                recorder.record_step(gstep)
                g_gstep.set(gstep)
                faults.on_step(gstep, n_steps=kpack)
                idx_dev = mesh_lib.shard_stacked_batch(mesh, idx_k)
                with obs.BUS.span("step_dispatch"):
                    state, loss, _, diag = _step_out(
                        train_step(
                            state, dev_cache.arrays, idx_dev,
                            jnp.int32(epoch), epoch_rng,
                        )
                    )
                deferred_losses.append(loss)
                if diag is not None and monitor.push(diag["applied"]):
                    state = _rollback(state)
                _log_kernel_status_once()
                _maybe_trace(call * updates_per_call, loss)
                batches_done = (call + 1) * kpack
                if save_every and (
                    batches_done // save_every
                    > (batches_done - kpack) // save_every
                ):
                    _interval_save(
                        state, epoch, batches_done,
                        epoch * steps_per_epoch + batches_done,
                    )
                if preempt.triggered:
                    _preempt_exit(
                        state, epoch, batches_done,
                        epoch * steps_per_epoch + batches_done,
                    )
                if call % args.log_step == 0:
                    loss_f = float(loss)
                    loss_meter.update(loss_f, 1)
                    interval = rate_span.end()
                    rate_span = obs.BUS.begin("log_interval")
                    calls_done = min(args.log_step, call) or 1
                    wps_meter.update(
                        global_bs * kpack * calls_done
                        / max(interval, 1e-9)
                    )
                    g_loss.set(loss_f)
                    g_wps.set(wps_meter.val)
                    if writer is not None:
                        writer.add_scalar(
                            "train-loss/step",
                            loss_f,
                            epoch * steps_per_epoch + call * kpack,
                        )
                    if is_main_process():
                        logger.info(
                            f"{args.model_name}_train "
                            f"{progress.get_str(call * kpack)}"
                        )

        elif device_mode == "step":
            # Raw rows cross the host per step (fancy-index gather, no
            # per-sample augmentation / label synthesis / stacking);
            # the jitted step does the rest. Per-step train metrics are
            # skipped like the packed path — metrics targets only exist
            # on the host pipeline.
            import jax.numpy as jnp

            for step, (rows, idx, aug) in enumerate(
                obs.timed_iter(
                    io_guard.watch(
                        pipeline.prefetch_raw_to_device(
                            pipeline.iter_raw_batches(
                                dev_store,
                                epoch,
                                seed=args.seed,
                                shuffle=args.shuffle,
                                batch_size=args.batch_size,
                                num_shards=jax.process_count(),
                                shard_index=jax.process_index(),
                                start_batch=skip,
                                source_ids=src_ids_logical,
                                mixture_temperature=mixture_t,
                            ),
                            mesh,
                        ),
                        watchdog,
                    ),
                    "host_wait",
                ),
                start=skip,
            ):
                batches_done = step + 1
                gstep = epoch * steps_per_epoch + step
                recorder.record_step(gstep)  # before this step's spans end
                g_gstep.set(gstep)
                faults.on_step(gstep)
                with obs.BUS.span("step_dispatch"):
                    state, loss, _, diag = _step_out(
                        train_step(
                            state, rows, idx, aug, jnp.int32(epoch), epoch_rng
                        )
                    )
                deferred_losses.append(loss)
                if diag is not None and monitor.push(diag["applied"]):
                    state = _rollback(state)
                _log_kernel_status_once()
                _maybe_trace(step, loss)
                if save_every and (step + 1) % save_every == 0:
                    _interval_save(state, epoch, step + 1, gstep + 1)
                if preempt.triggered:
                    _preempt_exit(state, epoch, step + 1, gstep + 1)
                if step % args.log_step == 0:
                    loss_f = float(loss)
                    loss_meter.update(loss_f, 1)
                    interval = rate_span.end()
                    rate_span = obs.BUS.begin("log_interval")
                    steps_done = min(args.log_step, step) or 1
                    wps_meter.update(
                        global_bs * steps_done / max(interval, 1e-9)
                    )
                    g_loss.set(loss_f)
                    g_wps.set(wps_meter.val)
                    if writer is not None:
                        writer.add_scalar("train-loss/step", loss_f, gstep)
                    if is_main_process():
                        logger.info(
                            f"{args.model_name}_train {progress.get_str(step)}"
                        )

        elif kpack > 1:
            # Packed path: one jitted call consumes kpack batches — either
            # kpack sequential updates (--steps-per-call) or one
            # accumulated update (--grad-accum-steps). The per-call loss is
            # already the mean over its micro-batches.
            for call, (xk, yk) in enumerate(
                obs.timed_iter(
                    io_guard.watch(
                        pipeline.prefetch_packed_to_device(
                            iter(train_loader), mesh, kpack
                        ),
                        watchdog,
                        on_death=_on_loader_death,
                    ),
                    "host_wait",
                ),
                start=skip // kpack,
            ):
                first_b = epoch * steps_per_epoch + call * kpack
                recorder.record_step(first_b)  # before this call's spans end
                g_gstep.set(first_b)
                faults.on_step(first_b, n_steps=kpack)
                xk = faults.corrupt_inputs(first_b, xk, n_steps=kpack)
                with obs.BUS.span("step_dispatch"):
                    state, loss, _, diag = _step_out(
                        train_step(state, xk, yk, epoch_rng)
                    )
                deferred_losses.append(loss)
                if diag is not None and monitor.push(diag["applied"]):
                    state = _rollback(state)
                _log_kernel_status_once()
                _maybe_trace(call * updates_per_call, loss)
                batches_done = (call + 1) * kpack
                if save_every and (
                    batches_done // save_every
                    > (batches_done - kpack) // save_every
                ):
                    _interval_save(
                        state, epoch, batches_done,
                        epoch * steps_per_epoch + batches_done,
                    )
                if preempt.triggered:
                    _preempt_exit(
                        state, epoch, batches_done,
                        epoch * steps_per_epoch + batches_done,
                    )
                if call % args.log_step == 0:
                    loss_f = float(loss)
                    loss_meter.update(loss_f, 1)
                    interval = rate_span.end()
                    rate_span = obs.BUS.begin("log_interval")
                    calls_done = min(args.log_step, call) or 1
                    wps_meter.update(
                        global_bs * kpack * calls_done
                        / max(interval, 1e-9)
                    )
                    g_loss.set(loss_f)
                    g_wps.set(wps_meter.val)
                    if writer is not None:
                        writer.add_scalar(
                            "train-loss/step",
                            loss_f,
                            epoch * steps_per_epoch + call * kpack,
                        )
                    if is_main_process():
                        logger.info(
                            f"{args.model_name}_train "
                            f"{progress.get_str(call * kpack)}"
                        )

        else:
            for step, batch in enumerate(
                obs.timed_iter(
                    io_guard.watch(
                        pipeline.prefetch_to_device(iter(train_loader), mesh),
                        watchdog,
                        on_death=_on_loader_death,
                    ),
                    "host_wait",
                ),
                start=skip,
            ):
                batches_done = step + 1
                gstep = epoch * steps_per_epoch + step
                recorder.record_step(gstep)  # before this step's spans end
                g_gstep.set(gstep)
                faults.on_step(gstep)
                inputs = faults.corrupt_inputs(gstep, batch.inputs)
                with obs.BUS.span("step_dispatch"):
                    state, loss, outputs, diag = _step_out(
                        train_step(
                            state, inputs, batch.loss_targets, epoch_rng
                        )
                    )
                deferred_losses.append(loss)
                if diag is not None and monitor.push(diag["applied"]):
                    state = _rollback(state)
                _log_kernel_status_once()
                _maybe_trace(step, loss)
                if save_every and (step + 1) % save_every == 0:
                    _interval_save(state, epoch, step + 1, gstep + 1)
                if preempt.triggered:
                    _preempt_exit(state, epoch, step + 1, gstep + 1)

                if step % args.log_step == 0:
                    loss_f = float(loss)
                    loss_meter.update(loss_f, 1)
                    interval = rate_span.end()
                    rate_span = obs.BUS.begin("log_interval")
                    steps_done = min(args.log_step, step) or 1
                    wps_meter.update(
                        global_bs * steps_done / max(interval, 1e-9)
                    )
                    g_loss.set(loss_f)
                    g_wps.set(wps_meter.val)

                    results = _postprocess_batch(args, spec, outputs, fs)
                    batch_metrics = _make_metrics(args, tasks, fs)
                    _update_task_metrics(
                        metrics_merged,
                        batch_metrics,
                        results,
                        batch.metrics_targets,
                        args.batch_size,
                    )
                    if writer is not None:
                        writer.add_scalar("train-loss/step", loss_f, gstep)
                        for task, m in batch_metrics.items():
                            writer.add_scalars(
                                f"train.{task}.metrics/step",
                                m.get_all_metrics(),
                                gstep,
                            )
                    if is_main_process():
                        logger.info(
                            f"{args.model_name}_train {progress.get_str(step)}"
                        )

        if tracing:  # epoch shorter than the capture window
            # Sync first: steps may still be executing asynchronously, and
            # stopping early would truncate their device activity.
            jax.block_until_ready(deferred_losses)
            profiling.trace_stop()
            tracing = False
            profile_steps = 0
            logger.info(f"Profiler trace saved (short epoch): {trace_dir}")

        if monitor.flush():  # lagging guard flags from the epoch tail
            state = _rollback(state)
        epoch_losses = [float(l) for l in jax.device_get(deferred_losses)]
        train_losses.extend(epoch_losses)
        # Exact epoch mean from every step's loss (the meter only samples
        # every log_step steps, for the progress line). Guard-skipped steps
        # leave non-finite entries in the raw curve; the epoch mean is
        # taken over the finite ones only.
        finite_losses = [l for l in epoch_losses if np.isfinite(l)]
        epoch_train_loss = (
            float(np.mean(finite_losses)) if finite_losses else 0.0
        )
        for m in metrics_merged.values():
            m.synchronize_between_processes()

        # -- data-plane epoch report (docs/FAULT_TOLERANCE.md) ----------------
        # Quarantined samples and guard counters, logged every epoch so a
        # slowly-rotting dataset is visible long before the
        # --max-quarantine-frac abort trips.
        q_report = train_loader.dataset.quarantine_report()
        if q_report["quarantined"]:
            logger.warning(
                f"[data-plane] epoch {epoch} quarantine report: "
                f"{json.dumps(q_report)}"
            )
            _emit_event(
                "quarantine_report", epoch=epoch,
                quarantined=len(q_report["quarantined"]),
                frac=q_report["frac"],
            )
        if io_guard.COUNTERS.any_faults():
            logger.info(
                f"[data-plane] counters: {io_guard.COUNTERS.snapshot()}"
            )

        # -- validate + checkpoint (ref train.py:402-415) ---------------------
        try:
            with obs.BUS.span("validate"):
                val_loss, val_metrics = validate(
                    args, state, eval_step, spec, val_loader, mesh,
                    watchdog=watchdog,
                )
        except io_guard.LoaderDeathError as e:
            _loader_death_exit(e, state, epoch, steps_per_epoch)
        obs.BUS.gauge("val_loss").set(val_loss)
        val_losses.append(val_loss)
        if writer is not None:
            writer.add_scalar("train-loss/epoch", epoch_train_loss, epoch)
            writer.add_scalar("val-loss/epoch", val_loss, epoch)
            # Train metrics accumulated at --log-step cadence + psum'd
            # across hosts above (ref train.py:420-442 logs both phases).
            for task, m in metrics_merged.items():
                writer.add_scalars(
                    f"train.{task}.metrics/epoch", m.get_all_metrics(), epoch
                )
            for task, m in val_metrics.items():
                writer.add_scalars(
                    f"val.{task}.metrics/epoch", m.get_all_metrics(), epoch
                )

        epoch_end_step = (epoch + 1) * steps_per_epoch
        if val_loss < best_loss:
            best_loss = val_loss
            patience_counter = 0
            # Checkpoint path is deterministic across hosts: step-numbered
            # under the log_dir that cli.main_worker broadcast from process 0
            # (replacing the reference's rank0 ckpt-path broadcast,
            # train.py:481-482). The val metric feeds the manager's
            # keep-best retention, so GC never deletes this step.
            best_ckpt_path = ckpt_mgr.save(
                epoch_end_step,
                state,
                epoch=epoch,
                data_epoch=epoch + 1,
                data_batch_offset=0,
                val_loss=val_loss,
                seed=args.seed,
                steps_per_epoch=steps_per_epoch,
                batch_size=int(args.batch_size),
                on_exists="skip",  # an interval save may own this boundary
            )
        else:
            patience_counter += 1
            if patience_counter > args.patience:
                logger.info(
                    f"Early stopping at epoch {epoch} "
                    f"(no val improvement in {args.patience} epochs)"
                )
                break
        if preempt.triggered:  # SIGTERM during validation
            _preempt_exit(state, epoch, steps_per_epoch, epoch_end_step)

        dt = epoch_span.end()
        epoch_times.append(dt)
        eta = float(np.mean(epoch_times)) * (epochs - epoch - 1)
        logger.info(
            f"Epoch {epoch}: train-loss {epoch_train_loss:.4e} "
            f"val-loss {val_loss:.4e} best {best_loss:.4e} "
            f"time {strftimedelta(dt)} ETA {strftimedelta(eta)}"
        )
        _emit_event(
            "epoch_summary",
            epoch=epoch,
            train_loss=round(epoch_train_loss, 6),
            val_loss=round(float(val_loss), 6),
            best_loss=round(float(best_loss), 6),
            epoch_time_s=round(dt, 3),
            wps=round(wps_meter.val, 1),
            data_plane=io_guard.COUNTERS.snapshot(),
        )

    preempt.__exit__()
    if watchdog is not None:
        watchdog.stop()
    if io_guard.COUNTERS.any_faults():
        logger.info(
            f"[data-plane] run counters: {io_guard.COUNTERS.snapshot()}"
        )
    if monitor.total_skipped:
        logger.warning(
            f"Bad-update guard skipped {monitor.total_skipped} non-finite "
            "update(s) this run"
        )
    ckpt_mgr.close()  # barrier on any in-flight async save
    if is_main_process():
        np.save(os.path.join(logger.logdir(), "train_losses.npy"), train_losses)
        np.save(os.path.join(logger.logdir(), "val_losses.npy"), val_losses)
    if writer is not None:
        writer.close()
    _emit_event("train_done", best_loss=round(float(best_loss), 6))
    _obs_close()
    train_loader.close()
    val_loader.close()
    return best_ckpt_path


def test_worker(args: Any) -> float:
    """Test run on the held-out split (ref test.py:10-88). Returns loss."""
    spec = taskspec.get_task_spec(args.model_name)
    loss_fn = spec.loss()
    mesh = mesh_lib.make_mesh(seq=int(getattr(args, "seq_shards", 1) or 1))
    mesh_lib.set_active_mesh(mesh)

    test_loader = _build_loader(args, spec, "test")

    in_channels = taskspec.get_num_inchannels(args.model_name)
    model = api.create_model(
        args.model_name, in_channels=in_channels, in_samples=args.in_samples
    )
    variables = api.init_variables(
        model, seed=args.seed, in_samples=args.in_samples, in_channels=in_channels
    )
    tx = build_optimizer(args.optim, args.max_lr)
    state = create_train_state(model, variables, tx)

    if not args.checkpoint:
        raise ValueError("test mode requires --checkpoint")
    # Raw (target-free) restore: test never steps the optimizer, and the
    # test-time tx may have a different state structure (float LR vs
    # schedule) — params + batch_stats are all that matter (the reference
    # likewise tolerates bare state-dicts, _factory.py:101-102).
    restored = load_checkpoint(args.checkpoint)
    state = state.replace(
        params=restored["params"],
        batch_stats=restored.get("batch_stats") or state.batch_stats,
    )
    logger.info(f"Loaded checkpoint: {args.checkpoint}")

    eval_step = jit_eval_step(
        make_eval_step(
            spec, loss_fn, compute_dtype=getattr(args, "dtype", "fp32")
        ),
        mesh,
    )
    # Same stall protection as training (--data-watchdog-sec): a wedged
    # test loader exits with the preempt code instead of hanging. A
    # loader death here simply propagates — there is no training state
    # to checkpoint, and a loud crash beats a silent hang.
    wd_timeout = float(getattr(args, "data_watchdog_sec", 0.0) or 0.0)
    watchdog = (
        io_guard.StallWatchdog(wd_timeout).start() if wd_timeout > 0 else None
    )
    try:
        loss, metrics_merged = validate(
            args,
            state,
            eval_step,
            spec,
            test_loader,
            mesh,
            testing=True,
            save_results=args.save_test_results,
            watchdog=watchdog,
        )
    finally:
        if watchdog is not None:
            watchdog.stop()
    if is_main_process():
        # Structured metrics artifact beside the log/CSV (the reference only
        # logs a formatted string, test.py:83-88); consumed by
        # tools/parity_eval.py and anything scripting over test runs.
        payload = {
            "model": args.model_name,
            "dataset": args.dataset_name,
            "loss": float(loss),
            "metrics": {
                task: m.get_metrics(m.metric_names())
                for task, m in metrics_merged.items()
            },
        }
        out_json = get_safe_path(
            os.path.join(
                logger.logdir(), f"test_metrics_{args.dataset_name}.json"
            )
        )
        with open(out_json, "w") as f:
            json.dump(payload, f, indent=1)
        logger.info(f"Test metrics saved: {out_json}")
    test_loader.close()
    return loss
