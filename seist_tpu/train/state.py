"""Train state pytree.

One immutable pytree carries everything the reference's train worker keeps in
mutable objects (model.parameters(), BN running stats inside modules,
optimizer state, global step — /root/reference/training/train.py:278-354).
Being a pytree, the whole state threads through a single jitted train step and
shards/replicates uniformly over the mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import optax
from flax import core, struct
from flax.training import train_state


class TrainState(train_state.TrainState):
    """Flax TrainState + BatchNorm running statistics.

    ``batch_stats`` replaces torch BN buffers; under jit with the batch
    sharded on the ``data`` axis, reductions over the batch axis are *global*
    (XLA inserts the collective), so cross-replica stat sync — the
    reference's SyncBatchNorm conversion (train.py:374) — falls out for free.
    """

    batch_stats: core.FrozenDict[str, Any] = struct.field(default=None)


def create_train_state(
    model,
    variables: dict,
    tx: optax.GradientTransformation,
) -> TrainState:
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        batch_stats=variables.get("batch_stats"),
        tx=tx,
    )
