"""Mixed-precision policy: bf16 compute, fp32 params/optimizer/stats.

TPU-first lever the torch reference lacks entirely (its only nod is the
TF32 matmul hint at ref main.py:224-226): run matmuls/convs/elementwise in
bfloat16 on the MXU/VPU while keeping everything stateful — params,
optimizer moments, BatchNorm running stats — and everything numerically
delicate — BN statistics (flax computes them in >=fp32 internally),
attention softmax (the Pallas kernel upcasts to fp32 in VMEM), the loss —
in float32.

Implementation is jmp-style step-level casting, not per-module dtype
threading: the train/eval step casts params and inputs to the compute dtype
before ``apply`` and casts outputs back to fp32 before the loss. Gradients
flow through the cast back to the fp32 master params, so the optimizer
update is full precision. BatchNorm modules additionally need their
*output* dtype pinned (their fp32 running stats would otherwise promote
every activation back to fp32) — ``models/common.py::make_norm`` consults
the trace-time policy below for that.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Optional

import jax
import jax.numpy as jnp

_POLICY: dict = {"dtype": None}


def resolve_dtype(name: Optional[str]):
    """Map a CLI-level dtype name to a jnp dtype (None = full fp32)."""
    if name is None:
        return None
    key = str(name).lower()
    if key in ("fp32", "float32", "f32", "none"):
        return None
    if key in ("bf16", "bfloat16"):
        return jnp.bfloat16
    raise ValueError(f"Unknown compute dtype '{name}' (use fp32 or bf16)")


def policy_dtype():
    """The active compute dtype (None outside a ``precision_policy`` block)."""
    return _POLICY["dtype"]


@contextmanager
def precision_policy(dtype):
    """Activate a compute dtype for the duration of a model trace."""
    old = _POLICY["dtype"]
    _POLICY["dtype"] = dtype
    try:
        yield
    finally:
        _POLICY["dtype"] = old


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves of a pytree; leave ints/bools/None untouched."""
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def cast_to_float32(tree: Any) -> Any:
    return jax.tree.map(
        lambda x: x.astype(jnp.float32)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )
