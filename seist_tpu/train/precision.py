"""Mixed-precision policy: bf16 compute, fp32 params/optimizer/stats.

TPU-first lever the torch reference lacks entirely (its only nod is the
TF32 matmul hint at ref main.py:224-226): run matmuls/convs/elementwise in
bfloat16 on the MXU/VPU while keeping everything stateful — params,
optimizer moments, BatchNorm running stats — and everything numerically
delicate — BN statistics (flax computes them in >=fp32 internally),
attention softmax (the Pallas kernel upcasts to fp32 in VMEM), the loss —
in float32.

Implementation is jmp-style step-level casting, not per-module dtype
threading: the train/eval step casts params and inputs to the compute dtype
before ``apply`` and casts outputs back to fp32 before the loss. Gradients
flow through the cast back to the fp32 master params, so the optimizer
update is full precision. BatchNorm modules additionally need their
*output* dtype pinned (their fp32 running stats would otherwise promote
every activation back to fp32) — ``models/common.py::make_norm`` consults
the trace-time policy below for that.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Optional

import jax
import jax.numpy as jnp


class _Policy(threading.local):
    """Per-THREAD active policy. The train worker is single-threaded,
    but the serve plane traces programs under ``precision_policy`` from
    several threads at once (async warm-up, per-variant batcher flush
    threads building live-jit fallbacks): with a process-global policy,
    an fp32 program traced while another thread holds the bf16 policy
    would silently compile bf16 norms/LSTM carries into the fp32
    (parity-reference) executable."""

    dtype = None  # class attr = the per-thread default


_POLICY = _Policy()


def resolve_dtype(name: Optional[str]):
    """Map a CLI-level dtype name to a jnp dtype (None = full fp32)."""
    if name is None:
        return None
    key = str(name).lower()
    if key in ("fp32", "float32", "f32", "none"):
        return None
    if key in ("bf16", "bfloat16"):
        return jnp.bfloat16
    raise ValueError(f"Unknown compute dtype '{name}' (use fp32 or bf16)")


def policy_dtype():
    """The active compute dtype (None outside a ``precision_policy`` block)."""
    return _POLICY.dtype


def policy_param_dtype():
    """Dtype for trace-time-created carries/params of policy-aware modules
    (``models/common.py::make_norm``'s norm dtype, ``common.LSTM``'s cell
    carry): the active compute dtype, fp32 outside a policy block.

    This is the contract the irlint ``f32-matmul-under-bf16-policy`` rule
    audits: any module that materializes a NEW floating array at trace
    time (an RNN carry, a norm's internal stats) must draw its dtype from
    the policy — one fp32 trace-time array silently promotes every matmul
    downstream of it back to fp32 (the eqtransformer/magnet LSTM-carry
    gap: bf16 coverage 0.44/0.41 until the carry followed the policy).
    Step-level casting (``cast_floating`` on params/inputs) cannot reach
    these arrays because they never exist outside the trace.
    """
    return _POLICY.dtype or jnp.float32


@contextmanager
def precision_policy(dtype):
    """Activate a compute dtype for the duration of a model trace
    (thread-scoped — see :class:`_Policy`)."""
    old = _POLICY.dtype
    _POLICY.dtype = dtype
    try:
        yield
    finally:
        _POLICY.dtype = old


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves of a pytree; leave ints/bools/None untouched."""
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def cast_to_float32(tree: Any) -> Any:
    return jax.tree.map(
        lambda x: x.astype(jnp.float32)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )
