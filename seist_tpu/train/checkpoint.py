"""Checkpoint save / restore (orbax).

Counterpart of the reference's torch checkpointing
(/root/reference/models/_factory.py:59-126): the saved payload carries the
same logical fields — epoch, model params (+ BN stats), optimizer state, best
loss — and restore tolerates params-only checkpoints the way the reference
tolerates raw state-dicts (:101-102). DDP/compile prefix-stripping has no
analogue here: a pytree is a pytree.

Orbax handles multi-host coordination internally (every process must call
save; only process 0 writes metadata), replacing the reference's
rank-0-only torch.save guard (train.py:407-415).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from seist_tpu.utils.logger import logger


def _as_abstract(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), tree
    )


def save_checkpoint(
    ckpt_dir: str,
    state,
    epoch: int,
    loss: float,
) -> str:
    """Write ``<ckpt_dir>/model-<epoch>`` (ref naming: `model-{epoch}.pth`,
    train.py:411). Returns the checkpoint path."""
    path = os.path.join(os.path.abspath(ckpt_dir), f"model-{epoch}")
    # opt_state is stored as a flat leaves list: optax state trees contain
    # empty-namedtuple nodes (EmptyState) that do not round-trip through a
    # structured orbax restore; the treedef comes from the live TrainState at
    # restore time (restore_into_state).
    payload = {
        "params": state.params,
        "batch_stats": state.batch_stats if state.batch_stats is not None else {},
        "opt_state": list(jax.tree_util.tree_leaves(state.opt_state)),
        "meta": {"epoch": epoch, "loss": float(loss), "step": int(state.step)},
    }
    with ocp.StandardCheckpointer() as saver:
        saver.save(path, payload, force=True)
    logger.info(f"Checkpoint saved: {path}")
    return path


def load_checkpoint(
    ckpt_path: str,
    state=None,
) -> Dict[str, Any]:
    """Restore a checkpoint.

    With ``state`` given, the restored arrays adopt the state's exact
    structure/dtypes (full resume: params + batch_stats + opt_state + meta).
    Without it, returns the raw pytree (params-only inspection / inference),
    mirroring the reference's tolerance for bare state-dicts
    (_factory.py:101-102).
    """
    path = os.path.abspath(ckpt_path)
    with ocp.StandardCheckpointer() as restorer:
        if state is None:
            return restorer.restore(path)
        target = {
            "params": _as_abstract(state.params),
            "batch_stats": _as_abstract(
                state.batch_stats if state.batch_stats is not None else {}
            ),
            "opt_state": _as_abstract(
                list(jax.tree_util.tree_leaves(state.opt_state))
            ),
            "meta": {"epoch": 0, "loss": 0.0, "step": 0},
        }
        try:
            return restorer.restore(path, target)
        except Exception:
            raw = restorer.restore(path)
            if "opt_state" in raw:
                # The checkpoint IS a full one — the structured restore
                # failed for a real reason (shape mismatch from a wrong
                # --model-name, partial write, ...). Surface that, don't
                # silently resume with fresh optimizer moments.
                raise
    # Params(+stats)-only checkpoint — e.g. written by
    # tools/import_pretrained.py from the reference's raw .pth state-dicts.
    # Adopt the weights, keep the fresh optimizer state: the reference's
    # loader has the same tolerance (_factory.py:101-102 treats a bare
    # state-dict as the model dict and resumes with epoch -1).
    logger.info(
        f"Checkpoint {path} has no optimizer state; loading params only"
    )
    return {
        "params": raw["params"],
        "batch_stats": raw.get("batch_stats") or {},
        "opt_state": list(jax.tree_util.tree_leaves(state.opt_state)),
        "meta": raw.get("meta")
        or {"epoch": -1, "loss": float("inf"), "step": 0},
    }


def restore_into_state(state, restored: Dict[str, Any]):
    """Apply a restored payload onto a TrainState (resume path,
    ref train.py:255-264,324-326)."""
    opt_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(state.opt_state),
        jax.tree_util.tree_leaves(restored["opt_state"]),
    )
    return state.replace(
        params=restored["params"],
        batch_stats=restored["batch_stats"] or state.batch_stats,
        opt_state=opt_state,
        step=int(restored["meta"]["step"]),
    )
