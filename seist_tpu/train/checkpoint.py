"""Checkpoint save / restore (orbax), step-granular and preemption-safe.

Two layers:

* :class:`TrainCheckpointManager` — the fault-tolerance layer built on
  ``orbax.checkpoint.CheckpointManager``: step-granular saves keyed by the
  GLOBAL BATCH counter, async (background) writes with a
  barrier-at-next-save, a keep-last-K-plus-best retention policy with
  logged GC, and orbax's atomic finalize (a save lands in
  ``model_<step>.orbax-checkpoint-tmp-<n>`` and is renamed only when
  complete, so a crash mid-save never corrupts — or even exposes — the
  newest checkpoint; interrupted tmp dirs are swept on the next open).
  The payload carries FULL resume state: params, BN stats, optimizer
  leaves, and a meta record with the data-pipeline position
  (``data_epoch``, ``data_batch_offset``, seed) and the schedule step, so
  a restore continues mid-epoch without replaying or skipping data.

* Legacy functions (``save_checkpoint`` / ``load_checkpoint`` /
  ``restore_into_state``) — the epoch-named single-checkpoint path the
  reference's torch checkpointing maps onto
  (/root/reference/models/_factory.py:59-126). ``load_checkpoint`` also
  restores manager-written step directories (it descends into the
  ``default/`` item dir), so tools/supervise.py can hand either layout to
  ``--checkpoint``.

Orbax handles multi-host coordination internally (every process must call
save; only process 0 writes metadata), replacing the reference's
rank-0-only torch.save guard (train.py:407-415).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from seist_tpu.utils.logger import logger

# Exit code of a training process that checkpointed and exited on SIGTERM
# (sysexits.h EX_TEMPFAIL: "temporary failure, retry"). tools/supervise.py
# treats it as a clean preemption — immediate relaunch, retry budget
# untouched. Keep in sync with tools/supervise.py:PREEMPT_EXIT_CODE (that
# file stays stdlib-only and cannot import this one).
PREEMPT_EXIT_CODE = 75

# Resume meta written by the manager. Superset of the legacy
# {epoch, loss, step}: data_epoch/data_batch_offset pin the data-pipeline
# position (the shuffle order is a pure function of (seed, data_epoch),
# data/pipeline.py), and step doubles as the LR-schedule position (optax
# schedules read the update count, which save/restore round-trips via
# state.step and the opt_state count leaves).
_RESUME_META = {
    "epoch": 0,
    "loss": 0.0,
    "step": 0,
    "data_epoch": 0,
    "data_batch_offset": 0,
    "total_batches": 0,
    "seed": 0,
    # Batch geometry the data position is expressed in: a resume with a
    # different --batch-size would reinterpret the offset and replay/skip
    # samples, so the worker validates these like the seed.
    "steps_per_epoch": 0,
    "batch_size": 0,
}
_LEGACY_META = {"epoch": 0, "loss": 0.0, "step": 0}


def _as_abstract(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), tree
    )


def _host_copy(tree):
    """Deep-copy a pytree to host numpy. Async saves serialize in the
    background while the train loop keeps stepping with DONATED state
    buffers; on the CPU backend np-views of those buffers would be
    silently rewritten mid-serialization, so the snapshot must own its
    memory."""
    return jax.tree_util.tree_map(
        lambda x: np.array(x) if hasattr(x, "shape") else x, tree
    )


def _state_payload(state) -> Dict[str, Any]:
    # opt_state is stored as a flat leaves list: optax state trees contain
    # empty-namedtuple nodes (EmptyState) that do not round-trip through a
    # structured orbax restore; the treedef comes from the live TrainState
    # at restore time (restore_into_state).
    return {
        "params": state.params,
        "batch_stats": state.batch_stats if state.batch_stats is not None else {},
        "opt_state": list(jax.tree_util.tree_leaves(state.opt_state)),
    }


def _restore_target(state, meta_defaults: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "params": _as_abstract(state.params),
        "batch_stats": _as_abstract(
            state.batch_stats if state.batch_stats is not None else {}
        ),
        "opt_state": _as_abstract(
            list(jax.tree_util.tree_leaves(state.opt_state))
        ),
        "meta": dict(meta_defaults),
    }


class ProgressFile:
    """Tiny atomic JSON progress record — the ``best.json`` tmp+rename
    pattern generalized for flat (non-orbax) progress state. Used by the
    batch re-picking workers (tools/repick_archive.py) to persist their
    position between segment commits: ``load()`` returns the last saved
    dict (or None), ``save()`` replaces it atomically, so a SIGKILL at
    any instant leaves either the previous record or the new one —
    never a torn file. The record is advisory (the committed segment
    files are the authoritative resume state); it exists so a resumed
    worker can log where it died and skip completed units in O(1)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def save(self, record: Dict[str, Any]) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, sort_keys=True)
        os.replace(tmp, self.path)


class TrainCheckpointManager:
    """Step-granular async checkpointing with keep-last-K + best retention.

    ``step`` keys are the run's global batch counter
    (``epoch * steps_per_epoch + batches_done``): monotonic across
    epochs, aligned with the fault-injection step numbering, and exactly
    the quantity "work lost on preemption" is measured in.

    Async contract: ``save`` snapshots the state to host memory
    synchronously (donation-safe) and serializes in the background; the
    next ``save`` (or ``wait()`` / ``close()``) barriers on the previous
    one, so at most one write is ever in flight and a completed ``save``
    call means the PREVIOUS checkpoint is durable.
    """

    def __init__(
        self,
        directory: str,
        *,
        keep_last: int = 3,
        async_save: bool = True,
        step_prefix: str = "model",
    ):
        self.directory = os.path.abspath(directory)
        self.keep_last = max(1, int(keep_last))
        self._step_prefix = step_prefix
        self._best_step: Optional[int] = None
        self._best_loss = float("inf")
        # Best tracking must survive the manager's own process dying —
        # that is the PR's whole scenario. A preempted run that resumed
        # with only in-memory best state would let _gc delete the run's
        # best-val checkpoint a few saves later.
        self._best_file = os.path.join(self.directory, "best.json")
        self._load_best()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None,  # retention is ours: last K + best
                step_prefix=step_prefix,
                enable_async_checkpointing=async_save,
                create=True,
                # Sweep `.orbax-checkpoint-tmp-*` left by a crash mid-save.
                cleanup_tmp_directories=True,
            ),
        )

    # ------------------------------------------------------------- queries
    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self._step_prefix}_{step}")

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    @property
    def best_step(self) -> Optional[int]:
        return self._best_step

    # --------------------------------------------------------------- save
    def save(
        self,
        step: int,
        state,
        *,
        epoch: int,
        data_epoch: int,
        data_batch_offset: int,
        loss: float = float("inf"),
        val_loss: Optional[float] = None,
        seed: int = 0,
        steps_per_epoch: int = 0,
        batch_size: int = 0,
        wait: bool = False,
        on_exists: str = "error",
    ) -> str:
        """Write checkpoint ``step``. Returns the (future) step path.

        ``data_epoch`` / ``data_batch_offset`` must be the position of the
        NEXT batch to consume — restore hands them straight to
        ``Loader.set_start_batch``. ``val_loss`` (when this save follows a
        validation pass) feeds the best-checkpoint retention. Overwriting
        an existing step is an explicit error (``on_exists='error'``);
        schedule-driven savers that may legitimately re-reach a step
        boundary (epoch-end save after an interval save, resume replay)
        pass ``on_exists='skip'``.
        """
        if step in self._mgr.all_steps():
            if on_exists == "skip":
                logger.info(f"Checkpoint step {step} already saved; skipping")
                self._note_metric(step, val_loss)
                if wait:  # the skipped step's async write may be in flight
                    self.wait()
                return self.step_path(step)
            raise FileExistsError(
                f"checkpoint step {step} already exists in {self.directory}; "
                "refusing to overwrite (pass on_exists='skip' to tolerate)"
            )
        payload = _host_copy(_state_payload(state))
        payload["meta"] = {
            "epoch": int(epoch),
            "loss": float(loss if val_loss is None else val_loss),
            "step": int(state.step),
            "data_epoch": int(data_epoch),
            "data_batch_offset": int(data_batch_offset),
            "total_batches": int(step),
            "seed": int(seed),
            "steps_per_epoch": int(steps_per_epoch),
            "batch_size": int(batch_size),
        }
        # Implicit barrier-at-next-save: orbax waits for the in-flight
        # write before starting this one. force=True bypasses orbax's
        # should_save, which silently SKIPS any step <= the directory's
        # latest — a run resumed from an older step (manual rollback to
        # best) would otherwise log saves that never happened. Overwrite
        # protection is ours (the on_exists check above).
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(payload), force=True
        )
        if not saved:
            raise RuntimeError(
                f"orbax declined checkpoint save at step {step} in "
                f"{self.directory}"
            )
        self._note_metric(step, val_loss)
        self._gc(protect=step)
        if wait:
            self.wait()
        logger.info(
            f"Checkpoint save dispatched: step {step} "
            f"(epoch {epoch}, data position {data_epoch}:{data_batch_offset})"
        )
        return self.step_path(step)

    def _load_best(self) -> None:
        try:
            with open(self._best_file) as f:
                best = json.load(f)
            self._best_step = int(best["step"])
            self._best_loss = float(best["loss"])
        except (OSError, ValueError, KeyError):
            pass  # no sidecar yet (fresh run / legacy dir)

    def _note_metric(self, step: int, val_loss: Optional[float]) -> None:
        if val_loss is None or float(val_loss) >= self._best_loss:
            return
        self._best_loss = float(val_loss)
        self._best_step = step
        # Persist (process 0 only; every host computes the same best from
        # the host-identical val loss). Atomic tmp+rename so a crash
        # mid-write leaves the previous record intact.
        if jax.process_index() == 0:
            tmp = self._best_file + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"step": step, "loss": self._best_loss}, f)
                os.replace(tmp, self._best_file)
            except OSError as e:
                logger.warning(f"best.json write failed: {e!r}")

    def _gc(self, protect: int) -> None:
        """Keep the last ``keep_last`` steps plus the best-val step; delete
        (and log) the rest. ``protect`` is the just-dispatched step, which
        may not appear in ``all_steps`` until its async write finalizes."""
        steps = sorted(set(self._mgr.all_steps()) | {protect})
        keep = set(steps[-self.keep_last:])
        keep.add(protect)
        if self._best_step is not None:
            keep.add(self._best_step)
        for s in steps:
            if s in keep:
                continue
            logger.info(
                f"Checkpoint GC: deleting step {s} ({self.step_path(s)}) — "
                f"retention keeps last {self.keep_last} + best "
                f"({self._best_step})"
            )
            self._mgr.delete(s)

    # ------------------------------------------------------------ restore
    def restore(self, state, step: Optional[int] = None) -> Dict[str, Any]:
        """Restore checkpoint ``step`` (default: latest) shaped like the
        live ``state``; returns the payload dict for
        :func:`restore_into_state`."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint to restore in {self.directory}"
            )
        target = _restore_target(state, _RESUME_META)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return restored

    # ------------------------------------------------------------ control
    def wait(self) -> None:
        """Barrier on the in-flight async save (preempt exit path: the
        checkpoint must be durable before the process dies)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def save_checkpoint(
    ckpt_dir: str,
    state,
    epoch: int,
    loss: float,
) -> str:
    """Write ``<ckpt_dir>/model-<epoch>`` (ref naming: `model-{epoch}.pth`,
    train.py:411). Returns the checkpoint path.

    Overwriting an existing checkpoint is an explicit error: the old
    ``force=True`` silently clobbered a prior ``model-<epoch>`` (e.g. two
    runs sharing a log dir, or a resume with a stale ``--start-epoch``),
    destroying the only copy of those params. Step-granular training
    should use :class:`TrainCheckpointManager` instead.
    """
    path = os.path.join(os.path.abspath(ckpt_dir), f"model-{epoch}")
    if os.path.exists(path):
        raise FileExistsError(
            f"checkpoint {path} already exists; refusing to overwrite "
            "(delete it or choose a different epoch/log dir)"
        )
    payload = _state_payload(state)
    payload["meta"] = {"epoch": epoch, "loss": float(loss), "step": int(state.step)}
    with ocp.StandardCheckpointer() as saver:
        saver.save(path, payload)
    logger.info(f"Checkpoint saved: {path}")
    return path


def _payload_dir(ckpt_path: str) -> str:
    """Resolve the orbax item dir: manager-written steps nest the payload
    under ``<step>/default`` (single-item CheckpointManager layout)."""
    path = os.path.abspath(ckpt_path)
    default = os.path.join(path, "default")
    if os.path.isdir(default):
        return default
    return path


def load_checkpoint(
    ckpt_path: str,
    state=None,
) -> Dict[str, Any]:
    """Restore a checkpoint.

    With ``state`` given, the restored arrays adopt the state's exact
    structure/dtypes (full resume: params + batch_stats + opt_state + meta).
    Without it, returns the raw pytree (params-only inspection / inference),
    mirroring the reference's tolerance for bare state-dicts
    (_factory.py:101-102). Accepts both legacy ``model-<epoch>`` dirs and
    manager-written ``model_<step>`` dirs (resume meta included).
    """
    path = _payload_dir(ckpt_path)
    is_manager_layout = path != os.path.abspath(ckpt_path)
    with ocp.StandardCheckpointer() as restorer:
        if state is None:
            return restorer.restore(path)
        # Manager-written checkpoints (default/ item layout) carry the
        # full resume meta; legacy ones only {epoch, loss, step}. Try the
        # layout's native format first so the kept exception is the
        # informative one (a param-shape mismatch, not the other
        # format's meta-tree mismatch).
        metas = (
            (_RESUME_META, _LEGACY_META)
            if is_manager_layout
            else (_LEGACY_META, _RESUME_META)
        )
        first_exc: Optional[Exception] = None
        for meta in metas:
            try:
                return restorer.restore(path, _restore_target(state, meta))
            # Probing both meta layouts: orbax raises layout-specific types
            # we can't enumerate. The first (most informative) failure is
            # kept and re-raised below if the raw restore can't save us.
            except Exception as e:
                first_exc = first_exc or e
        raw = restorer.restore(path)
        if "opt_state" in raw:
            # The checkpoint IS a full one — the structured restore
            # failed for a real reason (shape mismatch from a wrong
            # --model-name, partial write, ...). Surface that (chaining
            # orbax's precise mismatch message), don't silently resume
            # with fresh optimizer moments.
            raise ValueError(
                f"checkpoint {path} has optimizer state but does not match "
                "the live TrainState (wrong --model-name? partial write?)"
            ) from first_exc
    # Params(+stats)-only checkpoint — e.g. written by
    # tools/import_pretrained.py from the reference's raw .pth state-dicts.
    # Adopt the weights, keep the fresh optimizer state: the reference's
    # loader has the same tolerance (_factory.py:101-102 treats a bare
    # state-dict as the model dict and resumes with epoch -1).
    logger.info(
        f"Checkpoint {path} has no optimizer state; loading params only"
    )
    return {
        "params": raw["params"],
        "batch_stats": raw.get("batch_stats") or {},
        "opt_state": list(jax.tree_util.tree_leaves(state.opt_state)),
        "meta": raw.get("meta")
        or {"epoch": -1, "loss": float("inf"), "step": 0},
    }


def restore_into_state(state, restored: Dict[str, Any]):
    """Apply a restored payload onto a TrainState (resume path,
    ref train.py:255-264,324-326)."""
    opt_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(state.opt_state),
        jax.tree_util.tree_leaves(restored["opt_state"]),
    )
    return state.replace(
        params=restored["params"],
        batch_stats=restored["batch_stats"] or state.batch_stats,
        opt_state=opt_state,
        step=int(restored["meta"]["step"]),
    )
