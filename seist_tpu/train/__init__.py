"""Training engine: schedules, optimizers, jitted steps, checkpointing."""

from seist_tpu.train.checkpoint import (  # noqa: F401
    PREEMPT_EXIT_CODE,
    TrainCheckpointManager,
    load_checkpoint,
    restore_into_state,
    save_checkpoint,
)
from seist_tpu.train.optim import build_optimizer, l1_sign_decay  # noqa: F401
from seist_tpu.train.schedule import (  # noqa: F401
    build_cyclic_schedule,
    cyclic_lr,
    reference_gamma,
)
from seist_tpu.train.state import TrainState, create_train_state  # noqa: F401
from seist_tpu.train.step import (  # noqa: F401
    fold_rngs,
    jit_cached_call,
    jit_device_aug_step,
    jit_eval_step,
    jit_multi_step,
    jit_step,
    make_cached_train_call,
    make_device_aug_train_step,
    make_eval_step,
    make_accum_train_step,
    make_multi_train_step,
    make_train_step,
    resolve_donation,
)
