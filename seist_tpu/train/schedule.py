"""Learning-rate schedules.

The reference trains every model with torch ``CyclicLR``
(/root/reference/training/train.py:343-354): warmup of ``step_size_up`` steps
from base_lr to max_lr, then ``step_size_down`` back, cycling; mode one of
triangular / triangular2 / exp_range, with the quirky
``gamma = base_lr ** (1 / (2 * steps))`` rule computed by the caller
(train.py:349). This module reproduces those semantics as a pure
``step -> lr`` function usable directly as an optax schedule inside jit.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def cyclic_lr(
    base_lr: float,
    max_lr: float,
    step_size_up: int,
    step_size_down: Optional[int] = None,
    mode: str = "triangular",
    gamma: float = 1.0,
):
    """torch.optim.lr_scheduler.CyclicLR parity (cycle_momentum=False).

    Formula matches torch's ``get_lr``: position inside the cycle scales the
    height (max_lr - base_lr); triangular2 halves the height each cycle;
    exp_range multiplies it by gamma**step.
    """
    if mode not in ("triangular", "triangular2", "exp_range"):
        raise ValueError(f"Unknown CyclicLR mode: {mode}")
    step_size_up = float(step_size_up)
    step_size_down = float(
        step_size_down if step_size_down is not None else step_size_up
    )
    total_size = step_size_up + step_size_down
    step_ratio = step_size_up / total_size

    def schedule(count):
        t = jnp.asarray(count, dtype=jnp.float32)
        cycle = jnp.floor(1.0 + t / total_size)
        x = 1.0 + t / total_size - cycle
        scale_factor = jnp.where(
            x <= step_ratio, x / step_ratio, (x - 1.0) / (step_ratio - 1.0)
        )
        height = (max_lr - base_lr) * scale_factor
        if mode == "triangular":
            return base_lr + height
        if mode == "triangular2":
            return base_lr + height * (2.0 ** -(cycle - 1.0))
        return base_lr + height * jnp.power(gamma, t)

    return schedule


def reference_gamma(base_lr: float, total_steps: int) -> float:
    """The caller-side gamma rule (ref: train.py:349):
    ``gamma = base_lr ** ((steps * 2) ** -1)`` so the exp_range envelope
    decays to ~sqrt(base_lr) over the run."""
    return float(base_lr ** ((total_steps * 2) ** -1))


def build_cyclic_schedule(
    base_lr: float,
    max_lr: float,
    total_steps: int,
    warmup_steps: float = 2000,
    down_steps: float = 3000,
    mode: str = "exp_range",
):
    """Schedule construction exactly as the reference train worker does it
    (train.py:328-354): warmup/down values < 1 are ratios of total steps."""
    up = warmup_steps if warmup_steps >= 1 else max(1, int(warmup_steps * total_steps))
    down = down_steps if down_steps >= 1 else max(1, int(down_steps * total_steps))
    return cyclic_lr(
        base_lr=base_lr,
        max_lr=max_lr,
        step_size_up=int(up),
        step_size_down=int(down),
        mode=mode,
        gamma=reference_gamma(base_lr, total_steps),
    )
