"""Jitted train / eval steps.

The reference's per-batch hot loop (/root/reference/training/train.py:75-177)
is: H2D copy -> forward -> loss -> backward -> optimizer -> NCCL allreduce.
Here the entire step is ONE jitted XLA program: forward + backward + update
fuse, and when the batch is sharded over the mesh's ``data`` axis the gradient
all-reduce is emitted by XLA over ICI — there is no DDP wrapper and no
explicit collective call.

Loss/target transforms come from the TaskSpec
(seist_tpu/taskspec.py; ref config.py:88-135), applied inside the jitted
program so e.g. the baz (cos,sin) encoding costs nothing extra.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seist_tpu.taskspec import TaskSpec
from seist_tpu.train.precision import (
    cast_floating,
    cast_to_float32,
    precision_policy,
    resolve_dtype,
)
from seist_tpu.train.state import TrainState


_donation_gate_logged = False


def resolve_donation(donate: Tuple[int, ...]) -> Tuple[int, ...]:
    """Donation/compile-cache correctness gate (ROADMAP open item).

    On jax 0.4.37's CPU backend, an executable DESERIALIZED from the
    persistent XLA compile cache intermittently (~20-40% of processes)
    corrupts donated outputs in unsynchronized donated step chains —
    after a few back-to-back train steps ``state.step`` reads back
    another buffer's bits and repeated reads of the same Array differ
    (use-after-reuse of an aliased input). Freshly compiled executables
    are always correct, as are chains synchronized per step. Donation is
    a memory optimization, never a semantic one, so when BOTH hazard
    ingredients are present — the disk cache enabled AND the CPU backend
    — the donation request is dropped: the cache keeps its multi-minute
    compile savings and the step chain keeps its correctness
    (tests/test_donation_cache.py runs the repro chain under exactly this
    config).

    Env overrides: ``SEIST_DONATE_WITH_CACHE=1`` restores donation (for
    a jaxlib where the aliasing serialization is fixed), ``=0`` gates it
    on every backend (if the hazard is ever seen off-CPU).
    """
    if not donate:
        return donate
    force = os.environ.get("SEIST_DONATE_WITH_CACHE", "")
    if force == "1":
        return donate
    cache_on = bool(jax.config.jax_compilation_cache_dir)
    if cache_on and (force == "0" or jax.default_backend() == "cpu"):
        global _donation_gate_logged
        if not _donation_gate_logged:
            _donation_gate_logged = True
            from seist_tpu.utils.logger import logger

            logger.warning(
                "persistent compile cache active on the CPU backend: "
                "dropping step-state donation (deserialized executables "
                "can corrupt donated outputs — ROADMAP; "
                "SEIST_DONATE_WITH_CACHE=1 overrides)"
            )
        return ()
    return donate


def _first_call_span(jitted: Callable, name: str) -> Callable:
    """Record the jitted function's FIRST invocation as a bus span
    (``jit_first_call_ms{fn=...}``, obs/bus.py) — on a fresh process that
    call IS the compile (minutes for the big models), historically
    invisible outside stderr. Steady-state cost: one truthiness check per
    call."""
    from functools import wraps

    done: list = []

    @wraps(jitted)
    def call(*args, **kwargs):
        if done:
            return jitted(*args, **kwargs)
        from seist_tpu.obs.bus import BUS

        with BUS.span("jit_first_call", fn=name):
            out = jitted(*args, **kwargs)
        done.append(1)
        return out

    return call


def _apply_transforms(spec: TaskSpec, outputs, targets):
    if spec.targets_transform_for_loss is not None:
        targets = spec.targets_transform_for_loss(targets)
    if spec.outputs_transform_for_loss is not None:
        outputs = spec.outputs_transform_for_loss(outputs)
    return outputs, targets


def _forward_loss(spec: TaskSpec, loss_fn: Callable, cdtype, apply_fn) -> Callable:
    """Shared train-mode forward+loss body for the single-step and
    gradient-accumulation paths: cast params/inputs to the compute dtype,
    apply with mutable BN stats, cast outputs back to fp32, apply the task
    transforms. Returns ``compute(params, stats, inputs, targets, key) ->
    (loss, (outputs, new_stats))`` — differentiable in ``params`` (arg 0).
    """

    def compute(params, stats, inputs, targets, key):
        variables = {"params": cast_floating(params, cdtype)}
        has_stats = stats is not None
        if has_stats:
            variables["batch_stats"] = stats
        with precision_policy(cdtype):
            out = apply_fn(
                variables,
                cast_floating(inputs, cdtype),
                train=True,
                mutable=["batch_stats"] if has_stats else [],
                rngs={"dropout": key},
            )
        outputs, mutated = out if has_stats else (out[0], {})
        outputs = cast_to_float32(outputs)
        o, t = _apply_transforms(spec, outputs, targets)
        return loss_fn(o, t), (outputs, mutated.get("batch_stats"))

    return compute


def _guarded_update(state: TrainState, grads, loss, new_stats):
    """Apply the gradient update only when loss AND global grad-norm are
    finite; otherwise return ``state`` unchanged (params, opt_state, BN
    stats, and ``step`` all keep their pre-update values, so a skipped
    step does not advance the LR schedule).

    Multi-host agreement: by the time this runs, ``grads`` have already
    been all-reduced over the mesh's ``data`` axis (XLA emits the
    collective for the batch-sharded backward), so the finite flag is
    computed from values that are bit-identical on every host — the
    gradient all-reduce IS the cross-host agreement, and no worker can
    take the skip branch while another applies the update.

    Returns ``(state, diag)`` with ``diag = {"applied": i32 0/1,
    "grad_norm": f32}``.
    """
    grad_norm = optax.global_norm(grads)
    finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
    updated = state.apply_gradients(grads=grads)
    if new_stats is not None:
        updated = updated.replace(batch_stats=cast_to_float32(new_stats))
    # NaN grads make NaN optimizer moments; jnp.where discards the whole
    # poisoned update in one pass over the state pytree.
    state = jax.tree.map(
        lambda n, o: jnp.where(finite, n, o), updated, state
    )
    return state, {"applied": finite.astype(jnp.int32), "grad_norm": grad_norm}


def make_train_step(
    spec: TaskSpec,
    loss_fn: Callable,
    compute_dtype: Optional[str] = None,
    guard: bool = False,
) -> Callable:
    """Build ``train_step(state, inputs, targets, rng) -> (state, loss, outputs)``.

    ``rng`` is a base key; the global step is folded in so every step gets
    fresh dropout/droppath noise while the traced program stays static.

    ``compute_dtype`` 'bf16' runs the forward/backward in bfloat16 (fp32
    master params, optimizer, BN stats, softmax, loss — see
    train/precision.py); gradients flow through the cast back to the fp32
    params, so the optimizer update is full precision.

    ``guard=True`` adds the bad-update guard (:func:`_guarded_update`):
    the step then returns ``(state, loss, outputs, diag)`` where a
    non-finite loss or gradient norm leaves the state untouched and
    ``diag["applied"] == 0``. The returned ``loss`` is the raw (possibly
    non-finite) value so callers can log what happened.
    """
    cdtype = resolve_dtype(compute_dtype)

    def train_step(state: TrainState, inputs, targets, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        fwd = _forward_loss(spec, loss_fn, cdtype, state.apply_fn)
        (loss, (outputs, new_stats)), grads = jax.value_and_grad(
            fwd, has_aux=True
        )(state.params, state.batch_stats, inputs, targets, step_rng)
        if guard:
            state, diag = _guarded_update(state, grads, loss, new_stats)
            return state, loss, outputs, diag
        state = state.apply_gradients(grads=grads)
        if new_stats is not None:
            state = state.replace(batch_stats=cast_to_float32(new_stats))
        return state, loss, outputs

    return train_step


def make_multi_train_step(
    spec: TaskSpec,
    loss_fn: Callable,
    compute_dtype: Optional[str] = None,
    steps_per_call: int = 1,
    guard: bool = False,
) -> Callable:
    """Build a step that runs ``steps_per_call`` optimizer updates inside ONE
    jitted program via ``lax.scan`` over stacked micro-batches.

    ``multi_step(state, inputs_k, targets_k, rng) -> (state, mean_loss, None)``
    where every leaf of ``inputs_k``/``targets_k`` has a leading
    ``steps_per_call`` axis (k distinct batches — this is k REAL sequential
    training steps, not gradient accumulation).

    Why: each jit dispatch costs a host->device round trip; on a remote-
    tunneled TPU that fixed cost can rival the compute itself (measured
    ~66 ms/step on this sandbox's tunnel vs ~85 ms of compute for
    seist_l_dpk at batch 256). Scanning k steps amortizes it k-fold. The
    per-step RNG folding uses ``state.step`` exactly like the single-step
    path, so dropout/droppath noise matches a loop of k single steps.

    The reference has no analogue (its loop is host-driven per batch,
    ref train.py:75-177). Trade-offs: per-micro-step outputs are not
    returned (train-loop metrics sample the steps that fall on the
    single-step path) and k batches must be resident at once.

    Sharding caveat: the batch axis here is axis 1, not axis 0 — do NOT
    pass this through :func:`jit_step`, whose data sharding targets the
    leading axis (it would shard the k micro-step axis across devices,
    silently computing something other than k sequential global-batch
    updates). Under a mesh, jit it directly with
    ``in_shardings=(replicated, P(None, 'data'), P(None, 'data'),
    replicated)``.
    """
    if steps_per_call <= 1:
        return make_train_step(spec, loss_fn, compute_dtype, guard=guard)
    base = make_train_step(spec, loss_fn, compute_dtype, guard=guard)

    if guard:
        # Each scanned micro-update carries its own finite check; the call
        # reports the per-micro-step applied MASK (ordered — the worker's
        # consecutive-bad tracking needs to know whether skips were
        # trailing), and the mean loss is taken over the finite
        # micro-steps only (all-skipped -> NaN, which the worker logs but
        # never feeds back into params).
        def guarded_multi_step(state: TrainState, inputs_k, targets_k, rng):
            def body(st, batch):
                x, y = batch
                st, loss, _, diag = base(st, x, y, rng)
                return st, (loss, diag["applied"])

            state, (losses, applied) = jax.lax.scan(
                body, state, (inputs_k, targets_k)
            )
            return state, _finite_mean(losses, applied), None, {
                "applied": applied
            }

        return guarded_multi_step

    def multi_step(state: TrainState, inputs_k, targets_k, rng):
        def body(st, batch):
            x, y = batch
            st, loss, _ = base(st, x, y, rng)
            return st, loss

        state, losses = jax.lax.scan(body, state, (inputs_k, targets_k))
        return state, losses.mean(), None

    return multi_step


def _finite_mean(losses, applied):
    """Mean loss over the applied (finite) micro-steps of a scanned call;
    NaN when every step was skipped (callers log it but never feed it
    back into params)."""
    n_ok = applied.sum()
    return jnp.where(
        n_ok > 0,
        jnp.where(applied > 0, losses, 0.0).sum()
        / jnp.maximum(n_ok, 1).astype(losses.dtype),
        jnp.float32(jnp.nan),
    )


def make_device_aug_train_step(
    spec: TaskSpec,
    loss_fn: Callable,
    process_rows: Callable,
    compute_dtype: Optional[str] = None,
    guard: bool = False,
) -> Callable:
    """Build the augment-inside-the-step variant (``--device-aug step``):

    ``step(state, rows, idx, aug, epoch, rng)`` where ``rows`` is a raw
    sample-row pytree (data/pipeline.RawStore batch), ``idx`` the (B,)
    global epoch indices keying the augmentation PRNG, ``aug`` the (B,)
    augment flags. ``process_rows`` (data/device_aug.make_row_processor)
    turns them into (inputs, targets) INSIDE the jitted program — the
    host never runs per-sample numpy augmentation, label synthesis, or
    Python stacking; it only gathers raw rows. Jit with
    :func:`jit_device_aug_step`.

    Returns ``(state, loss, None[, diag])`` — per-step model outputs are
    not exposed (the device path has no host-side metrics targets to
    score them against, and returning them would force a cross-device
    gather under the replicated out_shardings).
    """
    base = make_train_step(spec, loss_fn, compute_dtype, guard=guard)

    def device_aug_step(state: TrainState, rows, idx, aug, epoch, rng):
        inputs, targets = process_rows(rows, idx, aug, epoch)
        ret = base(state, inputs, targets, rng)
        if guard:
            st, loss, _, diag = ret
            return st, loss, None, diag
        st, loss, _ = ret
        return st, loss, None

    return device_aug_step


def jit_device_aug_step(step_fn: Callable, mesh: Optional[Mesh]) -> Callable:
    """Jit a :func:`make_device_aug_train_step` function: rows/idx/aug
    batch-sharded on ``data``; state/epoch/rng replicated. Outputs are
    pinned replicated — without the pin GSPMD is free to hand back
    data-sharded state leaves, which then clash with the replicated
    in_shardings of the next consumer (the eval step)."""
    donate = resolve_donation((0,))
    if mesh is None:
        return _first_call_span(
            jax.jit(step_fn, donate_argnums=donate), "device_aug_step"
        )
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    return _first_call_span(
        jax.jit(
            step_fn,
            in_shardings=(repl, data, data, data, repl, repl),
            out_shardings=repl,
            donate_argnums=donate,
        ),
        "device_aug_step",
    )


def make_cached_train_call(
    spec: TaskSpec,
    loss_fn: Callable,
    process_cache: Callable,
    steps_per_call: int = 1,
    compute_dtype: Optional[str] = None,
    guard: bool = False,
) -> Callable:
    """Build the scan-based epoch executor over an HBM-resident raw cache
    (``--device-aug cached``):

    ``call(state, cache, idx_k, epoch, rng) -> (state, mean_loss, None
    [, diag])`` runs ``steps_per_call`` optimizer updates inside ONE
    jitted program; each scanned step gathers its raw rows from
    ``cache`` by the (k, B) ``idx_k`` slice, augments + synthesizes
    labels on device (``process_cache`` =
    data/device_aug.make_cache_processor), and updates. The only
    per-call host->device traffic is the k*B int32 indices — per-step
    host stacking is zero, which is the whole point.

    Guarded calls return the ordered per-micro-step applied mask exactly
    like :func:`make_multi_train_step`. Jit via :func:`jit_cached_call`.
    """
    base = make_train_step(spec, loss_fn, compute_dtype, guard=guard)

    if guard:
        def guarded_call(state: TrainState, cache, idx_k, epoch, rng):
            def body(st, idx):
                x, y = process_cache(cache, idx, epoch)
                st, loss, _, diag = base(st, x, y, rng)
                return st, (loss, diag["applied"])

            state, (losses, applied) = jax.lax.scan(body, state, idx_k)
            return state, _finite_mean(losses, applied), None, {
                "applied": applied
            }

        return guarded_call

    def call(state: TrainState, cache, idx_k, epoch, rng):
        def body(st, idx):
            x, y = process_cache(cache, idx, epoch)
            st, loss, _ = base(st, x, y, rng)
            return st, loss

        state, losses = jax.lax.scan(body, state, idx_k)
        return state, losses.mean(), None

    return call


def jit_cached_call(call_fn: Callable, mesh: Optional[Mesh], cache) -> Callable:
    """Jit a :func:`make_cached_train_call` function. The cache pytree is
    sharded on its sample axis over ``data`` (matching
    pipeline.DeviceEpochCache's upload placement); the (k, B) index array
    shards its batch axis; state/epoch/rng replicate. ``cache`` is only
    consulted for its pytree structure."""
    donate = resolve_donation((0,))
    if mesh is None:
        return _first_call_span(
            jax.jit(call_fn, donate_argnums=donate), "cached_call"
        )
    import jax.tree_util as jtu

    repl = NamedSharding(mesh, P())
    row_sh = jtu.tree_map(lambda _: NamedSharding(mesh, P("data")), cache)
    idx_sh = NamedSharding(mesh, P(None, "data"))
    return _first_call_span(
        jax.jit(
            call_fn,
            in_shardings=(repl, row_sh, idx_sh, repl, repl),
            # Replicated outputs: GSPMD would otherwise be free to hand
            # back data-sharded state leaves that clash with the eval
            # step's replicated in_shardings (observed live on the 8-dev
            # CPU mesh).
            out_shardings=NamedSharding(mesh, P()),
            donate_argnums=donate,
        ),
        "cached_call",
    )


def make_accum_train_step(
    spec: TaskSpec,
    loss_fn: Callable,
    compute_dtype: Optional[str] = None,
    accum_steps: int = 1,
    guard: bool = False,
) -> Callable:
    """Build ONE optimizer update from ``accum_steps`` micro-batch
    gradients, scanned inside a single jitted program.

    ``accum_step(state, inputs_k, targets_k, rng) -> (state, mean_loss, None)``
    where every leaf of ``inputs_k``/``targets_k`` has a leading
    ``accum_steps`` axis (same stacked layout as
    :func:`make_multi_train_step` — jit under a mesh with
    :func:`jit_multi_step`). The scan carries a running gradient sum, so
    peak memory is ONE micro-batch's activations plus one gradient pytree:
    this is how the reference's batch-500 training config
    (ref main.py:119-149) fits a memory-tight chip without changing the
    effective batch. The reference itself has no gradient accumulation
    (SURVEY.md §2.4: absent).

    Semantics vs one big-batch step:

    * gradients — mean over micro-batches == big-batch gradient for
      mean-reduced losses and equal micro sizes (exact for BN-free
      models; with BatchNorm the batch statistics couple samples, so the
      gradient matches SMALL-batch BN semantics, like torch DDP
      accumulation loops).
    * BatchNorm running stats — chained through the micro-steps, exactly
      as if the micro-batches had been separate forward passes.
    * dropout/droppath — each micro-batch folds its index into the step
      key, so noise differs per micro-batch.
    * ``state.step`` advances by ONE per call (one update), so LR
      schedules see update counts, not micro-step counts.
    """
    if accum_steps <= 1:
        return make_train_step(spec, loss_fn, compute_dtype, guard=guard)
    cdtype = resolve_dtype(compute_dtype)

    def accum_step(state: TrainState, inputs_k, targets_k, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        has_stats = state.batch_stats is not None
        grad_fn = jax.value_and_grad(
            _forward_loss(spec, loss_fn, cdtype, state.apply_fn), has_aux=True
        )

        def body(carry, batch):
            grads_sum, stats, loss_sum, i = carry
            x, y = batch
            key = jax.random.fold_in(step_rng, i)
            (loss, (_, new_stats)), grads = grad_fn(state.params, stats, x, y, key)
            grads_sum = jax.tree.map(jnp.add, grads_sum, grads)
            if has_stats:
                stats = cast_to_float32(new_stats)
            return (grads_sum, stats, loss_sum + loss, i + 1), None

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        carry0 = (zeros, state.batch_stats, jnp.zeros(()), jnp.zeros((), jnp.int32))
        (grads_sum, stats, loss_sum, _), _ = jax.lax.scan(
            body, carry0, (inputs_k, targets_k)
        )
        grads = jax.tree.map(lambda g: g / accum_steps, grads_sum)
        mean_loss = loss_sum / accum_steps
        if guard:
            # One NaN micro-batch poisons the summed gradient (and the
            # chained BN stats), so the finite check on the mean covers
            # every micro-step: skip the whole accumulated update.
            state, diag = _guarded_update(
                state, grads, mean_loss, stats if has_stats else None
            )
            return state, mean_loss, None, diag
        state = state.apply_gradients(grads=grads)
        if has_stats:
            state = state.replace(batch_stats=stats)
        return state, mean_loss, None

    return accum_step


def make_eval_step(
    spec: TaskSpec, loss_fn: Callable, compute_dtype: Optional[str] = None
) -> Callable:
    """Build ``eval_step(state, inputs, targets, mask) -> (loss, outputs)``
    (the reference's no-grad validate body, validate.py:54-127).

    ``mask`` (float, shape (N,)) zeroes padded tail rows: the input pipeline
    pads the final eval batch to keep jit shapes static, so the loss is
    recombined from *per-sample* losses (vmap over batch-of-1 slices) —
    a mask-weighted mean for mean-reduced losses, a masked sum for
    sum-reduced ones (``loss_fn.reduction == 'sum'``, e.g. MousaviLoss).
    """
    sum_reduced = getattr(loss_fn, "reduction", "mean") == "sum"
    cdtype = resolve_dtype(compute_dtype)

    def eval_step(state: TrainState, inputs, targets, mask):
        variables = {"params": cast_floating(state.params, cdtype)}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        with precision_policy(cdtype):
            outputs = state.apply_fn(
                variables, cast_floating(inputs, cdtype), train=False
            )
        outputs = cast_to_float32(outputs)
        o, t = _apply_transforms(spec, outputs, targets)

        def one(o1, t1):
            ob = jax.tree.map(lambda a: a[None], o1)
            tb = jax.tree.map(lambda a: a[None], t1)
            return loss_fn(ob, tb)

        per_sample = jax.vmap(one)(o, t)
        w = mask.astype(per_sample.dtype)
        masked = (per_sample * w).sum()
        loss = masked if sum_reduced else masked / jnp.maximum(w.sum(), 1.0)
        return loss, outputs

    return eval_step


def jit_step(
    step_fn: Callable,
    mesh: Optional[Mesh] = None,
    donate_state: bool = True,
    n_batch_args: int = 2,
    n_extra_args: int = 1,
    span_name: str = "train_step",
) -> Callable:
    """Jit a step function with mesh shardings. Defaults fit the *train* step
    ``(state, inputs, targets, rng)``; for eval steps use :func:`jit_eval_step`.

    State (arg 0) is replicated; the next ``n_batch_args`` args (inputs,
    targets pytrees) are sharded on ``data``; the remaining ``n_extra_args``
    (rng, ...) are replicated. Without a mesh this is a plain jit (single
    device).
    """
    donate = resolve_donation((0,)) if donate_state else ()
    if mesh is None:
        return _first_call_span(
            jax.jit(step_fn, donate_argnums=donate), span_name
        )
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    in_shardings = (repl,) + (data,) * n_batch_args + (repl,) * n_extra_args
    return _first_call_span(
        jax.jit(step_fn, in_shardings=in_shardings, donate_argnums=donate),
        span_name,
    )


def jit_multi_step(
    step_fn: Callable, mesh: Optional[Mesh] = None, donate_state: bool = True
) -> Callable:
    """Jit a :func:`make_multi_train_step` function under a mesh.

    The stacked batches carry the micro-step axis FIRST and the batch axis
    SECOND, so the data sharding is ``P(None, 'data')`` — :func:`jit_step`
    would wrongly shard the micro-step axis (see make_multi_train_step's
    sharding caveat).
    """
    donate = resolve_donation((0,)) if donate_state else ()
    if mesh is None:
        return _first_call_span(
            jax.jit(step_fn, donate_argnums=donate), "multi_step"
        )
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(None, "data"))
    return _first_call_span(
        jax.jit(
            step_fn, in_shardings=(repl, data, data, repl),
            donate_argnums=donate,
        ),
        "multi_step",
    )


def jit_eval_step(step_fn: Callable, mesh: Optional[Mesh] = None) -> Callable:
    """Jit an eval step ``(state, inputs, targets, mask) -> (loss, outputs)``.

    Never donates the state (eval does not return one — donating would
    invalidate the live TrainState); inputs, targets and mask are all
    batch-sharded on ``data``.
    """
    return jit_step(
        step_fn, mesh=mesh, donate_state=False, n_batch_args=3,
        n_extra_args=0, span_name="eval_step",
    )


def fold_rngs(rng: jax.Array, epoch: int) -> jax.Array:
    """Per-epoch base key (the reference reshuffles samplers per epoch,
    train.py:381-382; here the same idea reseeds augmentation/dropout)."""
    return jax.random.fold_in(rng, epoch)
