"""Optimizer construction (optax).

Counterpart of the reference's optimizer block
(/root/reference/training/train.py:302-323): Adam / AdamW / SGD selected by
name, per-step LR driven by the cyclic schedule.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax

Schedule = Union[float, Callable[[int], float]]


def l1_sign_decay(
    alpha: float,
    mask: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """L1 regularization applied in gradient space: ``g + alpha * sign(w)``.

    This is the optax equivalent of the reference EQTransformer's
    backward-hook L1 on its first conv stage
    (/root/reference/models/eqtransformer.py:43-51,388-396) — instead of
    mutating grads in a hook, chain this transform before the optimizer and
    scope it with ``mask`` (a ``params -> bool pytree`` fn selecting e.g. the
    first conv stage's kernels).
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("l1_sign_decay requires params")
        updates = jax.tree_util.tree_map(
            lambda g, p: g + alpha * jnp.sign(p), updates, params
        )
        return updates, state

    tx = optax.GradientTransformation(init_fn, update_fn)
    if mask is not None:
        tx = optax.masked(tx, mask)
    return tx


def build_optimizer(
    name: str,
    learning_rate: Schedule,
    weight_decay: float = 0.0,
    momentum: float = 0.9,
    l1_kernel_alpha: float = 0.0,
    l1_bias_alpha: float = 0.0,
    l1_mask_fn: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """Optimizer chain. ``l1_kernel_alpha``/``l1_bias_alpha`` prepend
    :func:`l1_sign_decay` transforms (the reference EQTransformer's L1
    grad hooks); ``l1_mask_fn(params, kind)`` scopes them — e.g.
    ``models.eqtransformer.l1_param_mask`` selects exactly the conv params
    the reference hooks.
    """
    name = name.lower()
    if name == "adam":
        tx = optax.adam(learning_rate)
        # torch Adam's `weight_decay` is L2-into-gradient, not decoupled.
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif name == "adamw":
        tx = optax.adamw(learning_rate, weight_decay=weight_decay)
    elif name == "sgd":
        tx = optax.sgd(learning_rate, momentum=momentum)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    else:
        raise NotImplementedError(
            f"Unsupported optimizer: '{name}' (adam/adamw/sgd)"
        )

    pre = []
    for alpha, kind in ((l1_kernel_alpha, "kernel"), (l1_bias_alpha, "bias")):
        if alpha:
            mask = (
                (lambda p, _kind=kind: l1_mask_fn(p, _kind))
                if l1_mask_fn is not None
                else None
            )
            pre.append(l1_sign_decay(alpha, mask=mask))
    return optax.chain(*pre, tx) if pre else tx
