"""Optimizer construction (optax).

Counterpart of the reference's optimizer block
(/root/reference/training/train.py:302-323): Adam / AdamW / SGD selected by
name, per-step LR driven by the cyclic schedule.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax

Schedule = Union[float, Callable[[int], float]]


def l1_sign_decay(
    alpha: float,
    mask: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """L1 regularization applied in gradient space: ``g + alpha * sign(w)``.

    This is the optax equivalent of the reference EQTransformer's
    backward-hook L1 on its first conv stage
    (/root/reference/models/eqtransformer.py:43-51,388-396) — instead of
    mutating grads in a hook, chain this transform before the optimizer and
    scope it with ``mask`` (a ``params -> bool pytree`` fn selecting e.g. the
    first conv stage's kernels).
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("l1_sign_decay requires params")
        updates = jax.tree_util.tree_map(
            lambda g, p: g + alpha * jnp.sign(p), updates, params
        )
        return updates, state

    tx = optax.GradientTransformation(init_fn, update_fn)
    if mask is not None:
        tx = optax.masked(tx, mask)
    return tx


def build_optimizer(
    name: str,
    learning_rate: Schedule,
    weight_decay: float = 0.0,
    momentum: float = 0.9,
) -> optax.GradientTransformation:
    name = name.lower()
    if name == "adam":
        tx = optax.adam(learning_rate)
        # torch Adam's `weight_decay` is L2-into-gradient, not decoupled.
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
        return tx
    if name == "adamw":
        return optax.adamw(learning_rate, weight_decay=weight_decay)
    if name == "sgd":
        tx = optax.sgd(learning_rate, momentum=momentum)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
        return tx
    raise NotImplementedError(f"Unsupported optimizer: '{name}' (adam/adamw/sgd)")
