"""Synthetic seismogram dataset — no disk, fully deterministic.

Not in the reference (which has no test data strategy at all, SURVEY.md §4);
this dataset generates plausible 3-channel event waveforms (noise + damped
P/S wavelets) with every label the io-item catalog knows (ppks/spks, emg,
smg, pmp, clr, baz, dis, snr), so any registered model can run end-to-end —
tests, smoke runs, and bench.py all use it. Event ``idx`` is generated from
``default_rng(seed * 1e6 + idx)``: stable across epochs and worker layouts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import pandas as pd

from seist_tpu.data.base import DatasetBase, Event
from seist_tpu.registry import register_dataset


def make_wavelet(
    rng: np.random.Generator, length: int, freq: float, fs: int
) -> np.ndarray:
    """Damped sinusoid: t*exp(-3t) envelope, random-phase carrier. Shared
    by this dataset and tools/fixtures.py (the parity fixture uses the same
    recipe)."""
    t = np.arange(length) / fs
    envelope = t * np.exp(-3.0 * t)
    carrier = np.sin(2 * np.pi * freq * t + rng.uniform(0, 2 * np.pi))
    return (envelope * carrier / (np.abs(envelope).max() + 1e-9)).astype(
        np.float32
    )


class Synthetic(DatasetBase):
    _name = "synthetic"
    _part_range = None
    _channels = ["z", "n", "e"]
    _sampling_rate = 50

    def __init__(
        self,
        *,
        num_events: int = 256,
        trace_samples: int = 12000,
        data_dir: str = "",
        cache: bool = True,
        **kwargs,
    ):
        self._num_events = num_events
        self._trace_samples = trace_samples
        # Wavelet synthesis costs ~2x what the downstream pipeline does
        # (profiled); caching makes repeated epochs measure the *pipeline*
        # (the role a real dataset's disk read plays is much cheaper).
        # Copies are returned because the preprocessor mutates in place.
        self._cache: dict = {} if cache else None
        super().__init__(data_dir=data_dir, **kwargs)

    def _load_meta_data(self) -> pd.DataFrame:
        meta = pd.DataFrame({"idx": np.arange(self._num_events)})
        return self._shuffle_and_split(meta)

    def _make_wavelet(self, rng, length: int, freq: float) -> np.ndarray:
        return make_wavelet(rng, length, freq, self._sampling_rate)

    @staticmethod
    def _copy_event(event: Event) -> Event:
        """Deep-enough copy: the preprocessor mutates data/label fields in
        place, so cached events must never be handed out aliased."""
        return {
            k: (v.copy() if isinstance(v, np.ndarray) else list(v))
            if isinstance(v, (np.ndarray, list))
            else v
            for k, v in event.items()
        }

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        if self._cache is not None and idx in self._cache:
            event, meta = self._cache[idx]
            return self._copy_event(event), dict(meta)
        row = self._meta_data.iloc[idx]
        rng = np.random.default_rng(int(self._seed) * 1_000_000 + int(row["idx"]))
        length = self._trace_samples
        n_ch = len(self._channels)

        data = rng.normal(0, 1.0, size=(n_ch, length)).astype(np.float32)
        ppk = int(rng.integers(length // 10, length // 2))
        spk = int(ppk + rng.integers(length // 20, length // 4))
        amp = rng.uniform(5.0, 20.0)
        wl = min(length - spk, length // 4)
        for c in range(n_ch):
            p_w = self._make_wavelet(rng, wl, freq=rng.uniform(4, 8))
            s_w = self._make_wavelet(rng, wl, freq=rng.uniform(1.5, 4))
            data[c, ppk : ppk + wl] += amp * p_w
            data[c, spk : spk + wl] += 1.6 * amp * s_w

        emg = float(np.clip(rng.normal(3.5, 1.0), 0, 8))
        event: Event = {
            "data": data,
            "ppks": [ppk],
            "spks": [spk],
            "emg": [emg],
            "smg": [float(np.clip(emg + rng.normal(0, 0.2), 0, 8))],
            "pmp": [int(rng.integers(0, 2))],
            "clr": [int(rng.integers(0, 2))],
            "baz": [float(rng.uniform(0, 360))],
            "dis": [float(rng.uniform(0, 330))],
            "snr": np.full(n_ch, 20.0, dtype=np.float32),
        }
        meta = {"idx": int(row["idx"])}
        if self._cache is not None:
            self._cache[idx] = (self._copy_event(event), dict(meta))
        return event, meta


@register_dataset
def synthetic(**kwargs):
    return Synthetic(**kwargs)
