"""Input pipeline: dataset + preprocessor -> sharded device batches.

TPU-native redesign of the reference's torch ``Dataset``/``DataLoader``/
``DistributedSampler`` stack (training/preprocess.py:824-953,
train.py:221-247):

* :class:`SeismicDataset` — composes an L2 dataset reader with the
  ``DataPreprocessor``; same io contract as the reference adapter
  (inputs, loss_targets, metrics_targets, meta json) including the
  2x-epoch augmentation rule — raw copy for ``idx < size``, augmented for
  ``idx >= size`` (ref preprocess.py:918-937). Every sample's RNG is
  ``default_rng((seed, epoch, idx))`` — reproducible regardless of worker
  scheduling (the reference relies on global numpy state per worker).
* :class:`Loader` — per-epoch seeded shuffle, per-host contiguous sharding
  (the ``DistributedSampler`` equivalent: each host reads only its slice),
  thread-pool batch assembly (h5py/numpy release the GIL for the heavy
  parts), fixed batch shapes (``drop_last`` on train; tail batch padded and
  masked on eval so jit never retraces).
* :func:`prefetch_to_device` — double-buffered ``jax.device_put`` with a
  ``NamedSharding`` so host->HBM copy of batch N+1 overlaps the step on N
  (replaces torch ``pin_memory`` + H2D copies at train.py:77-84).
"""

from __future__ import annotations

import collections
import json
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from seist_tpu import taskspec
from seist_tpu.data import io_guard
from seist_tpu.data.preprocess import DataPreprocessor, pad_phases
from seist_tpu.registry import DATASETS
from seist_tpu.utils import faults as faults_lib
from seist_tpu.utils.logger import logger

Batch = collections.namedtuple(
    "Batch", ["inputs", "loss_targets", "metrics_targets", "meta", "mask"]
)


class SeismicDataset:
    """Dataset reader + preprocessing -> one training example
    (ref preprocess.py:824-953)."""

    def __init__(
        self,
        dataset_name: str,
        mode: str,
        *,
        seed: int,
        data_dir: str = "",
        input_names: Sequence = (),
        label_names: Sequence = (),
        task_names: Sequence[str] = (),
        in_samples: int = 8192,
        augmentation: bool = False,
        shuffle: bool = True,
        data_split: bool = True,
        train_size: float = 0.8,
        val_size: float = 0.1,
        max_event_num: int = 1,
        max_quarantine_frac: float = 0.05,
        dataset_kwargs: Optional[dict] = None,
        **preprocessor_kwargs,
    ) -> None:
        self._seed = int(seed)
        self._mode = mode.lower()
        self._input_names = list(input_names)
        self._label_names = list(label_names)
        self._task_names = list(task_names)
        self._max_event_num = max_event_num
        self._epoch = 0

        # val/test never augment (ref preprocess.py:858-860).
        self._augmentation = bool(augmentation) and self._mode == "train"
        if self._augmentation != bool(augmentation):
            logger.warning(f"[{self._mode}] Augmentation -> {self._augmentation}")

        self._dataset = DATASETS.create(
            dataset_name,
            seed=self._seed,
            mode=self._mode,
            data_dir=data_dir,
            shuffle=shuffle,
            data_split=data_split,
            train_size=train_size,
            val_size=val_size,
            **(dataset_kwargs or {}),
        )
        logger.info(repr(self._dataset))
        self._dataset_size = len(self._dataset)
        # Data-plane self-healing (data/io_guard.py): per-dataset
        # quarantine registry + the env-driven chaos injector, both
        # captured at construction so tests can set SEIST_FAULT_IO_* /
        # --max-quarantine-frac deterministically.
        self._quarantine = io_guard.Quarantine(
            self._dataset_size, max_frac=float(max_quarantine_frac)
        )
        self._io_faults = faults_lib.IoFaultInjector.from_env()
        # Immutable after construction: lets the clean read path skip the
        # injector entirely (guard fast path in _fetch_event).
        self._io_faults_enabled = self._io_faults.enabled
        if self._augmentation:
            logger.warning(
                f"Data augmentation: Dataset size -> {self._dataset_size * 2}"
            )

        label_width_sec = preprocessor_kwargs.pop("label_width", 0.5)
        self._preprocessor = DataPreprocessor(
            data_channels=self._dataset.channels(),
            sampling_rate=self._dataset.sampling_rate(),
            in_samples=in_samples,
            max_event_num=max_event_num,
            soft_label_width=int(label_width_sec * self._dataset.sampling_rate()),
            **preprocessor_kwargs,
        )

    @property
    def preprocessor(self) -> DataPreprocessor:
        return self._preprocessor

    @property
    def augmentation(self) -> bool:
        return self._augmentation

    @property
    def raw_size(self) -> int:
        """Number of RAW events (len() doubles under augmentation)."""
        return self._dataset_size

    @property
    def input_names(self) -> list:
        return list(self._input_names)

    @property
    def label_names(self) -> list:
        return list(self._label_names)

    @property
    def quarantine(self) -> io_guard.Quarantine:
        return self._quarantine

    @property
    def io_faults(self) -> faults_lib.IoFaultInjector:
        return self._io_faults

    def quarantine_report(self) -> Dict[str, Any]:
        """Epoch-end quarantine report (logged by train/worker.py)."""
        return self._quarantine.report()

    def raw_event(self, idx: int):
        """One UNprocessed event + meta — the device-aug upload path reads
        raw traces here and runs augmentation/labels on device."""
        return self._dataset[idx % self._dataset_size]

    def source_ids(self) -> Optional[np.ndarray]:
        """Per-LOGICAL-index source ids when the underlying dataset is a
        multi-source (mixture) pack, else None. Under the 2x augmentation
        rule the array is doubled — logical index ``n + i`` is sample
        ``i``'s augmented replica, same source."""
        fn = getattr(self._dataset, "source_ids", None)
        sids = fn() if callable(fn) else None
        if sids is None:
            return None
        sids = np.asarray(sids)
        return np.concatenate([sids, sids]) if self._augmentation else sids

    def _fetch_event(self, raw_idx: int, *, idx: int) -> Tuple[Event, dict]:
        """Guarded sample read (data/io_guard.py): transient faults are
        retried (with injected flakiness riding the same loop); a sample
        that is permanently corrupt — failed ingest validation or an
        exhausted retry budget — is quarantined and deterministically
        replaced by the first cleanly-reading candidate of the
        ``(seed, epoch, idx)``-keyed fallback sequence, so batch shapes
        and the global sample order stay fixed and resume-stable.

        Fast path (no quarantined samples, no injected faults): one
        direct read + ingest validation — a try frame, a counter bump and
        one ``np.isfinite`` pass per sample (benched ~1% of loader stage
        time; the BENCH ``data_plane`` section re-measures it every run).
        Any failure falls through to the full retry/quarantine ladder."""
        if not (self._quarantine.active or self._io_faults_enabled):
            try:
                event, meta = self._dataset[raw_idx]
                io_guard.validate_event(event)
                io_guard.COUNTERS.inc("reads")
                return event, meta
            except (OSError, io_guard.CorruptSampleError):
                pass  # enter the retrying/quarantining ladder below
        return self._fetch_event_slow(raw_idx, idx=idx)

    def _fetch_event_slow(
        self, raw_idx: int, *, idx: int
    ) -> Tuple[Event, dict]:
        for cand in self._quarantine.candidates(
            raw_idx, seed=self._seed, epoch=self._epoch, idx=idx
        ):
            try:
                event, meta = io_guard.guarded_event_read(
                    lambda c=cand: self._dataset[c],
                    key=cand,
                    desc=f"{self._dataset.name()}[{cand}]",
                    injector=self._io_faults,
                )
            except io_guard.CorruptSampleError as e:
                # Covers RetriesExhaustedError too; add() raises
                # QuarantineOverflowError past --max-quarantine-frac.
                self._quarantine.add(cand, repr(e))
                continue
            if cand != raw_idx:
                io_guard.COUNTERS.inc("fallback_reads")
            return event, meta
        raise io_guard.CorruptSampleError(
            f"no clean fallback found for sample {raw_idx} "
            f"(quarantined: {len(self._quarantine)}/{self._dataset_size})"
        )

    def sampling_rate(self) -> int:
        return self._dataset.sampling_rate()

    def data_channels(self) -> list:
        return self._dataset.channels()

    def name(self) -> str:
        return f"{self._dataset.name()}_{self._mode}"

    def set_epoch(self, epoch: int) -> None:
        """Advance the per-sample RNG stream (the reference reshuffles via
        ``DistributedSampler.set_epoch``, train.py:381-382)."""
        self._epoch = int(epoch)

    def __len__(self) -> int:
        # Augmentation doubles the epoch (ref preprocess.py:918-922).
        return 2 * self._dataset_size if self._augmentation else self._dataset_size

    def __getitem__(self, idx: int) -> Tuple[Any, Any, Dict[str, np.ndarray], str]:
        raw_idx = idx % self._dataset_size
        if io_guard.enabled():
            event, meta_data = self._fetch_event(raw_idx, idx=int(idx))
        else:
            event, meta_data = self._dataset[raw_idx]
        rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, self._epoch, int(idx)])
        )
        event = self._preprocessor.process(
            event=event,
            augmentation=(self._augmentation and idx >= self._dataset_size),
            rng=rng,
        )
        inputs = self._preprocessor.get_inputs(event, self._input_names)
        loss_targets = self._preprocessor.get_targets_for_loss(
            event, self._label_names
        )
        metrics_targets = self._preprocessor.get_targets_for_metrics(
            event, max_event_num=self._max_event_num, task_names=self._task_names
        )
        meta_json = json.dumps({k: str(v) for k, v in dict(meta_data).items()})
        return inputs, loss_targets, metrics_targets, meta_json


def from_task_spec(
    spec: taskspec.TaskSpec,
    dataset_name: str,
    mode: str,
    **kwargs,
) -> SeismicDataset:
    """Build a :class:`SeismicDataset` wired to a model's task spec
    (inputs/labels/eval lists; ref train.py:199-217)."""
    return SeismicDataset(
        dataset_name,
        mode,
        input_names=[
            list(g) if isinstance(g, (tuple, list)) else g for g in spec.inputs
        ],
        label_names=[
            list(g) if isinstance(g, (tuple, list)) else g for g in spec.labels
        ],
        task_names=list(spec.eval),
        **kwargs,
    )


def _shard_order(
    order: np.ndarray, num_shards: int, shard_index: int
) -> np.ndarray:
    """Host-shard a global epoch order: head-wrapped to equalize shard
    sizes (torch ``DistributedSampler``'s pad rule; unequal step counts
    would deadlock the collective-bearing jitted steps), then interleaved
    ``rank::world`` — the union over hosts covers the full order and the
    per-position shards are disjoint (test-pinned)."""
    if num_shards <= 1:
        return order
    n = len(order)
    target = -(-n // num_shards) * num_shards
    if target > n:
        order = np.concatenate([order, order[: target - n]])
    return order[shard_index::num_shards]


def epoch_indices(
    n: int,
    *,
    seed: int,
    epoch: int,
    shuffle: bool,
    num_shards: int = 1,
    shard_index: int = 0,
) -> np.ndarray:
    """This host's epoch-``epoch`` sample order — THE shuffle contract
    shared by the host :class:`Loader` and the device-aug executors, so
    both paths consume the identical global sample sequence: seeded
    permutation (a pure function of (seed, epoch) — mid-epoch resume
    depends on this), host-sharded by :func:`_shard_order`. Together with
    a batch offset this is the full resume address: ``(seed, epoch,
    shard_index, start_batch)`` determines the remaining batch sequence
    exactly, with no replay and no skips."""
    if shuffle:
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        order = rng.permutation(n)
    else:
        order = np.arange(n)
    return _shard_order(order, num_shards, shard_index)


# Keys the mixture-draw PRNG stream apart from the shuffle/fallback ones.
_MIXTURE_SALT = 0x313C7


def mixture_epoch_indices(
    source_ids: np.ndarray,
    *,
    seed: int,
    epoch: int,
    temperature: float,
    num_shards: int = 1,
    shard_index: int = 0,
) -> np.ndarray:
    """Temperature-weighted mixture epoch order over multi-source packed
    data (seqio-style mixing, arXiv:2203.17189), under the SAME resume
    contract as :func:`epoch_indices`: a pure function of
    ``(seed, epoch)``, epoch length fixed at ``len(source_ids)`` (so
    steps_per_epoch and ``(epoch, start_batch)`` addressing are
    unchanged), host-sharded by :func:`_shard_order`.

    Each epoch slot draws its source with probability
    ``p_s ∝ (n_s / n)^(1/T)`` (T=1: proportional — every sample appears
    ~once; T→∞: uniform over sources) and consumes the next sample of
    that source's stream: a seeded permutation of the source's members,
    re-permuted on every wrap — small sources are resampled evenly,
    large ones subsampled without replacement."""
    source_ids = np.asarray(source_ids)
    n = int(source_ids.shape[0])
    if temperature <= 0:
        raise ValueError(f"mixture temperature must be > 0, got {temperature}")
    counts = np.bincount(source_ids)
    if counts.size < 2:
        raise ValueError("mixture sampling needs >= 2 sources")
    p = (counts / n) ** (1.0 / float(temperature))
    p = np.where(counts > 0, p, 0.0)
    p = p / p.sum()
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), int(epoch), _MIXTURE_SALT])
    )
    choice = rng.choice(counts.size, size=n, p=p)
    order = np.empty(n, np.int64)
    for s in range(counts.size):
        slots = np.flatnonzero(choice == s)
        if slots.size == 0:
            continue
        members = np.flatnonzero(source_ids == s)
        wraps = -(-slots.size // members.size)
        stream = np.concatenate(
            [
                np.random.default_rng(
                    np.random.SeedSequence(
                        [int(seed), int(epoch), _MIXTURE_SALT, s, w]
                    )
                ).permutation(members)
                for w in range(wraps)
            ]
        )
        order[slots] = stream[: slots.size]
    return _shard_order(order, num_shards, shard_index)


def _epoch_order(
    n: int,
    *,
    seed: int,
    epoch: int,
    shuffle: bool,
    num_shards: int = 1,
    shard_index: int = 0,
    source_ids: Optional[np.ndarray] = None,
    mixture_temperature: float = 0.0,
) -> np.ndarray:
    """The ONE epoch-order dispatcher every consumer goes through (host
    Loader, raw-row step feed, cached device executor): plain seeded
    permutation, or the temperature-weighted mixture order when a
    multi-source pack + temperature are configured. Both are pure
    functions of (seed, epoch) — the O(1) mid-epoch resume contract."""
    if mixture_temperature and source_ids is not None:
        if len(source_ids) != n:
            raise ValueError(
                f"source_ids has {len(source_ids)} entries for {n} samples"
            )
        return mixture_epoch_indices(
            source_ids,
            seed=seed,
            epoch=epoch,
            temperature=mixture_temperature,
            num_shards=num_shards,
            shard_index=shard_index,
        )
    return epoch_indices(
        n,
        seed=seed,
        epoch=epoch,
        shuffle=shuffle,
        num_shards=num_shards,
        shard_index=shard_index,
    )


def _stack(samples: List[Any]) -> Any:
    """Stack a list of per-sample structures (arrays / tuples of arrays)."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([s[i] for s in samples]) for i in range(len(first))
        )
    return np.stack(samples)


class Loader:
    """Host-side batch loader with per-host sharding and fixed shapes.

    Each epoch: seeded global permutation -> this host's interleaved slice ->
    fixed-size batches assembled by a worker pool. Train drops the global
    tail (every host sees the same number of steps — the collective-sync
    equivalent of ``drop_last``); eval pads the final batch and sets
    ``Batch.mask`` zeros on padding rows.

    Workers: ``num_workers`` threads by default (the hot per-sample ops —
    h5py reads, numpy array math, native wavekit kernels — release the
    GIL, so threads scale on multi-core hosts). ``worker_processes > 0``
    switches to a process pool instead, sidestepping the GIL entirely for
    Python-bound augmentation mixes at the cost of per-sample IPC; batches
    are bit-identical either way (per-sample RNG is derived from
    (seed, epoch, idx), never worker identity).
    """

    def __init__(
        self,
        dataset: SeismicDataset,
        batch_size: int,
        *,
        shuffle: bool = False,
        drop_last: bool = False,
        num_workers: int = 8,
        worker_processes: int = 0,
        seed: int = 0,
        num_shards: int = 1,
        shard_index: int = 0,
        mixture_temperature: float = 0.0,
    ) -> None:
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = max(1, num_workers)
        self.worker_processes = max(0, worker_processes)
        self.seed = seed
        self.num_shards = num_shards
        self.shard_index = shard_index
        # Temperature-weighted mixture sampling (multi-source packs only;
        # see mixture_epoch_indices). Resolved once: the per-sample source
        # ids are static for the dataset's lifetime.
        self.mixture_temperature = float(mixture_temperature or 0.0)
        self._source_ids = None
        if self.mixture_temperature > 0:
            fn = getattr(dataset, "source_ids", None)
            self._source_ids = fn() if callable(fn) else None
            if self._source_ids is None:
                raise ValueError(
                    "mixture_temperature set but the dataset exposes no "
                    "mixture sources (pack with tools/pack_dataset.py "
                    "--mixture)"
                )
        self.epoch = 0
        self._start_batch = 0
        self._pool: Optional[ThreadPoolExecutor] = None
        self._proc_pool = None
        if self.worker_processes and io_guard.enabled():
            # Each process-pool worker holds its own pickled dataset copy:
            # quarantine state and io_guard counters accumulate PER WORKER
            # (replacement content stays deterministic — the fallback rule
            # depends only on the data), so the parent's epoch report and
            # counter logs understate faults and --max-quarantine-frac is
            # enforced per worker rather than globally. Thread workers
            # (the default) share one registry and report exactly.
            logger.warning(
                "worker_processes > 0: data-plane quarantine/counters are "
                "tracked per worker process; parent-side epoch reports "
                "undercount and the --max-quarantine-frac abort applies "
                "per worker (docs/FAULT_TOLERANCE.md)"
            )
        # One injector per pipeline: reuse the dataset's (so a
        # programmatic fault plan reaches the stall hook too); fall back
        # to env parsing only for bare-dataset callers.
        self._io_faults = (
            getattr(dataset, "io_faults", None)
            or faults_lib.IoFaultInjector.from_env()
        )

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.dataset.set_epoch(epoch)

    def set_start_batch(self, start_batch: int) -> None:
        """Begin the NEXT ``__iter__`` at batch ``start_batch`` instead of
        0 (one-shot; subsequent epochs start at 0 again). This is the
        mid-epoch resume hook: the shuffle order is a pure function of
        (seed, epoch), so a restored (epoch, batch_offset) position
        continues the exact same sample sequence an uninterrupted run
        would have seen — no replayed and no skipped data."""
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self._start_batch = int(start_batch)

    def close(self) -> None:
        """Release the worker pool(s). Safe to call multiple times; the
        loader remains usable (a new pool spins up on the next __iter__)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        if self._proc_pool is not None:
            self._proc_pool.shutdown(wait=False, cancel_futures=True)
            self._proc_pool = None

    def __del__(self):  # best-effort: Loaders built in loops must not leak
        try:
            self.close()
        # __del__ runs during interpreter teardown when pool/module state
        # may already be gone; raising here would only print an unraisable
        # warning, so swallow everything.
        except Exception:
            pass

    def _indices(self) -> np.ndarray:
        return _epoch_order(
            len(self.dataset),
            seed=self.seed,
            epoch=self.epoch,
            shuffle=self.shuffle,
            num_shards=self.num_shards,
            shard_index=self.shard_index,
            source_ids=self._source_ids,
            mixture_temperature=self.mixture_temperature,
        )

    def __len__(self) -> int:
        n = len(self._indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _fetch(self, chunk: np.ndarray) -> List[Any]:
        """Fetch one batch's samples via the configured worker pool.

        Sample-level faults never reach here (the guarded read in
        SeismicDataset retries transients and quarantines corruption);
        anything a worker still raises is a loader-thread death — a bug
        or an environment failure the retry ladder cannot absorb — and is
        wrapped as LoaderDeathError so the train worker can checkpoint
        and preempt-exit instead of crashing opaquely. The deliberate
        aborts (QuarantineOverflowError, CorruptSampleError's
        no-clean-fallback) pass through untouched: those must kill the
        run loudly, not trigger a relaunch loop.
        """
        try:
            return self._fetch_inner(chunk)
        except (io_guard.QuarantineOverflowError, io_guard.CorruptSampleError):
            raise
        # Not swallowed — re-raised as the typed loader-death signal the
        # train worker turns into a checkpoint + clean-preempt exit.
        except Exception as e:
            io_guard.COUNTERS.inc("loader_deaths")
            raise io_guard.LoaderDeathError(
                f"loader worker died fetching batch chunk "
                f"[{int(chunk[0])}..{int(chunk[-1])}]: {e!r}"
            ) from e

    def _fetch_inner(self, chunk: np.ndarray) -> List[Any]:
        if self.worker_processes:
            if self._proc_pool is None:
                import multiprocessing
                from concurrent.futures import ProcessPoolExecutor

                # forkserver/spawn, never fork: the pool is created lazily
                # from the prefetch producer THREAD of a JAX-initialized
                # parent — forking there can inherit locks held mid-acquire
                # by other threads (h5py/logging/libtpu) and hang the
                # children. The dataset is pickled ONCE per worker via the
                # initializer — never per sample.
                try:
                    ctx = multiprocessing.get_context("forkserver")
                except ValueError:  # platform without forkserver
                    ctx = multiprocessing.get_context("spawn")
                self._proc_pool = ProcessPoolExecutor(
                    max_workers=self.worker_processes,
                    mp_context=ctx,
                    initializer=_proc_worker_init,
                    initargs=(self.dataset,),
                )
            epoch = self.epoch
            return list(
                self._proc_pool.map(
                    _proc_worker_getitem,
                    [(epoch, int(i)) for i in chunk],
                    # Batch the IPC: one message per worker-chunk, not per
                    # sample (ordering is preserved by map).
                    chunksize=max(1, len(chunk) // self.worker_processes),
                )
            )
        # One persistent pool for the loader's lifetime (threads are reused
        # across epochs instead of re-spawned each __iter__).
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.num_workers,
                thread_name_prefix="seist-loader",
            )
        # Chunked tasks, not per-sample: at batch 500 the per-future
        # lock/notify traffic alone cost ~25% of loader wall time
        # (profiled). A few tasks per worker keeps load balance without
        # hundreds of futures per batch.
        n_tasks = min(len(chunk), self.num_workers * 4)
        slices = np.array_split(np.asarray(chunk), n_tasks)
        getitem = self.dataset.__getitem__

        def run_slice(ids):
            return [getitem(int(i)) for i in ids]

        out: List[Any] = []
        for part in self._pool.map(run_slice, slices):
            out.extend(part)
        return out

    def __iter__(self) -> Iterator[Batch]:
        # Bus counters resolved once per epoch, not per batch (obs/bus.py;
        # the scrape side reads them via --metrics-port / snapshot()).
        from seist_tpu.obs.bus import BUS

        c_batches = BUS.counter("loader_batches")
        c_samples = BUS.counter("loader_samples")
        indices = self._indices()
        nb = len(self)
        start, self._start_batch = self._start_batch, 0  # one-shot
        for b in range(start, nb):
            # Chaos hook: SEIST_FAULT_IO_STALL_BATCH wedges the loader
            # here — the stall-watchdog e2e's stand-in for a deadlocked
            # worker pool or a hung filesystem.
            self._io_faults.maybe_stall(b)
            chunk = indices[b * self.batch_size : (b + 1) * self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1], pad)])
            samples = self._fetch(chunk)
            inputs = _stack([s[0] for s in samples])
            loss_targets = _stack([s[1] for s in samples])
            metrics_targets = {
                k: np.stack([s[2][k] for s in samples])
                for k in samples[0][2]
            }
            meta = [s[3] for s in samples]
            mask = np.ones(self.batch_size, dtype=np.float32)
            if pad:
                mask[-pad:] = 0.0
            c_batches.inc()
            c_samples.inc(len(samples) - pad)
            yield Batch(inputs, loss_targets, metrics_targets, meta, mask)




_PROC_DATASET: Optional[SeismicDataset] = None


def _proc_worker_init(dataset: SeismicDataset) -> None:
    global _PROC_DATASET
    _PROC_DATASET = dataset


def _proc_worker_getitem(epoch_idx):
    """Process-pool sample fetch. Epoch rides along with every index: the
    parent's ``set_epoch`` does not propagate to live workers, and the
    per-sample RNG is seeded from (seed, epoch, idx)."""
    epoch, idx = epoch_idx
    _PROC_DATASET.set_epoch(epoch)
    return _PROC_DATASET[idx]


def _double_buffer(iterator, transform, prefetch: int, account: str = ""):
    """Producer-thread double buffering: apply ``transform`` (typically a
    sharded device_put) to each item ahead of the consumer, propagating
    producer exceptions. Shared by the prefetch_* variants.

    ``account`` names a bus-counter prefix for backpressure accounting on
    the bounded queue: ``<account>_backpressure_s`` accumulates the
    seconds the producer spent blocked on a full queue (the consumer —
    i.e. the device step — was the bottleneck), ``<account>_queue_full``
    counts the blocking puts. Zero backpressure = the pipeline is
    input-bound; saturated backpressure = the chip is."""
    buf: "queue.Queue" = queue.Queue(maxsize=prefetch)
    sentinel = object()
    err: List[BaseException] = []
    if account:
        from seist_tpu.obs.bus import BUS, monotonic

        c_wait = BUS.counter(f"{account}_backpressure_s")
        c_full = BUS.counter(f"{account}_queue_full")

    def _put(item) -> None:
        if not account or not buf.full():
            buf.put(item)
            return
        c_full.inc()
        t0 = monotonic()
        buf.put(item)
        c_wait.inc(monotonic() - t0)

    def producer():
        try:
            for item in iterator:
                _put(transform(item))
        except BaseException as e:  # propagate loader errors to the consumer
            err.append(e)
        finally:
            buf.put(sentinel)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    while True:
        item = buf.get()
        if item is sentinel:
            if err:
                raise err[0]
            return
        yield item


def prefetch_to_device(
    iterator: Iterator[Batch],
    mesh=None,
    prefetch: int = 2,
) -> Iterator[Batch]:
    """Double-buffered host->device transfer of Batch arrays.

    Arrays are ``device_put`` with the batch axis sharded over the mesh's
    ``data`` axis (XLA overlaps the copy with the running step); ``meta``
    stays on host. With ``mesh=None`` batches pass through untouched.
    """
    if mesh is None:
        yield from iterator
        return

    import jax

    from seist_tpu.parallel.mesh import shard_batch

    def put(batch: Batch) -> Batch:
        def _put(x):
            # shard_batch holds the single placement rule (device_put vs
            # make_array_from_process_local_data on multi-host).
            return shard_batch(mesh, x) if isinstance(x, np.ndarray) else x

        return Batch(
            jax.tree.map(_put, batch.inputs),
            jax.tree.map(_put, batch.loss_targets),
            {k: _put(v) for k, v in batch.metrics_targets.items()},
            batch.meta,
            _put(batch.mask),
        )

    yield from _double_buffer(iterator, put, prefetch)


def _guarded_raw_event(sds: SeismicDataset, i: int) -> dict:
    """RawStore ingest read: transient faults retried like the host path;
    a permanently-corrupt sample raises ValueError — the device store
    holds EVERY sample resident for the whole run, so it refuses rather
    than bake a fallback in; the worker catches the ValueError and falls
    back to the host path, whose per-read quarantine handles it."""
    if not io_guard.enabled():
        return sds.raw_event(i)[0]
    try:
        event, _ = io_guard.guarded_event_read(
            lambda: sds.raw_event(i),
            key=i,
            desc=f"{sds.name()}.raw[{i}]",
            injector=sds.io_faults,
        )
        return event
    except io_guard.CorruptSampleError as e:
        raise ValueError(
            f"sample {i} is permanently corrupt ({e}); --device-aug "
            "falls back to the host path, which quarantines it"
        ) from e


class RawStore:
    """Host-side fixed-shape raw arrays for the device-aug paths
    (``--device-aug step|cached``): every raw trace decoded ONCE, the
    draw-free preprocessing (``_is_noise`` classification + ``pad_phases``)
    precomputed per sample, VALUE/ONEHOT label fields extracted to dense
    arrays. The per-step host work collapses to (at most) a fancy-index
    row gather — all augmentation, windowing, normalization and label
    synthesis happen on device (seist_tpu/data/device_aug.py).

    Requires a uniform raw trace length (every real dataset here decodes
    fixed-length traces); :meth:`build` raises ``ValueError`` otherwise
    and the worker falls back to the host path.
    """

    def __init__(
        self,
        arrays: Dict[str, Any],
        *,
        n_raw: int,
        augmentation: bool,
        raw_len: int,
        phase_slots: int,
    ) -> None:
        self.arrays = arrays
        self.n_raw = int(n_raw)
        self.augmentation = bool(augmentation)
        self.raw_len = int(raw_len)
        self.phase_slots = int(phase_slots)

    def __len__(self) -> int:
        # 2x-epoch rule: raw copy for idx < n_raw, augmented for >= n_raw
        # (matches SeismicDataset.__len__).
        return 2 * self.n_raw if self.augmentation else self.n_raw

    @property
    def nbytes(self) -> int:
        import jax

        return int(
            sum(np.asarray(a).nbytes for a in jax.tree.leaves(self.arrays))
        )

    @classmethod
    def estimate_bytes(cls, sds: SeismicDataset) -> int:
        """Resident-cache size estimate WITHOUT decoding the dataset:
        one sample's raw waveform bytes x dataset size (phase/value
        sidecars are noise next to the waveforms). The probe read goes
        through the guarded path — a transient fault at setup time must
        not crash device-aug selection when the same fault one call
        later (inside build) would be retried."""
        event = _guarded_raw_event(sds, 0)
        return int(
            np.asarray(event["data"]).astype(np.float32, copy=False).nbytes
            * sds.raw_size
        )

    @classmethod
    def build(cls, sds: SeismicDataset) -> "RawStore":
        pre = sds.preprocessor
        names = taskspec.flatten_io_names(
            sds.input_names + sds.label_names
        )
        value_names = sorted(
            {n for n in names if taskspec.get_kind(n) == taskspec.VALUE}
        )
        onehot_names = sorted(
            {n for n in names if taskspec.get_kind(n) == taskspec.ONEHOT}
        )

        from seist_tpu.data import device_aug as da

        # ONE decode pass per sample (the expensive part); the big
        # waveform arrays are written straight into the final stacked
        # buffer and per-sample events are dropped as they are consumed,
        # so peak host RAM stays ~1x the dataset. The cheap
        # _is_noise/pad_phases list math runs twice (once to size
        # phase_slots, once inside host_prepare — the ONE implementation
        # of the row contract the device kernels rely on).
        n = sds.raw_size
        events: List[Optional[dict]] = []
        raw_len = None
        max_phases = 1
        for i in range(n):
            event = _guarded_raw_event(sds, i)
            length = int(np.asarray(event["data"]).shape[-1])
            if raw_len is None:
                raw_len = length
            elif length != raw_len:
                raise ValueError(
                    f"device-aug needs uniform raw trace lengths; sample "
                    f"{i} has {length} != {raw_len}"
                )
            ppks, spks = list(event["ppks"]), list(event["spks"])
            if not pre._is_noise(event["data"], ppks, spks, event["snr"]):
                p, s = pad_phases(
                    ppks, spks, pre.min_event_gap, pre.in_samples
                )
                max_phases = max(max_phases, len(p), len(s))
            events.append(event)
        phase_slots = max(max_phases, pre._max_event_num)
        n_ch = len(pre.data_channels)

        arrays: Dict[str, Any] = {
            "data": np.empty((n, n_ch, int(raw_len or 0)), np.float32),
            "ppks": np.empty((n, phase_slots), np.int32),
            "np_p": np.empty((n,), np.int32),
            "spks": np.empty((n, phase_slots), np.int32),
            "np_s": np.empty((n,), np.int32),
        }
        vals = {name: np.zeros((n, 1), np.float32) for name in value_names}
        oh = {name: np.zeros((n,), np.int32) for name in onehot_names}
        for i in range(n):
            event = events[i]
            events[i] = None  # free as consumed
            row = da.host_prepare(pre, event, phase_slots)
            arrays["data"][i] = row["data"]
            arrays["ppks"][i] = row["ppks"]
            arrays["np_p"][i] = row["np_p"]
            arrays["spks"][i] = row["spks"]
            arrays["np_s"][i] = row["np_s"]
            if row["is_noise"] and (value_names or onehot_names):
                # The host path ERRORS on a noise-classified trace with
                # VALUE/ONEHOT labels (_clear_event_except empties the
                # field and get_io_item raises / stacking fails);
                # zero-filling here would silently train on fabricated
                # labels. Refuse — the worker falls back to the host
                # path, which surfaces the dataset problem loudly.
                raise ValueError(
                    f"sample {i} is noise-classified but the task has "
                    f"VALUE/ONEHOT labels "
                    f"({value_names + onehot_names}); the device path "
                    "will not fabricate label values for it"
                )
            for name in value_names:
                v = np.asarray(event.get(name, []), np.float32)
                if v.size == 0:  # host path would crash at stacking
                    raise ValueError(
                        f"sample {i} has no '{name}' value; refusing to "
                        "fabricate a device-path label"
                    )
                vals[name][i] = v.reshape(-1)[:1]
            for name in onehot_names:
                v = event.get(name, [])
                if not len(v):  # host get_io_item raises here too
                    raise ValueError(
                        f"sample {i} has no '{name}' class; refusing to "
                        "fabricate a device-path label"
                    )
                oh[name][i] = int(v[0])
        if value_names:
            arrays["values"] = vals
        if onehot_names:
            arrays["onehots"] = oh
        return cls(
            arrays,
            n_raw=n,
            augmentation=sds.augmentation,
            raw_len=int(raw_len or 0),
            phase_slots=phase_slots,
        )

    def row_batch(self, raw_idx: np.ndarray) -> Dict[str, Any]:
        """Fancy-index a batch of raw rows (numpy; the step-mode per-step
        host work)."""
        import jax

        return jax.tree.map(lambda a: a[raw_idx], self.arrays)


class DeviceEpochCache:
    """HBM-resident raw epochs (``--device-aug cached``): the RawStore
    arrays uploaded ONCE, sample axis sharded over the mesh's ``data``
    axis (sample count padded to divisibility; pad rows are never
    indexed). Each train step then only receives a (k, B) int32 index
    array — there is no per-step sample traffic across the host boundary
    at all."""

    def __init__(self, store: RawStore, mesh=None) -> None:
        import jax

        self.store = store
        arrays = store.arrays
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from seist_tpu.parallel.mesh import AXIS_DATA

            shards = mesh.shape[AXIS_DATA]
            n = store.n_raw
            pad = (-n) % shards
            if pad:
                arrays = jax.tree.map(
                    lambda a: np.concatenate(
                        [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
                    ),
                    arrays,
                )
            sharding = NamedSharding(mesh, P(AXIS_DATA))
            if jax.process_count() > 1:
                # Multi-host: every host holds the full raw arrays (the
                # upload reads the whole dataset), but device_put cannot
                # place onto non-addressable devices — hand XLA only the
                # slices this host's devices own. Combined with the
                # host-sharded epoch_index_chunks below this is the
                # deterministic global shard contract that used to force
                # the cached->step fallback on multi-host.
                self.arrays = jax.tree.map(
                    lambda a: jax.make_array_from_callback(
                        a.shape, sharding, lambda idx, a=a: a[idx]
                    ),
                    arrays,
                )
            else:
                self.arrays = jax.tree.map(
                    lambda a: jax.device_put(a, sharding), arrays
                )
        else:
            self.arrays = jax.tree.map(jax.device_put, arrays)
        self.nbytes = int(
            sum(a.nbytes for a in jax.tree.leaves(self.arrays))
        )

    def epoch_index_chunks(
        self,
        epoch: int,
        *,
        seed: int,
        shuffle: bool,
        batch_size: int,
        steps_per_call: int,
        start_batch: int = 0,
        num_shards: int = 1,
        shard_index: int = 0,
        source_ids: Optional[np.ndarray] = None,
        mixture_temperature: float = 0.0,
    ):
        """Yield (k, B) int32 index arrays for one epoch — the same
        global sample sequence the host Loader would produce
        (:func:`_epoch_order`), chunked for the scan-based executor. On
        multi-host runs each host yields ITS interleaved shard of the
        global order (``batch_size`` local rows per step;
        ``shard_stacked_batch`` assembles the global batch), so the
        union over hosts covers exactly what a single host would train
        on. Trailing part-groups are dropped (drop-last + static jit
        shapes, as on the packed host path)."""
        order = _epoch_order(
            len(self.store),
            seed=seed,
            epoch=epoch,
            shuffle=shuffle,
            num_shards=num_shards,
            shard_index=shard_index,
            source_ids=source_ids,
            mixture_temperature=mixture_temperature,
        )
        nb = len(order) // batch_size
        calls = nb // steps_per_call
        for c in range(start_batch // steps_per_call, calls):
            flat = order[
                c * steps_per_call * batch_size
                : (c + 1) * steps_per_call * batch_size
            ]
            yield np.asarray(
                flat.reshape(steps_per_call, batch_size), np.int32
            )


def iter_raw_batches(
    store: RawStore,
    epoch: int,
    *,
    seed: int,
    shuffle: bool,
    batch_size: int,
    num_shards: int = 1,
    shard_index: int = 0,
    start_batch: int = 0,
    source_ids: Optional[np.ndarray] = None,
    mixture_temperature: float = 0.0,
):
    """Step-mode (``--device-aug step``) feed: per batch, gather the raw
    rows on host (a numpy fancy index — no per-sample augmentation, no
    label synthesis, no Python stacking) and yield
    ``(rows, idx, aug)`` for the augment-inside-the-step train step.
    Sample order matches the host Loader exactly (:func:`_epoch_order`,
    drop-last). A store exposing ``row_batch_at`` (the packed
    direct-ingest store) gets the (epoch, logical idx) context its
    guarded reads key quarantine fallbacks on."""
    order = _epoch_order(
        len(store),
        seed=seed,
        epoch=epoch,
        shuffle=shuffle,
        num_shards=num_shards,
        shard_index=shard_index,
        source_ids=source_ids,
        mixture_temperature=mixture_temperature,
    )
    nb = len(order) // batch_size
    n_raw = store.n_raw
    row_batch_at = getattr(store, "row_batch_at", None)
    for b in range(start_batch, nb):
        sel = np.asarray(order[b * batch_size : (b + 1) * batch_size], np.int64)
        raw = sel % n_raw if store.augmentation else sel
        aug = (
            (sel >= n_raw)
            if store.augmentation
            else np.zeros(sel.shape, bool)
        )
        if row_batch_at is not None:
            rows = row_batch_at(raw, epoch=epoch, idx=sel)
        else:
            rows = store.row_batch(raw)
        yield rows, sel.astype(np.int32), aug


def prefetch_raw_to_device(iterator, mesh, prefetch: int = 2):
    """Double-buffered device feed for :func:`iter_raw_batches` items:
    rows/idx/aug all batch-sharded on ``data`` (same placement rule as
    the host path's batches). The bounded queue's backpressure is
    accounted on the bus (``data_ingest_backpressure_s`` /
    ``data_ingest_queue_full`` — docs/OBSERVABILITY.md)."""
    if mesh is None:
        yield from iterator
        return

    from seist_tpu.parallel.mesh import shard_batch

    yield from _double_buffer(
        iterator,
        lambda item: shard_batch(mesh, item),
        prefetch,
        account="data_ingest",
    )


def prefetch_packed_to_device(
    iterator: Iterator[Batch],
    mesh,
    steps_per_call: int,
    prefetch: int = 2,
) -> Iterator[Tuple[Any, Any]]:
    """Group ``steps_per_call`` train batches into one stacked
    ``(inputs_k, targets_k)`` pair — leading axis = micro-step, second =
    batch — double-buffered to device with the batch axis sharded on
    ``data`` (``shard_stacked_batch``). Feeds ``make_multi_train_step``.

    A trailing group smaller than ``steps_per_call`` is DROPPED (same
    spirit as the train loader's drop-last; jit shapes must stay static).
    Only inputs/loss_targets survive packing: the multi-step path returns
    no per-micro-step outputs, so metrics targets/meta have no consumer.
    """
    import jax

    from seist_tpu.parallel.mesh import shard_stacked_batch

    def packed():
        group: List[Batch] = []
        for b in iterator:
            group.append(b)
            if len(group) == steps_per_call:
                inputs = jax.tree.map(
                    lambda *xs: np.stack(xs), *[g.inputs for g in group]
                )
                targets = jax.tree.map(
                    lambda *xs: np.stack(xs), *[g.loss_targets for g in group]
                )
                yield inputs, targets
                group = []

    if mesh is None:
        yield from packed()
        return

    yield from _double_buffer(
        packed(), lambda item: shard_stacked_batch(mesh, item), prefetch
    )
