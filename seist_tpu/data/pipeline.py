"""Input pipeline: dataset + preprocessor -> sharded device batches.

TPU-native redesign of the reference's torch ``Dataset``/``DataLoader``/
``DistributedSampler`` stack (training/preprocess.py:824-953,
train.py:221-247):

* :class:`SeismicDataset` — composes an L2 dataset reader with the
  ``DataPreprocessor``; same io contract as the reference adapter
  (inputs, loss_targets, metrics_targets, meta json) including the
  2x-epoch augmentation rule — raw copy for ``idx < size``, augmented for
  ``idx >= size`` (ref preprocess.py:918-937). Every sample's RNG is
  ``default_rng((seed, epoch, idx))`` — reproducible regardless of worker
  scheduling (the reference relies on global numpy state per worker).
* :class:`Loader` — per-epoch seeded shuffle, per-host contiguous sharding
  (the ``DistributedSampler`` equivalent: each host reads only its slice),
  thread-pool batch assembly (h5py/numpy release the GIL for the heavy
  parts), fixed batch shapes (``drop_last`` on train; tail batch padded and
  masked on eval so jit never retraces).
* :func:`prefetch_to_device` — double-buffered ``jax.device_put`` with a
  ``NamedSharding`` so host->HBM copy of batch N+1 overlaps the step on N
  (replaces torch ``pin_memory`` + H2D copies at train.py:77-84).
"""

from __future__ import annotations

import collections
import json
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from seist_tpu import taskspec
from seist_tpu.data.preprocess import DataPreprocessor
from seist_tpu.registry import DATASETS
from seist_tpu.utils.logger import logger

Batch = collections.namedtuple(
    "Batch", ["inputs", "loss_targets", "metrics_targets", "meta", "mask"]
)


class SeismicDataset:
    """Dataset reader + preprocessing -> one training example
    (ref preprocess.py:824-953)."""

    def __init__(
        self,
        dataset_name: str,
        mode: str,
        *,
        seed: int,
        data_dir: str = "",
        input_names: Sequence = (),
        label_names: Sequence = (),
        task_names: Sequence[str] = (),
        in_samples: int = 8192,
        augmentation: bool = False,
        shuffle: bool = True,
        data_split: bool = True,
        train_size: float = 0.8,
        val_size: float = 0.1,
        max_event_num: int = 1,
        dataset_kwargs: Optional[dict] = None,
        **preprocessor_kwargs,
    ) -> None:
        self._seed = int(seed)
        self._mode = mode.lower()
        self._input_names = list(input_names)
        self._label_names = list(label_names)
        self._task_names = list(task_names)
        self._max_event_num = max_event_num
        self._epoch = 0

        # val/test never augment (ref preprocess.py:858-860).
        self._augmentation = bool(augmentation) and self._mode == "train"
        if self._augmentation != bool(augmentation):
            logger.warning(f"[{self._mode}] Augmentation -> {self._augmentation}")

        self._dataset = DATASETS.create(
            dataset_name,
            seed=self._seed,
            mode=self._mode,
            data_dir=data_dir,
            shuffle=shuffle,
            data_split=data_split,
            train_size=train_size,
            val_size=val_size,
            **(dataset_kwargs or {}),
        )
        logger.info(repr(self._dataset))
        self._dataset_size = len(self._dataset)
        if self._augmentation:
            logger.warning(
                f"Data augmentation: Dataset size -> {self._dataset_size * 2}"
            )

        label_width_sec = preprocessor_kwargs.pop("label_width", 0.5)
        self._preprocessor = DataPreprocessor(
            data_channels=self._dataset.channels(),
            sampling_rate=self._dataset.sampling_rate(),
            in_samples=in_samples,
            max_event_num=max_event_num,
            soft_label_width=int(label_width_sec * self._dataset.sampling_rate()),
            **preprocessor_kwargs,
        )

    @property
    def preprocessor(self) -> DataPreprocessor:
        return self._preprocessor

    def sampling_rate(self) -> int:
        return self._dataset.sampling_rate()

    def data_channels(self) -> list:
        return self._dataset.channels()

    def name(self) -> str:
        return f"{self._dataset.name()}_{self._mode}"

    def set_epoch(self, epoch: int) -> None:
        """Advance the per-sample RNG stream (the reference reshuffles via
        ``DistributedSampler.set_epoch``, train.py:381-382)."""
        self._epoch = int(epoch)

    def __len__(self) -> int:
        # Augmentation doubles the epoch (ref preprocess.py:918-922).
        return 2 * self._dataset_size if self._augmentation else self._dataset_size

    def __getitem__(self, idx: int) -> Tuple[Any, Any, Dict[str, np.ndarray], str]:
        event, meta_data = self._dataset[idx % self._dataset_size]
        rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, self._epoch, int(idx)])
        )
        event = self._preprocessor.process(
            event=event,
            augmentation=(self._augmentation and idx >= self._dataset_size),
            rng=rng,
        )
        inputs = self._preprocessor.get_inputs(event, self._input_names)
        loss_targets = self._preprocessor.get_targets_for_loss(
            event, self._label_names
        )
        metrics_targets = self._preprocessor.get_targets_for_metrics(
            event, max_event_num=self._max_event_num, task_names=self._task_names
        )
        meta_json = json.dumps({k: str(v) for k, v in dict(meta_data).items()})
        return inputs, loss_targets, metrics_targets, meta_json


def from_task_spec(
    spec: taskspec.TaskSpec,
    dataset_name: str,
    mode: str,
    **kwargs,
) -> SeismicDataset:
    """Build a :class:`SeismicDataset` wired to a model's task spec
    (inputs/labels/eval lists; ref train.py:199-217)."""
    return SeismicDataset(
        dataset_name,
        mode,
        input_names=[
            list(g) if isinstance(g, (tuple, list)) else g for g in spec.inputs
        ],
        label_names=[
            list(g) if isinstance(g, (tuple, list)) else g for g in spec.labels
        ],
        task_names=list(spec.eval),
        **kwargs,
    )


def _stack(samples: List[Any]) -> Any:
    """Stack a list of per-sample structures (arrays / tuples of arrays)."""
    first = samples[0]
    if isinstance(first, tuple):
        return tuple(
            np.stack([s[i] for s in samples]) for i in range(len(first))
        )
    return np.stack(samples)


class Loader:
    """Host-side batch loader with per-host sharding and fixed shapes.

    Each epoch: seeded global permutation -> this host's interleaved slice ->
    fixed-size batches assembled by a worker pool. Train drops the global
    tail (every host sees the same number of steps — the collective-sync
    equivalent of ``drop_last``); eval pads the final batch and sets
    ``Batch.mask`` zeros on padding rows.

    Workers: ``num_workers`` threads by default (the hot per-sample ops —
    h5py reads, numpy array math, native wavekit kernels — release the
    GIL, so threads scale on multi-core hosts). ``worker_processes > 0``
    switches to a process pool instead, sidestepping the GIL entirely for
    Python-bound augmentation mixes at the cost of per-sample IPC; batches
    are bit-identical either way (per-sample RNG is derived from
    (seed, epoch, idx), never worker identity).
    """

    def __init__(
        self,
        dataset: SeismicDataset,
        batch_size: int,
        *,
        shuffle: bool = False,
        drop_last: bool = False,
        num_workers: int = 8,
        worker_processes: int = 0,
        seed: int = 0,
        num_shards: int = 1,
        shard_index: int = 0,
    ) -> None:
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = max(1, num_workers)
        self.worker_processes = max(0, worker_processes)
        self.seed = seed
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.epoch = 0
        self._start_batch = 0
        self._pool: Optional[ThreadPoolExecutor] = None
        self._proc_pool = None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.dataset.set_epoch(epoch)

    def set_start_batch(self, start_batch: int) -> None:
        """Begin the NEXT ``__iter__`` at batch ``start_batch`` instead of
        0 (one-shot; subsequent epochs start at 0 again). This is the
        mid-epoch resume hook: the shuffle order is a pure function of
        (seed, epoch), so a restored (epoch, batch_offset) position
        continues the exact same sample sequence an uninterrupted run
        would have seen — no replayed and no skipped data."""
        if start_batch < 0:
            raise ValueError(f"start_batch must be >= 0, got {start_batch}")
        self._start_batch = int(start_batch)

    def close(self) -> None:
        """Release the worker pool(s). Safe to call multiple times; the
        loader remains usable (a new pool spins up on the next __iter__)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        if self._proc_pool is not None:
            self._proc_pool.shutdown(wait=False, cancel_futures=True)
            self._proc_pool = None

    def __del__(self):  # best-effort: Loaders built in loops must not leak
        try:
            self.close()
        except Exception:
            pass

    def _indices(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, self.epoch])
            )
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        if self.num_shards > 1:
            # Equalize shard sizes by wrapping the head (exactly torch
            # DistributedSampler's pad-to-even rule): every host must see
            # the SAME number of batches or the collective-bearing jitted
            # steps deadlock mid-epoch.
            target = -(-n // self.num_shards) * self.num_shards
            if target > n:
                order = np.concatenate([order, order[: target - n]])
        # Interleaved host shard (DistributedSampler-style: rank::world).
        return order[self.shard_index :: self.num_shards]

    def __len__(self) -> int:
        n = len(self._indices())
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _fetch(self, chunk: np.ndarray) -> List[Any]:
        """Fetch one batch's samples via the configured worker pool."""
        if self.worker_processes:
            if self._proc_pool is None:
                import multiprocessing
                from concurrent.futures import ProcessPoolExecutor

                # forkserver/spawn, never fork: the pool is created lazily
                # from the prefetch producer THREAD of a JAX-initialized
                # parent — forking there can inherit locks held mid-acquire
                # by other threads (h5py/logging/libtpu) and hang the
                # children. The dataset is pickled ONCE per worker via the
                # initializer — never per sample.
                try:
                    ctx = multiprocessing.get_context("forkserver")
                except ValueError:  # platform without forkserver
                    ctx = multiprocessing.get_context("spawn")
                self._proc_pool = ProcessPoolExecutor(
                    max_workers=self.worker_processes,
                    mp_context=ctx,
                    initializer=_proc_worker_init,
                    initargs=(self.dataset,),
                )
            epoch = self.epoch
            return list(
                self._proc_pool.map(
                    _proc_worker_getitem,
                    [(epoch, int(i)) for i in chunk],
                    # Batch the IPC: one message per worker-chunk, not per
                    # sample (ordering is preserved by map).
                    chunksize=max(1, len(chunk) // self.worker_processes),
                )
            )
        # One persistent pool for the loader's lifetime (threads are reused
        # across epochs instead of re-spawned each __iter__).
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.num_workers,
                thread_name_prefix="seist-loader",
            )
        # Chunked tasks, not per-sample: at batch 500 the per-future
        # lock/notify traffic alone cost ~25% of loader wall time
        # (profiled). A few tasks per worker keeps load balance without
        # hundreds of futures per batch.
        n_tasks = min(len(chunk), self.num_workers * 4)
        slices = np.array_split(np.asarray(chunk), n_tasks)
        getitem = self.dataset.__getitem__

        def run_slice(ids):
            return [getitem(int(i)) for i in ids]

        out: List[Any] = []
        for part in self._pool.map(run_slice, slices):
            out.extend(part)
        return out

    def __iter__(self) -> Iterator[Batch]:
        indices = self._indices()
        nb = len(self)
        start, self._start_batch = self._start_batch, 0  # one-shot
        for b in range(start, nb):
            chunk = indices[b * self.batch_size : (b + 1) * self.batch_size]
            pad = self.batch_size - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1], pad)])
            samples = self._fetch(chunk)
            inputs = _stack([s[0] for s in samples])
            loss_targets = _stack([s[1] for s in samples])
            metrics_targets = {
                k: np.stack([s[2][k] for s in samples])
                for k in samples[0][2]
            }
            meta = [s[3] for s in samples]
            mask = np.ones(self.batch_size, dtype=np.float32)
            if pad:
                mask[-pad:] = 0.0
            yield Batch(inputs, loss_targets, metrics_targets, meta, mask)




_PROC_DATASET: Optional[SeismicDataset] = None


def _proc_worker_init(dataset: SeismicDataset) -> None:
    global _PROC_DATASET
    _PROC_DATASET = dataset


def _proc_worker_getitem(epoch_idx):
    """Process-pool sample fetch. Epoch rides along with every index: the
    parent's ``set_epoch`` does not propagate to live workers, and the
    per-sample RNG is seeded from (seed, epoch, idx)."""
    epoch, idx = epoch_idx
    _PROC_DATASET.set_epoch(epoch)
    return _PROC_DATASET[idx]


def _double_buffer(iterator, transform, prefetch: int):
    """Producer-thread double buffering: apply ``transform`` (typically a
    sharded device_put) to each item ahead of the consumer, propagating
    producer exceptions. Shared by the prefetch_* variants."""
    buf: "queue.Queue" = queue.Queue(maxsize=prefetch)
    sentinel = object()
    err: List[BaseException] = []

    def producer():
        try:
            for item in iterator:
                buf.put(transform(item))
        except BaseException as e:  # propagate loader errors to the consumer
            err.append(e)
        finally:
            buf.put(sentinel)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    while True:
        item = buf.get()
        if item is sentinel:
            if err:
                raise err[0]
            return
        yield item


def prefetch_to_device(
    iterator: Iterator[Batch],
    mesh=None,
    prefetch: int = 2,
) -> Iterator[Batch]:
    """Double-buffered host->device transfer of Batch arrays.

    Arrays are ``device_put`` with the batch axis sharded over the mesh's
    ``data`` axis (XLA overlaps the copy with the running step); ``meta``
    stays on host. With ``mesh=None`` batches pass through untouched.
    """
    if mesh is None:
        yield from iterator
        return

    import jax

    from seist_tpu.parallel.mesh import shard_batch

    def put(batch: Batch) -> Batch:
        def _put(x):
            # shard_batch holds the single placement rule (device_put vs
            # make_array_from_process_local_data on multi-host).
            return shard_batch(mesh, x) if isinstance(x, np.ndarray) else x

        return Batch(
            jax.tree.map(_put, batch.inputs),
            jax.tree.map(_put, batch.loss_targets),
            {k: _put(v) for k, v in batch.metrics_targets.items()},
            batch.meta,
            _put(batch.mask),
        )

    yield from _double_buffer(iterator, put, prefetch)


def prefetch_packed_to_device(
    iterator: Iterator[Batch],
    mesh,
    steps_per_call: int,
    prefetch: int = 2,
) -> Iterator[Tuple[Any, Any]]:
    """Group ``steps_per_call`` train batches into one stacked
    ``(inputs_k, targets_k)`` pair — leading axis = micro-step, second =
    batch — double-buffered to device with the batch axis sharded on
    ``data`` (``shard_stacked_batch``). Feeds ``make_multi_train_step``.

    A trailing group smaller than ``steps_per_call`` is DROPPED (same
    spirit as the train loader's drop-last; jit shapes must stay static).
    Only inputs/loss_targets survive packing: the multi-step path returns
    no per-micro-step outputs, so metrics targets/meta have no consumer.
    """
    import jax

    from seist_tpu.parallel.mesh import shard_stacked_batch

    def packed():
        group: List[Batch] = []
        for b in iterator:
            group.append(b)
            if len(group) == steps_per_call:
                inputs = jax.tree.map(
                    lambda *xs: np.stack(xs), *[g.inputs for g in group]
                )
                targets = jax.tree.map(
                    lambda *xs: np.stack(xs), *[g.loss_targets for g in group]
                )
                yield inputs, targets
                group = []

    if mesh is None:
        yield from packed()
        return

    yield from _double_buffer(
        packed(), lambda item: shard_stacked_batch(mesh, item), prefetch
    )
