"""Packed-shard dataset: offline HDF5 -> contiguous binary shards.

SURVEY.md §7's input-pipeline mitigation (the ArrayRecord-style offline
repack), built for the measured bottleneck: the r3 loader stage budget
put ~30% of per-sample cost in the read stage — h5py's per-sample
group/dataset lookup and decode — before any augmentation runs
(ref datasets/diting.py:139-142 does one ``grp.get(key)`` per sample;
our reader mirrors it in data/diting.py:103-146).

The repack trades that per-sample API cost for ONE seek-free slice:

* ``shard_XXXXX.bin`` — raw float32 C-order ``(C, L)`` waveforms,
  concatenated. Served through a per-process ``np.memmap`` (page-cache
  backed, zero-copy until the training-path ``.astype`` copy).
* ``index.npz`` — columnar metadata: per-sample shard id, byte offset,
  shape, and every Event label field (NaN = absent), loaded once into
  the pandas frame that :class:`~seist_tpu.data.base.DatasetBase`'s
  seeded shuffle-then-contiguous-split already operates on.
* ``meta.json`` — source dataset name, channels, sampling rate, count.

``pack_dataset`` converts ANY registered dataset (constructed with
``data_split=False, shuffle=False`` so the pack order is the source
metadata order); :class:`PackedDataset` (registered as ``packed``) then
serves the identical Event dicts through the standard reader contract —
same seed => same split as any other dataset.

Label encoding: every current dataset emits 0-or-1-element lists for
ppks/spks/emg/smg/pmp/clr/baz/dis (one event per window — ref
datasets/*.py); the packer asserts that and stores scalar-or-NaN.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np
import pandas as pd

from seist_tpu.data.base import DatasetBase, Event
from seist_tpu.data.io_guard import COUNTERS, CorruptSampleError
from seist_tpu.registry import register_dataset
from seist_tpu.utils.logger import logger

_INDEX = "index.npz"
_META = "meta.json"

# Event fields packed as scalar-or-NaN columns, in a fixed order.
# ppks/spks are sample indices (int at heart, float for the NaN), the
# rest are the label scalars the TaskSpec io catalog consumes.
_SCALAR_FIELDS = ("ppks", "spks", "emg", "smg", "pmp", "clr", "baz", "dis")
_INT_FIELDS = frozenset({"ppks", "spks", "pmp", "clr"})


def pack_dataset(
    src,
    out_dir: str,
    *,
    shard_mb: float = 512,
    log_every: int = 20_000,
) -> str:
    """Repack ``src`` (any DatasetBase, pre-split disabled) into packed
    shards under ``out_dir``. Returns ``out_dir``."""
    os.makedirs(out_dir, exist_ok=True)
    shard_bytes_max = int(shard_mb * 1_000_000)
    n = len(src)
    cols: Dict[str, list] = {
        **{f: [] for f in _SCALAR_FIELDS},
        "snr_0": [],
        "snr_1": [],
        "snr_2": [],
        "shard": [],
        "offset": [],
        "n_ch": [],
        "n_samp": [],
        "key": [],
    }
    shard_id = 0
    shard_off = 0
    shard_f = open(os.path.join(out_dir, f"shard_{shard_id:05d}.bin"), "wb")
    try:
        for i in range(n):
            event, row = src[i]
            data = np.ascontiguousarray(event["data"], dtype=np.float32)
            if data.ndim != 2:
                raise ValueError(f"event {i}: data must be (C, L), got {data.shape}")
            if shard_off + data.nbytes > shard_bytes_max and shard_off:
                shard_f.close()
                shard_id += 1
                shard_off = 0
                shard_f = open(
                    os.path.join(out_dir, f"shard_{shard_id:05d}.bin"), "wb"
                )
            shard_f.write(data.tobytes())
            for f in _SCALAR_FIELDS:
                v = event.get(f, [])
                if len(v) > 1:
                    raise ValueError(
                        f"event {i}: field {f} has {len(v)} values; the "
                        "packed format stores one event per window"
                    )
                cols[f].append(float(v[0]) if len(v) else np.nan)
            snr = np.asarray(event.get("snr", []), dtype=np.float64).ravel()
            for c in range(3):
                cols[f"snr_{c}"].append(
                    float(snr[c]) if c < snr.size else np.nan
                )
            cols["shard"].append(shard_id)
            cols["offset"].append(shard_off)
            cols["n_ch"].append(data.shape[0])
            cols["n_samp"].append(data.shape[1])
            cols["key"].append(str(row.get("key", i)) if isinstance(row, dict) else str(i))
            shard_off += data.nbytes
            if log_every and (i + 1) % log_every == 0:
                logger.info(f"packed {i + 1}/{n} events ({shard_id + 1} shards)")
    finally:
        shard_f.close()

    np.savez(
        os.path.join(out_dir, _INDEX),
        **{
            k: np.asarray(
                v,
                dtype=(
                    np.int64
                    if k in ("shard", "offset", "n_ch", "n_samp")
                    else (str if k == "key" else np.float64)
                ),
            )
            for k, v in cols.items()
        },
    )
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump(
            {
                "source": src.name(),
                "channels": src.channels(),
                "sampling_rate": src.sampling_rate(),
                "n_events": n,
                "n_shards": shard_id + 1,
                "format_version": 1,
            },
            f,
        )
    logger.info(f"packed {n} events into {shard_id + 1} shard(s) at {out_dir}")
    return out_dir


class PackedDataset(DatasetBase):
    """Reader for :func:`pack_dataset` output (registered as ``packed``).

    Same metadata/split/Event contract as every other dataset; the
    waveform read is a single memmap slice + one ``.astype`` copy
    instead of h5py's per-sample group walk."""

    _name = "packed"

    def __init__(self, **kwargs):
        data_dir = kwargs.get("data_dir", "")
        with open(os.path.join(data_dir, _META)) as f:
            self._meta = json.load(f)
        self._mmaps: Dict[int, np.memmap] = {}
        super().__init__(**kwargs)

    # Instance-level overrides of the classmethod accessors: the values
    # come from meta.json, not the class.
    def name(self):  # type: ignore[override]
        return self._name

    def __repr__(self) -> str:
        return (
            f"Dataset(name:packed, source:{self._meta['source']}, "
            f"channels:{self._meta['channels']}, "
            f"sampling_rate:{self._meta['sampling_rate']}, "
            f"n_events:{self._meta['n_events']}, "
            f"n_shards:{self._meta['n_shards']}, "
            f"data_dir:{self._data_dir}, mode:{self._mode})"
        )

    def channels(self):  # type: ignore[override]
        return list(self._meta["channels"])

    def sampling_rate(self):  # type: ignore[override]
        return int(self._meta["sampling_rate"])

    def _load_meta_data(self) -> pd.DataFrame:
        with np.load(
            os.path.join(self._data_dir, _INDEX), allow_pickle=False
        ) as z:
            frame = pd.DataFrame({k: z[k] for k in z.files})
        if len(frame) != self._meta["n_events"]:
            raise ValueError(
                f"index has {len(frame)} rows, meta.json says "
                f"{self._meta['n_events']}"
            )
        return self._shuffle_and_split(frame)

    def _mmap(self, shard: int) -> np.memmap:
        mm = self._mmaps.get(shard)
        if mm is None:
            mm = self._mmaps[shard] = np.memmap(
                os.path.join(self._data_dir, f"shard_{shard:05d}.bin"),
                dtype=np.uint8,
                mode="r",
            )
        return mm

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        row = self._row_dict(idx)
        c, length = int(row["n_ch"]), int(row["n_samp"])
        off = int(row["offset"])
        shard = int(row["shard"])
        nbytes = c * length * 4
        # OSError on the mmap (shard vanished / page-in failure on a
        # network mount) is transient: drop the cached map so the retry
        # re-mmaps a fresh fd. A slice that comes back short means the
        # shard file is truncated — permanent corruption of this sample.
        try:
            raw = self._mmap(shard)[off : off + nbytes]
        except OSError:
            if self._mmaps.pop(shard, None) is not None:
                COUNTERS.inc("reopens")  # same telemetry as evict_h5
            raise
        if raw.size != nbytes:
            raise CorruptSampleError(
                f"packed: short read in shard {shard} (sample {idx}: want "
                f"{nbytes} bytes at {off}, got {raw.size} — truncated shard?)"
            )
        data = np.frombuffer(raw, dtype=np.float32).reshape(c, length).copy()

        def scalar(field):
            v = row[field]
            if v != v:  # NaN
                return []
            return [int(v)] if field in _INT_FIELDS else [np.float32(v)]

        event: Event = {"data": data}
        for f in _SCALAR_FIELDS:
            event[f] = scalar(f)
        event["snr"] = np.array(
            [row["snr_0"], row["snr_1"], row["snr_2"]]
        )
        return event, row


@register_dataset
def packed(**kwargs):
    return PackedDataset(**kwargs)
