"""Packed-shard dataset: offline HDF5 -> contiguous binary shards.

SURVEY.md §7's input-pipeline mitigation (the ArrayRecord-style offline
repack), built for the measured bottleneck: the r3 loader stage budget
put ~30% of per-sample cost in the read stage — h5py's per-sample
group/dataset lookup and decode — before any augmentation runs
(ref datasets/diting.py:139-142 does one ``grp.get(key)`` per sample;
our reader mirrors it in data/diting.py:103-146).

The repack trades that per-sample API cost for ONE seek-free slice:

* ``shard_XXXXX.bin`` — raw float32 C-order ``(C, L)`` waveforms,
  concatenated. Served through a per-process ``np.memmap`` (page-cache
  backed, zero-copy until the training-path ``.astype`` copy).
* ``shard_XXXXX.idx.npz`` — the shard's columnar sidecar (per-sample
  within-shard byte offset, shape, every Event label field, source id).
  Written atomically AFTER the ``.bin`` — its presence is the
  shard-complete marker the resumable packer keys on.
* ``index.npz`` — the merged columnar metadata (sidecars + a ``shard``
  column), loaded once into the pandas frame that
  :class:`~seist_tpu.data.base.DatasetBase`'s seeded
  shuffle-then-contiguous-split already operates on.
* ``meta.json`` — source dataset name(s), channels, sampling rate,
  counts. Written LAST: a directory without it is an incomplete pack
  and the reader refuses it.

Packing is **plan-first**: the shard partition is a pure function of the
source sizes and ``samples_per_shard`` (derived deterministically from
sample 0 when only ``--shard-mb`` is given), computed before any bytes
move. That buys three properties at once:

* **parallel** — workers own disjoint shard ranges; an N-worker pack is
  bit-identical to a 1-worker pack (pinned by tests/test_packed.py);
* **resumable** — an interrupted pack re-plans identically and skips
  every shard whose sidecar already matches its ``.bin``;
* **mixture** — several registered datasets pack into ONE directory
  (sources occupy consecutive shard ranges; every index row carries a
  ``source_id`` provenance column) for temperature-weighted joint
  training (``pipeline.mixture_epoch_indices``, arXiv:2203.17189).

``pack_dataset`` converts ANY registered dataset (constructed with
``data_split=False, shuffle=False`` so the pack order is the source
metadata order); :class:`PackedDataset` (registered as ``packed``) then
serves the identical Event dicts through the standard reader contract —
same seed => same split as any other dataset.

Label encoding: every current dataset emits 0-or-1-element lists for
ppks/spks/emg/smg/pmp/clr/baz/dis (one event per window — ref
datasets/*.py); the packer asserts that and stores scalar-or-NaN.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from seist_tpu.data.base import DatasetBase, Event
from seist_tpu.data.io_guard import COUNTERS, CorruptSampleError
from seist_tpu.registry import register_dataset
from seist_tpu.utils.logger import logger

_INDEX = "index.npz"
_META = "meta.json"
_SIDECAR_SUFFIX = ".idx.npz"

# Event fields packed as scalar-or-NaN columns, in a fixed order.
# ppks/spks are sample indices (int at heart, float for the NaN), the
# rest are the label scalars the TaskSpec io catalog consumes.
_SCALAR_FIELDS = ("ppks", "spks", "emg", "smg", "pmp", "clr", "baz", "dis")
_INT_FIELDS = frozenset({"ppks", "spks", "pmp", "clr"})

# Sidecar/index column dtypes (keys excluded; they stay str).
_INT_COLS = (
    "shard", "offset", "n_ch", "n_samp", "source_id",
    "total_bytes", "plan_lo", "plan_hi", "storage_itemsize",
)
# Per-shard bookkeeping columns that never reach the merged index.
_SIDECAR_ONLY = ("total_bytes", "plan_lo", "plan_hi", "storage_itemsize")

# On-disk waveform storage dtypes (``meta.json["dtype"]``). float32 is
# the training-parity default; bfloat16 halves the shard bytes (and
# therefore read bandwidth) for inference-only archives; int8 (format
# v3) quarters them with a per-row per-channel scale sidecar column —
# readers dequantize/upcast to float32 on fill, so every consumer
# downstream of the read stays dtype-blind (the ROADMAP "quantized
# shard variants" item); the direct-ingest path can additionally stage
# int8 rows AS-IS and dequantize on device (data/ingest.py).
_DTYPE_ALIASES = {"fp32": "float32", "bf16": "bfloat16", "i8": "int8"}

#: int8 per-channel scale sidecar columns (format v3): NaN-padded to 3
#: channels exactly like snr_0..2; float packs never carry them, so the
#: v2 index schema is byte-for-byte unchanged.
_SCALE_COLS = ("scale_0", "scale_1", "scale_2")

#: Symmetric int8 quantization never emits -128 (clip to [-127, 127]),
#: so any -128 byte in a shard is out-of-contract — the poison marker
#: the io_guard ladder treats as permanent corruption (int8 rows cannot
#: carry NaN, this is their NaN-poison equivalent).
INT8_POISON = -128


def canonical_dtype(name: str) -> str:
    name = _DTYPE_ALIASES.get(str(name).lower(), str(name).lower())
    if name not in ("float32", "bfloat16", "int8"):
        raise ValueError(
            f"unsupported packed storage dtype '{name}' "
            "(use float32, bfloat16 or int8)"
        )
    return name


def storage_dtype(name: str) -> np.dtype:
    """Resolve a pack's on-disk waveform dtype. bfloat16 comes from
    ml_dtypes (a jax dependency), which registers it as a real numpy
    dtype — memmap slices / frombuffer / cast-assignment all work."""
    name = canonical_dtype(name)
    if name == "float32":
        return np.dtype(np.float32)
    if name == "int8":
        return np.dtype(np.int8)
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def quantize_rows(data: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-channel int8 quantization of one ``(C, L)`` float32
    waveform: ``scale = max|x| / 127`` (clamped like serve/aot's
    weight quantizer), ``q = clip(round(x / scale), -127, 127)``.
    Returns ``(q int8 (C, L), scale float32 (C,))`` — THE pack-time
    quantizer, shared by the repick engine's parity probe and the
    round-trip tests so tolerances cannot drift from the format."""
    data = np.asarray(data, np.float32)
    scale = (
        np.maximum(np.abs(data).max(axis=1), 1e-8) / 127.0
    ).astype(np.float32)
    q = np.clip(
        np.round(data / scale[:, None]), -127, 127
    ).astype(np.int8)
    return q, scale


class DtypeMixError(ValueError):
    """A pack directory already holds shards across the quantized/float
    boundary from what this run requests. Float<->float resumes repack
    (itemsize is part of the plan identity); int8 packs change the
    SIDECAR SCHEMA too (scale columns), so mixing is refused loudly
    instead of half-rewriting a directory two readers would disagree
    on."""

    def __init__(self, existing: str, requested: str, out_dir: str):
        self.existing = existing
        self.requested = requested
        self.out_dir = out_dir
        super().__init__(
            f"pack dir {out_dir} already holds {existing} shards; "
            f"refusing to mix with --dtype {requested} (int8 packs carry "
            "a scale sidecar column float packs lack). Pack into a fresh "
            "directory, or rewrite this one with --no-resume."
        )


def shard_path(out_dir: str, shard_id: int) -> str:
    return os.path.join(out_dir, f"shard_{shard_id:05d}.bin")


def sidecar_path(out_dir: str, shard_id: int) -> str:
    return shard_path(out_dir, shard_id) + _SIDECAR_SUFFIX


# ------------------------------------------------------------------- planning
@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """One shard's assignment: source ``source_id``'s samples
    ``[lo, hi)`` (source-local indices, source metadata order)."""

    shard_id: int
    source_id: int
    lo: int
    hi: int

    @property
    def n(self) -> int:
        return self.hi - self.lo


def _samples_per_shard(sample_nbytes: int, shard_mb: float) -> int:
    """Deterministic shard capacity from a byte budget: how many sample-0
    sized waveforms fit in ``shard_mb`` (matches the v1 rollover rule for
    uniform-size datasets — every current dataset decodes fixed-length
    traces)."""
    return max(1, int(shard_mb * 1_000_000) // max(int(sample_nbytes), 1))


def plan_shards(
    sources: Sequence[Any],
    *,
    samples_per_shard: Optional[int] = None,
    shard_mb: float = 512,
    dtype: str = "float32",
) -> Tuple[List[ShardPlan], List[int]]:
    """The deterministic shard partition: a pure function of the source
    lengths and the capacity knobs — NEVER of worker count or of which
    shards already exist. Returns ``(plans, per-source capacities)``.

    Sources occupy consecutive shard-id ranges (shards never span
    sources: provenance stays a per-shard constant and workers can own
    contiguous per-source sample ranges). With only ``shard_mb`` given,
    capacity derives PER SOURCE from that source's sample-0 nbytes —
    mixture sources with different trace lengths each honor the byte
    budget; reading one sample per source is the only data the plan
    ever touches."""
    caps: List[int] = []
    for src in sources:
        if samples_per_shard is not None:
            caps.append(max(1, int(samples_per_shard)))
            continue
        event0, _ = src[0]
        nbytes0 = (
            np.ascontiguousarray(event0["data"], dtype=np.float32).size
            * storage_dtype(dtype).itemsize
        )
        caps.append(_samples_per_shard(nbytes0, shard_mb))
    plans: List[ShardPlan] = []
    shard_id = 0
    for source_id, src in enumerate(sources):
        n = len(src)
        sps = caps[source_id]
        for lo in range(0, n, sps):
            plans.append(
                ShardPlan(shard_id, source_id, lo, min(lo + sps, n))
            )
            shard_id += 1
    return plans, caps


# ---------------------------------------------------------------- shard write
def _new_cols(quantized: bool = False) -> Dict[str, list]:
    return {
        **{f: [] for f in _SCALAR_FIELDS},
        "snr_0": [],
        "snr_1": [],
        "snr_2": [],
        **({c: [] for c in _SCALE_COLS} if quantized else {}),
        "offset": [],
        "n_ch": [],
        "n_samp": [],
        "key": [],
    }


def _append_sample(cols: Dict[str, list], event: Event, row: Any, i: int) -> None:
    for f in _SCALAR_FIELDS:
        v = event.get(f, [])
        if len(v) > 1:
            raise ValueError(
                f"event {i}: field {f} has {len(v)} values; the "
                "packed format stores one event per window"
            )
        cols[f].append(float(v[0]) if len(v) else np.nan)
    snr = np.asarray(event.get("snr", []), dtype=np.float64).ravel()
    for c in range(3):
        cols[f"snr_{c}"].append(float(snr[c]) if c < snr.size else np.nan)
    cols["key"].append(str(row.get("key", i)) if isinstance(row, dict) else str(i))


def _col_array(name: str, values: list) -> np.ndarray:
    if name in _INT_COLS:
        return np.asarray(values, np.int64)
    if name == "key":
        return np.asarray(values, str)
    return np.asarray(values, np.float64)


def _write_atomic_npz(path: str, cols: Dict[str, Any]) -> None:
    # Suffix .npz so np.savez doesn't append one of its own.
    tmp = path + ".tmp.npz"
    np.savez(tmp, **{k: _col_array(k, v) for k, v in cols.items()})
    os.replace(tmp, path)


def pack_shard(
    src, out_dir: str, plan: ShardPlan, *, dtype: str = "float32"
) -> Dict[str, int]:
    """Pack ONE shard: the plan's sample range streamed into
    ``shard_XXXXX.bin`` (via a ``.tmp`` rename) followed by its sidecar —
    the sidecar rename is the shard-complete commit point, so a kill at
    any instant leaves either a complete shard or a resumable hole."""
    store_dt = storage_dtype(dtype)
    quantized = store_dt == np.int8
    cols = _new_cols(quantized)
    total = 0
    bin_path = shard_path(out_dir, plan.shard_id)
    tmp_bin = bin_path + ".tmp"
    try:
        with open(tmp_bin, "wb") as f:
            for j in range(plan.lo, plan.hi):
                event, row = src[j]
                data = np.ascontiguousarray(event["data"], dtype=np.float32)
                if data.ndim != 2:
                    raise ValueError(
                        f"event {j}: data must be (C, L), got {data.shape}"
                    )
                if quantized:
                    if data.shape[0] > len(_SCALE_COLS):
                        raise ValueError(
                            f"event {j}: int8 packs support up to "
                            f"{len(_SCALE_COLS)} channels (scale sidecar "
                            f"columns), got {data.shape[0]}"
                        )
                    data, scale = quantize_rows(data)
                    for c in range(len(_SCALE_COLS)):
                        cols[f"scale_{c}"].append(
                            float(scale[c]) if c < scale.size else np.nan
                        )
                elif store_dt != np.float32:
                    data = data.astype(store_dt)
                f.write(data.tobytes())
                _append_sample(cols, event, row, j)
                cols["offset"].append(total)
                cols["n_ch"].append(data.shape[0])
                cols["n_samp"].append(data.shape[1])
                total += data.nbytes
    except BaseException:
        # A failed/interrupted shard must not leave a .tmp that a later
        # resume could mistake for progress (it can't — only the sidecar
        # commits a shard — but don't litter the pack dir either).
        try:
            os.unlink(tmp_bin)
        except OSError:
            pass
        raise
    os.replace(tmp_bin, bin_path)
    cols["source_id"] = [plan.source_id] * plan.n
    cols["total_bytes"] = [total]
    # Plan identity: lets shard_complete refuse a resume whose re-plan
    # assigns this shard a different sample range (source count/order or
    # capacity knobs changed). NOTE an in-place content change of the
    # SOURCE with identical sizes is undetectable without re-reading it
    # — resume assumes immutable sources; use --no-resume after editing
    # a source in place (docs/DATA.md).
    cols["plan_lo"] = [plan.lo]
    cols["plan_hi"] = [plan.hi]
    # Storage dtype is part of the plan identity too: a resume that
    # switches --dtype must repack, not silently mix itemsizes.
    cols["storage_itemsize"] = [store_dt.itemsize]
    _write_atomic_npz(sidecar_path(out_dir, plan.shard_id), cols)
    return {"samples": plan.n, "bytes": total}


def shard_complete(
    out_dir: str, plan: ShardPlan, *, dtype: str = "float32"
) -> bool:
    """A shard is complete iff its sidecar exists, describes the plan's
    sample count AND storage dtype, and the ``.bin`` on disk has exactly
    the byte length the sidecar recorded (a truncated bin from a crashed
    ``os.replace`` window, a re-plan with different capacity, or a resume
    with a different ``--dtype`` all fail this)."""
    side = sidecar_path(out_dir, plan.shard_id)
    bin_p = shard_path(out_dir, plan.shard_id)
    if not (os.path.exists(side) and os.path.exists(bin_p)):
        return False
    try:
        with np.load(side, allow_pickle=False) as z:
            total = int(z["total_bytes"][0])
            n = int(z["offset"].shape[0])
            source_id = int(z["source_id"][0]) if n else plan.source_id
            lo = int(z["plan_lo"][0])
            hi = int(z["plan_hi"][0])
            # Pre-dtype sidecars are all float32 packs.
            itemsize = (
                int(z["storage_itemsize"][0])
                if "storage_itemsize" in z.files
                else 4
            )
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        # A torn/garbled sidecar (np.load raises BadZipFile), or one
        # from a pre-plan-identity pack, is just an incomplete shard:
        # repack it.
        return False
    return (
        n == plan.n
        and source_id == plan.source_id
        and (lo, hi) == (plan.lo, plan.hi)
        and itemsize == storage_dtype(dtype).itemsize
        and os.path.getsize(bin_p) == total
    )


# --------------------------------------------------------------- orchestration
@dataclasses.dataclass
class PackSource:
    """One pack input: either a live dataset instance or a registered
    dataset spec (name + data_dir + kwargs) that every pack worker can
    construct for itself. Spec-based sources are what the CLI builds;
    live instances serve in-process callers and tests."""

    name: str = ""
    data_dir: str = ""
    dataset_kwargs: Optional[dict] = None
    dataset: Any = None

    def create(self) -> Any:
        if self.dataset is not None:
            return self.dataset
        from seist_tpu.registry import DATASETS

        # Pack order must be the source metadata order: no shuffle, no
        # split (the packed reader applies the standard seeded
        # shuffle/split itself — same seed => same split as the source).
        self.dataset = DATASETS.create(
            self.name,
            seed=0,
            mode="train",
            data_dir=self.data_dir,
            shuffle=False,
            data_split=False,
            **(self.dataset_kwargs or {}),
        )
        return self.dataset


_POOL_SOURCES: Optional[List[Any]] = None


def _pack_pool_init(sources: List[PackSource]) -> None:
    global _POOL_SOURCES
    import seist_tpu.data  # noqa: F401  (dataset registrations)

    _POOL_SOURCES = [s.create() for s in sources]


def _pack_pool_shard(job: Tuple[str, ShardPlan, str]) -> Dict[str, int]:
    out_dir, plan, dtype = job
    return pack_shard(
        _POOL_SOURCES[plan.source_id], out_dir, plan, dtype=dtype
    )


def merge_index(
    out_dir: str, plans: Sequence[ShardPlan]
) -> Dict[str, np.ndarray]:
    """Concatenate every sidecar (in shard order) into ``index.npz``
    with the per-row ``shard`` column added. Returns the merged columns."""
    merged: Dict[str, List[Any]] = {}
    for plan in plans:
        with np.load(
            sidecar_path(out_dir, plan.shard_id), allow_pickle=False
        ) as z:
            for k in z.files:
                if k in _SIDECAR_ONLY:
                    continue
                merged.setdefault(k, []).append(z[k])
            merged.setdefault("shard", []).append(
                np.full(plan.n, plan.shard_id, np.int64)
            )
    arrays = {k: np.concatenate(v) for k, v in merged.items()}
    _write_atomic_npz(os.path.join(out_dir, _INDEX), arrays)
    return arrays


def _existing_pack_dtype(out_dir: str) -> Optional[str]:
    """Best-effort canonical dtype of whatever already lives in
    ``out_dir``: meta.json when the pack committed, else the first
    complete sidecar (an interrupted pack has no meta yet). None when
    the directory holds no pack artifacts."""
    meta_p = os.path.join(out_dir, _META)
    if os.path.exists(meta_p):
        try:
            with open(meta_p) as f:
                return canonical_dtype(
                    json.load(f).get("dtype", "float32")
                )
        except (OSError, ValueError, KeyError):
            return None
    try:
        sidecars = sorted(
            f for f in os.listdir(out_dir) if f.endswith(_SIDECAR_SUFFIX)
        )
    except OSError:
        return None
    for name in sidecars:
        try:
            with np.load(
                os.path.join(out_dir, name), allow_pickle=False
            ) as z:
                if "scale_0" in z.files:
                    return "int8"
                itemsize = (
                    int(z["storage_itemsize"][0])
                    if "storage_itemsize" in z.files
                    else 4
                )
            return {1: "int8", 2: "bfloat16"}.get(itemsize, "float32")
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            continue
    return None


def pack_sources(
    sources: Sequence[PackSource],
    out_dir: str,
    *,
    num_workers: int = 0,
    samples_per_shard: Optional[int] = None,
    shard_mb: float = 512,
    resume: bool = True,
    dtype: str = "float32",
) -> Dict[str, Any]:
    """Pack one or more sources into ``out_dir`` (the parallel,
    resumable, mixture-capable path behind both :func:`pack_dataset` and
    ``python -m tools.pack_dataset``). Returns the stats dict the CLI
    prints as its JSON verdict."""
    from seist_tpu.obs.bus import monotonic

    dtype = canonical_dtype(dtype)
    t0 = monotonic()
    os.makedirs(out_dir, exist_ok=True)
    if resume:
        existing = _existing_pack_dtype(out_dir)
        if existing is not None and (existing == "int8") != (
            dtype == "int8"
        ):
            raise DtypeMixError(existing, dtype, out_dir)
    datasets = [s.create() for s in sources]
    channels = list(datasets[0].channels())
    fs = int(datasets[0].sampling_rate())
    for ds in datasets[1:]:
        if list(ds.channels()) != channels or int(ds.sampling_rate()) != fs:
            raise ValueError(
                "mixture sources must share channels and sampling rate: "
                f"{ds.name()} has ({ds.channels()}, {ds.sampling_rate()}) "
                f"vs ({channels}, {fs})"
            )
    plans, caps = plan_shards(
        datasets, samples_per_shard=samples_per_shard, shard_mb=shard_mb,
        dtype=dtype,
    )
    todo = [
        p for p in plans
        if not (resume and shard_complete(out_dir, p, dtype=dtype))
    ]
    skipped = len(plans) - len(todo)
    if skipped:
        logger.info(
            f"pack resume: {skipped}/{len(plans)} shard(s) already "
            f"complete in {out_dir}; packing the remaining {len(todo)}"
        )

    stats = {"samples": 0, "bytes": 0}
    if todo:
        if num_workers and num_workers > 1:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            # forkserver/spawn, never fork: pack may run inside a
            # JAX-initialized parent (pipeline.py has the full rationale).
            try:
                ctx = multiprocessing.get_context("forkserver")
            except ValueError:
                ctx = multiprocessing.get_context("spawn")
            # Spec-based sources are shipped as specs (workers rebuild
            # them), not as the parent's live instances — a live reader
            # can hold unpicklable/expensive state (e.g. a PackedDataset
            # source's cached memmap pickles as the whole shard).
            ship = [
                dataclasses.replace(s, dataset=None) if s.name else s
                for s in sources
            ]
            with ProcessPoolExecutor(
                max_workers=num_workers,
                mp_context=ctx,
                initializer=_pack_pool_init,
                initargs=(ship,),
            ) as pool:
                for out in pool.map(
                    _pack_pool_shard, [(out_dir, p, dtype) for p in todo]
                ):
                    stats["samples"] += out["samples"]
                    stats["bytes"] += out["bytes"]
        else:
            for plan in todo:
                out = pack_shard(
                    datasets[plan.source_id], out_dir, plan, dtype=dtype
                )
                stats["samples"] += out["samples"]
                stats["bytes"] += out["bytes"]

    arrays = merge_index(out_dir, plans)
    n_total = int(arrays["offset"].shape[0])
    meta = {
        "source": (
            datasets[0].name()
            if len(datasets) == 1
            else "mixture:" + "+".join(ds.name() for ds in datasets)
        ),
        "channels": channels,
        "sampling_rate": fs,
        "n_events": n_total,
        "n_shards": len(plans),
        # v3 = int8 waveforms + scale sidecar columns; float packs stay
        # v2 so every pre-int8 reader keeps accepting them unchanged.
        "format_version": 3 if dtype == "int8" else 2,
        "dtype": dtype,
        "samples_per_shard": caps[0] if len(set(caps)) == 1 else caps,
        "sources": [
            {
                "source_id": sid,
                "name": ds.name(),
                "data_dir": getattr(sources[sid], "data_dir", ""),
                "n_events": len(ds),
                "samples_per_shard": caps[sid],
            }
            for sid, ds in enumerate(datasets)
        ],
    }
    # meta.json LAST — its presence is the whole-pack commit point.
    tmp = os.path.join(out_dir, _META + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(out_dir, _META))
    wall_s = monotonic() - t0
    logger.info(
        f"packed {n_total} events into {len(plans)} shard(s) at {out_dir} "
        f"({skipped} resumed, {wall_s:.1f}s)"
    )
    # On-disk accounting for the dtype ladder verdict: actual shard
    # bytes vs what the same event set costs at fp32 (the ISSUE 18
    # bytes<=0.55x acceptance is measured here, not asserted).
    on_disk = sum(
        os.path.getsize(shard_path(out_dir, p.shard_id)) for p in plans
    )
    fp32_bytes = int((arrays["n_ch"] * arrays["n_samp"]).sum()) * 4
    return {
        "out": out_dir,
        "dtype": dtype,
        "shards": len(plans),
        "shards_skipped": skipped,
        "samples": n_total,
        "samples_packed": stats["samples"],
        "bytes": stats["bytes"],
        "on_disk_bytes": on_disk,
        "bytes_per_row": round(on_disk / max(n_total, 1), 1),
        "fp32_bytes_per_row": round(fp32_bytes / max(n_total, 1), 1),
        "bytes_vs_fp32": round(on_disk / max(fp32_bytes, 1), 4),
        "samples_per_shard": meta["samples_per_shard"],
        "sources": [s["name"] for s in meta["sources"]],
        "wall_s": round(wall_s, 2),
    }


def pack_dataset(
    src,
    out_dir: str,
    *,
    shard_mb: float = 512,
    samples_per_shard: Optional[int] = None,
    num_workers: int = 0,
    dtype: str = "float32",
    log_every: int = 0,  # kept for call-site compat; progress is per shard
) -> str:
    """Repack ``src`` (any DatasetBase, pre-split disabled) into packed
    shards under ``out_dir``. Returns ``out_dir``."""
    del log_every
    pack_sources(
        [PackSource(dataset=src)],
        out_dir,
        num_workers=num_workers,
        samples_per_shard=samples_per_shard,
        shard_mb=shard_mb,
        dtype=dtype,
    )
    return out_dir


def read_waveform_slice(
    mmaps: Dict[int, np.memmap],
    data_dir: str,
    shard: int,
    off: int,
    nbytes: int,
    *,
    desc: str,
) -> np.ndarray:
    """THE raw-slice fault ladder for packed shards, shared by the Event
    reader (:class:`PackedDataset`) and the direct-ingest store
    (data/ingest.py) so their io_guard classification can never diverge:
    per-shard memmaps cached in ``mmaps``; ``OSError`` (shard vanished /
    page-in failure on a network mount) evicts the cached map — counted
    as ``reopens``, same telemetry as evict_h5 — and re-raises as a
    TRANSIENT fault (the retry re-mmaps a fresh fd); a slice that comes
    back short means the shard file is truncated — PERMANENT corruption
    (:class:`CorruptSampleError`). Returns the uint8 slice view."""
    mm = mmaps.get(shard)
    if mm is None:
        mm = mmaps[shard] = np.memmap(
            shard_path(data_dir, shard), dtype=np.uint8, mode="r"
        )
    try:
        raw = mm[off : off + nbytes]
    except OSError:
        if mmaps.pop(shard, None) is not None:
            COUNTERS.inc("reopens")
        raise
    if raw.size != nbytes:
        raise CorruptSampleError(
            f"{desc}: short read in shard {shard} (want {nbytes} bytes "
            f"at {off}, got {raw.size} — truncated shard?)"
        )
    return raw


class PackedDataset(DatasetBase):
    """Reader for :func:`pack_dataset` output (registered as ``packed``).

    Same metadata/split/Event contract as every other dataset; the
    waveform read is a single memmap slice + one ``.astype`` copy
    instead of h5py's per-sample group walk."""

    _name = "packed"

    def __init__(self, **kwargs):
        data_dir = kwargs.get("data_dir", "")
        with open(os.path.join(data_dir, _META)) as f:
            self._meta = json.load(f)
        # Pre-dtype packs (and every v1 pack) stored float32.
        self._storage_dtype = storage_dtype(
            self._meta.get("dtype", "float32")
        )
        self._mmaps: Dict[int, np.memmap] = {}
        super().__init__(**kwargs)

    # Instance-level overrides of the classmethod accessors: the values
    # come from meta.json, not the class.
    def name(self):  # type: ignore[override]
        return self._name

    def __repr__(self) -> str:
        return (
            f"Dataset(name:packed, source:{self._meta['source']}, "
            f"channels:{self._meta['channels']}, "
            f"sampling_rate:{self._meta['sampling_rate']}, "
            f"n_events:{self._meta['n_events']}, "
            f"n_shards:{self._meta['n_shards']}, "
            f"data_dir:{self._data_dir}, mode:{self._mode})"
        )

    def channels(self):  # type: ignore[override]
        return list(self._meta["channels"])

    def sampling_rate(self):  # type: ignore[override]
        return int(self._meta["sampling_rate"])

    @property
    def storage_dtype(self) -> np.dtype:
        """On-disk waveform dtype (readers upcast to float32 on read)."""
        return self._storage_dtype

    def sources(self) -> List[Dict[str, Any]]:
        """Provenance of a mixture pack (one entry per source; v1 packs
        report their single source)."""
        return list(
            self._meta.get(
                "sources",
                [{"source_id": 0, "name": self._meta["source"],
                  "n_events": self._meta["n_events"]}],
            )
        )

    def source_ids(self) -> Optional[np.ndarray]:
        """Per-sample source id (THIS split's row order) when the pack
        holds a mixture; ``None`` for single-source packs — the signal
        ``pipeline``'s temperature-weighted sampler keys on."""
        if len(self.sources()) <= 1 or "source_id" not in self._meta_data:
            return None
        return self._meta_data["source_id"].to_numpy()

    def _load_meta_data(self) -> pd.DataFrame:
        with np.load(
            os.path.join(self._data_dir, _INDEX), allow_pickle=False
        ) as z:
            frame = pd.DataFrame({k: z[k] for k in z.files})
        if len(frame) != self._meta["n_events"]:
            raise ValueError(
                f"index has {len(frame)} rows, meta.json says "
                f"{self._meta['n_events']}"
            )
        return self._shuffle_and_split(frame)

    # Instances cross process boundaries (process-pool loader workers,
    # shard-parallel pack workers). A cached np.memmap pickles as a FULL
    # ndarray — the entire shard's bytes per worker — so ship the state
    # without the maps; workers re-mmap lazily on first read.
    def __getstate__(self) -> Dict[str, Any]:
        state = dict(self.__dict__)
        state["_mmaps"] = {}
        return state

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        row = self._row_dict(idx)
        c, length = int(row["n_ch"]), int(row["n_samp"])
        raw = read_waveform_slice(
            self._mmaps,
            self._data_dir,
            int(row["shard"]),
            int(row["offset"]),
            c * length * self._storage_dtype.itemsize,
            desc=f"packed (sample {idx})",
        )
        # .astype always copies — bf16 packs upcast, f32 packs keep the
        # original copy-out-of-the-memmap semantics.
        data = (
            np.frombuffer(raw, dtype=self._storage_dtype)
            .reshape(c, length)
            .astype(np.float32)
        )
        if self._storage_dtype == np.int8:
            # Format v3 host-path dequant. int8 rows cannot carry NaN,
            # so their poison markers are the out-of-contract -128 byte
            # and a non-finite sidecar scale — both permanent corruption
            # through the same io_guard ladder as a NaN-poisoned float
            # row.
            scale = np.array(
                [row[f"scale_{ch}"] for ch in range(c)], np.float32
            )
            if data.min() <= INT8_POISON:
                raise CorruptSampleError(
                    f"packed (sample {idx}): int8 row holds the "
                    f"out-of-contract {INT8_POISON} byte (poisoned?)"
                )
            if not np.isfinite(scale).all():
                raise CorruptSampleError(
                    f"packed (sample {idx}): non-finite int8 scale "
                    f"{scale.tolist()}"
                )
            data *= scale[:, None]

        def scalar(field):
            v = row[field]
            if v != v:  # NaN
                return []
            return [int(v)] if field in _INT_FIELDS else [np.float32(v)]

        event: Event = {"data": data}
        for f in _SCALAR_FIELDS:
            event[f] = scalar(f)
        event["snr"] = np.array(
            [row["snr_0"], row["snr_1"], row["snr_2"]]
        )
        return event, row


@register_dataset
def packed(**kwargs):
    return PackedDataset(**kwargs)
