"""Dataset base class: metadata loading + seeded shuffle/split.

Counterpart of the reference's ``datasets/base.py:5-90``: a dataset owns a
pandas metadata table and lazily reads one event's waveform + labels per
``__getitem__``. The seeded shuffle-then-contiguous-split contract
(ref diting.py:99-116) is hoisted here so every subclass shares it — the
same seed must yield the same split across train and later test runs
(ref README.md:226 warning).
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Dict, Optional, Tuple

import pandas as pd

from seist_tpu.utils.logger import logger

Event = Dict[str, Any]


class _H5Handles(threading.local):
    """Per-thread LRU cache of read-only h5py file handles.

    h5py file opens cost ~0.3 ms each (profiled: 2 opens/sample dominated
    the DiTing read stage); handles are NOT thread-safe to share, so each
    loader thread keeps its own. Capped (LRU-evicted handles are closed)
    so threads x part-files cannot exhaust the process fd limit: 28 DiTing
    parts x 32 threads would be ~900 fds uncapped vs 1024 default ulimit.
    Process-pool workers each get a fresh module state, so the cache
    composes with ``--loader-processes``.
    """

    MAX_OPEN = 16  # per thread

    def __init__(self):
        from collections import OrderedDict

        self.handles: "OrderedDict[str, Any]" = OrderedDict()


_h5_local = _H5Handles()


def open_h5(path: str, group: Optional[str] = None):
    """Thread-cached read-only ``h5py.File`` (see :class:`_H5Handles`).

    With ``group``, returns the (also cached) named group — saves the
    per-sample path walk when every event lives under one root group.
    """
    import h5py

    cache = _h5_local.handles
    entry = cache.get(path)
    if entry is None or not entry[0]:  # File is falsy once closed/invalid
        entry = (h5py.File(path, "r"), {})
        cache[path] = entry
        if len(cache) > _H5Handles.MAX_OPEN:
            _, (old_f, _) = cache.popitem(last=False)
            try:
                old_f.close()
            except Exception:  # noqa: BLE001 - already-invalid handle
                pass
    else:
        cache.move_to_end(path)
    f, groups = entry
    if group is None:
        return f
    g = groups.get(group)
    if g is None:
        g = groups[group] = f[group]
    return g


def evict_h5(path: str) -> bool:
    """Close and drop the calling thread's cached handle (and group cache)
    for ``path``. Readers call this when a read through the cached handle
    fails: an h5py ``File`` object stays truthy even when its backing fd
    has gone stale (NFS timeout, file replaced under us), so without
    eviction :func:`open_h5` would keep serving the dead handle forever
    and every retry would fail identically. After eviction the next
    ``open_h5`` reopens from scratch. Returns whether a handle was
    actually dropped."""
    cache = _h5_local.handles
    entry = cache.pop(path, None)
    if entry is None:
        return False
    try:
        entry[0].close()
    except Exception:  # noqa: BLE001 - handle already broken; dropping it is the point
        pass
    from seist_tpu.data.io_guard import COUNTERS

    COUNTERS.inc("reopens")
    return True


class DatasetBase:
    _name: str = ""
    _part_range: Optional[tuple] = None
    _channels: list = []
    _sampling_rate: int = 0

    def __init__(
        self,
        seed: int,
        mode: str,
        data_dir: str,
        shuffle: bool = True,
        data_split: bool = True,
        train_size: float = 0.8,
        val_size: float = 0.1,
        **kwargs,
    ):
        self._seed = seed
        mode = mode.lower()
        if mode not in ("train", "val", "test"):
            raise ValueError(f"mode must be train/val/test, got '{mode}'")
        self._mode = mode
        self._data_dir = data_dir
        self._shuffle = shuffle
        self._data_split = data_split
        if train_size + val_size >= 1.0:
            raise ValueError(f"train_size:{train_size}, val_size:{val_size}")
        self._train_size = train_size
        self._val_size = val_size
        self._meta_data = self._load_meta_data()

    # -- subclass hooks ------------------------------------------------------
    def _load_meta_data(self) -> pd.DataFrame:
        raise NotImplementedError

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        raise NotImplementedError

    # -- shared split logic --------------------------------------------------
    def _shuffle_and_split(self, meta_df: pd.DataFrame) -> pd.DataFrame:
        """Seeded full-frame shuffle, then contiguous train/val/test ranges
        (ref base.py:42, diting.py:99-116)."""
        if self._shuffle:
            meta_df = meta_df.sample(frac=1, replace=False, random_state=self._seed)
        meta_df = meta_df.reset_index(drop=True)
        if self._data_split:
            n = meta_df.shape[0]
            t_end = int(self._train_size * n)
            v_end = t_end + int(self._val_size * n)
            lo, hi = {
                "train": (0, t_end),
                "val": (t_end, v_end),
                "test": (v_end, n),
            }[self._mode]
            meta_df = meta_df.iloc[lo:hi, :]
            logger.info(f"Data Split: {self._mode}: {lo}-{hi}")
        return meta_df

    # -- fast row access -----------------------------------------------------
    def _row_dict(self, idx: int) -> Dict[str, Any]:
        """Metadata row ``idx`` as a plain dict, via a one-time column->numpy
        cache. ``DataFrame.iloc[idx]`` + per-field ``Series.__getitem__`` cost
        ~1 ms/sample in the loader hot path (profiled); numpy scalar indexing
        is ~30x cheaper and readers keep the same ``row[col]`` syntax."""
        cols = getattr(self, "_col_cache", None)
        if cols is None:
            cols = {
                c: self._meta_data[c].to_numpy()
                for c in self._meta_data.columns
            }
            self._col_cache = cols
        return {c: a[idx] for c, a in cols.items()}

    # -- public API (ref base.py:67-90) --------------------------------------
    def __len__(self) -> int:
        return len(self._meta_data)

    def __getitem__(self, idx: int) -> Tuple[Event, dict]:
        return self._load_event_data(idx=idx)

    def __repr__(self) -> str:
        return (
            f"Dataset(name:{self._name}, part_range:{self._part_range}, "
            f"channels:{self._channels}, sampling_rate:{self._sampling_rate}, "
            f"data_dir:{self._data_dir}, shuffle:{self._shuffle}, "
            f"data_split:{self._data_split}, train_size:{self._train_size}, "
            f"val_size:{self._val_size})"
        )

    @classmethod
    def name(cls) -> str:
        return cls._name

    @classmethod
    def sampling_rate(cls) -> int:
        return cls._sampling_rate

    @classmethod
    def channels(cls) -> list:
        return copy.deepcopy(cls._channels)
