"""SOS dataset reader (ref datasets/sos.py:11-91).

Single-channel 500 Hz waveforms stored as one ``.npz`` per trace, already
split on disk into ``train/ val/ test/`` subdirectories each holding an
``_all_label.csv`` index — so ``data_split`` is ignored (ref sos.py:43-46).
The reference's attribute bugs (``self.data_dir``/``self.mode`` without
underscore, sos.py:71) are fixed here, per SURVEY.md Appendix A.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np
import pandas as pd

from seist_tpu.data.base import DatasetBase, Event
from seist_tpu.data.io_guard import CorruptSampleError
from seist_tpu.registry import register_dataset
from seist_tpu.utils.logger import logger
from seist_tpu.utils.misc import cal_snr


class SOS(DatasetBase):
    _name = "sos"
    _part_range = None
    _channels = ["z"]
    _sampling_rate = 500

    def __init__(self, *, data_split: bool = False, **kwargs):
        super().__init__(data_split=data_split, **kwargs)

    def _load_meta_data(self) -> pd.DataFrame:
        if self._data_split:
            logger.warning(
                "dataset 'sos' is pre-split on disk; 'data_split' is ignored."
            )
        csv_path = os.path.join(self._data_dir, self._mode, "_all_label.csv")
        return pd.read_csv(csv_path, dtype={"fname": str, "itp": int, "its": int})

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        row = self._meta_data.iloc[idx]
        fpath = os.path.join(self._data_dir, self._mode, row["fname"])
        # OSError (incl. FileNotFoundError on a flaky mount) propagates as
        # a transient fault and is retried by the pipeline guard (no
        # cached handle to evict here — np.load opens fresh each time);
        # a file that unzips/decodes wrong is permanent corruption
        # (data/io_guard.py classification).
        import zipfile

        try:
            npz = np.load(fpath)
            data = np.stack(npz["data"].astype(np.float32), axis=1)
        except (zipfile.BadZipFile, KeyError, ValueError) as e:
            raise CorruptSampleError(
                f"sos: undecodable trace file {row['fname']!r} ({e})"
            ) from e
        # Unparseable pick columns are per-sample corruption (quarantine),
        # same classification as an undecodable waveform.
        try:
            ppk, spk = int(row["itp"]), int(row["its"])
        except (ValueError, TypeError) as e:
            raise CorruptSampleError(
                f"sos: undecodable picks for {row['fname']!r} ({e})"
            ) from e
        event: Event = {
            "data": data,
            "ppks": [ppk] if ppk > 0 else [],
            "spks": [spk] if spk > 0 else [],
            "snr": cal_snr(data=data, pat=ppk) if ppk > 0 else 0.0,
        }
        return event, row.to_dict()


@register_dataset
def sos(**kwargs):
    return SOS(**kwargs)
