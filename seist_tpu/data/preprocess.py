"""Waveform preprocessing: augmentation, windowing, normalization, labels.

Behavior-parity re-implementation of the reference's
``training/preprocess.py:16-821`` (DataPreprocessor and helpers), with two
deliberate changes for the TPU stack:

* **Explicit RNG** — every stochastic method takes a
  ``numpy.random.Generator`` instead of mutating global ``np.random`` state
  (the reference seeds globals in ``utils/misc.py:14-21``). This gives
  per-sample reproducibility independent of worker scheduling.
* **Channels-last outputs** — event data is ``(C, L)`` internally (matching
  the physics/augmentation math) but assembled io-items are channels-last:
  grouped items stack to ``(L, C)`` (the reference returns ``(C, L)``,
  preprocess.py:714-717).

Every method cites the reference lines it mirrors; the quirks checklist in
SURVEY.md Appendix A is encoded in tests/test_preprocess.py.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from seist_tpu import taskspec
from seist_tpu.utils.logger import logger

Event = Dict[str, Any]


def normalize(
    data: np.ndarray, mode: str = "std", axis: int = -1
) -> np.ndarray:
    """Demean + scale along ``axis`` — THE normalization every inference
    and training path shares (was copied in demo_predict.py and inlined in
    ops/stream.annotate; deduplicated here).

    Modes (named after their reference origins):

    * ``'std'``    — z-score (ref preprocess.py:224-242, std branch).
    * ``'max'``    — divide by the SIGNED per-channel max after demeaning
      (ref preprocess.py:228 uses ``np.max``, not abs-max — the training
      pipeline's quirk, preserved bit-for-bit; also the native kernel's
      contract, wavekit.cpp znorm mode 1).
    * ``'absmax'`` — divide by the abs max (ref demo_predict.py:8-23 —
      the demo's variant of 'max').
    * ``''``       — demean only.

    Zero scales divide by 1. Uses the native wavekit kernel when built for
    the hot 2-D (C, L) float32 case (one C call instead of several numpy
    passes per sample); the numpy path never mutates the input.
    """
    data = np.asarray(data)
    from seist_tpu import native

    if (
        native.available()
        and mode in ("std", "max", "")
        and data.ndim == 2
        and axis in (1, -1)
    ):
        # Explicit copy: ascontiguousarray returns the caller's array
        # unchanged when it is already float32 C-contiguous, and the
        # in-place native kernel would then mutate the caller's data.
        buf = np.array(data, dtype=np.float32, copy=True, order="C")
        if native.znorm(buf, mode):
            return buf
    data = data - np.mean(data, axis=axis, keepdims=True)
    if mode == "max":
        scale = np.max(data, axis=axis, keepdims=True)
    elif mode == "absmax":
        scale = np.max(np.abs(data), axis=axis, keepdims=True)
    elif mode == "std":
        scale = np.std(data, axis=axis, keepdims=True)
    elif mode == "":
        return data
    else:
        raise ValueError(
            f"Supported modes: 'max', 'absmax', 'std', '', got '{mode}'"
        )
    scale[scale == 0] = 1
    return data / scale


def make_soft_window(soft_label_width: int, soft_label_shape: str) -> np.ndarray:
    """The (width+1)-sample soft-label window (ref: preprocess.py:571-601).

    Module-level so the device-side label synthesis
    (seist_tpu/data/device_aug.py) shares the ONE window formula with
    :class:`DataPreprocessor` — the gaussian's fixed sigma-10 quirk must
    never fork between the host and device paths.
    """
    left = int(soft_label_width / 2)
    right = soft_label_width - left
    if soft_label_shape == "gaussian":
        # NB the gaussian sigma is fixed at 10 regardless of label width
        # (ref quirk, preprocess.py:576-578).
        return np.exp(-((np.arange(-left, right + 1)) ** 2) / (2 * 10**2))
    if soft_label_shape == "triangle":
        return 1 - np.abs(2 / soft_label_width * np.arange(-left, right + 1))
    if soft_label_shape == "box":
        return np.ones(soft_label_width + 1)
    if soft_label_shape == "sigmoid":
        def _sigmoid(x):
            return 1 / (1 + np.exp(x))

        l_l, l_r = -int(left / 2), left - int(left / 2)
        r_l, r_r = -int(right / 2), right - int(right / 2)
        x_l = -10 / left * np.arange(l_l, l_r)
        x_r = -10 / right * (-1) * np.arange(r_l, r_r)
        return np.concatenate((_sigmoid(x_l), [1.0], _sigmoid(x_r)), axis=0)
    raise NotImplementedError(f"Unsupported label shape: '{soft_label_shape}'")


def pad_phases(
    ppks: list, spks: list, padding_idx: int, num_samples: int
) -> Tuple[list, list]:
    """Pad the P/S phase lists to equal length (ref: preprocess.py:16-35).

    Leading unmatched S picks get a ``-padding_idx`` partner P; trailing
    unmatched P picks get a ``num_samples + padding_idx`` partner S.
    """
    padding_idx = abs(padding_idx)
    ppks, spks = sorted(ppks), sorted(spks)
    ppk_arr, spk_arr = np.array(ppks), np.array(spks)
    idx = 0
    while idx < min(len(ppks), len(spks)) and all(
        ppk_arr[: idx + 1] < spk_arr[-idx - 1 :]
    ):
        idx += 1
    ppks = len(spk_arr[: len(spk_arr) - idx]) * [-padding_idx] + ppks
    spks = spks + len(ppk_arr[idx:]) * [num_samples + padding_idx]
    assert len(ppks) == len(spks), f"pad_phases failed: {ppks} vs {spks}"
    return ppks, spks


def pad_array(s, length: int, padding_value: Union[int, float]) -> np.ndarray:
    """Right-pad a 1-D array to ``length`` (ref: preprocess.py:38-49)."""
    s = np.asarray(s)
    padding_size = int(length - s.shape[0])
    if padding_size < 0:
        raise ValueError(f"length < len(s): {s.shape[0]} > {length}")
    return np.pad(s, (0, padding_size), mode="constant", constant_values=padding_value)


class DataPreprocessor:
    """Augmentation + windowing + normalization + label generation.

    Ref: training/preprocess.py:52-821. Constructor arguments carry the same
    names and semantics as the reference so CLI flags map 1:1.
    """

    def __init__(
        self,
        data_channels: Sequence[str],
        sampling_rate: int,
        in_samples: int,
        min_snr: float = float("-inf"),
        p_position_ratio: float = -1.0,
        coda_ratio: float = 1.4,
        norm_mode: str = "std",
        add_event_rate: float = 0.0,
        add_noise_rate: float = 0.0,
        add_gap_rate: float = 0.0,
        drop_channel_rate: float = 0.0,
        scale_amplitude_rate: float = 0.0,
        pre_emphasis_rate: float = 0.0,
        pre_emphasis_ratio: float = 0.97,
        max_event_num: int = 1,
        generate_noise_rate: float = 0.0,
        shift_event_rate: float = 0.0,
        mask_percent: float = 0.0,
        noise_percent: float = 0.0,
        min_event_gap_sec: float = 0.0,
        soft_label_shape: str = "gaussian",
        soft_label_width: int = 50,
        dtype=np.float32,
    ):
        self.data_channels = list(data_channels)
        self.sampling_rate = sampling_rate
        self.in_samples = in_samples
        self.coda_ratio = coda_ratio
        self.norm_mode = norm_mode
        self.min_snr = min_snr
        self.p_position_ratio = p_position_ratio

        self.add_event_rate = add_event_rate
        self.add_noise_rate = add_noise_rate
        self.add_gap_rate = add_gap_rate
        self.drop_channel_rate = drop_channel_rate
        self.scale_amplitude_rate = scale_amplitude_rate
        self.pre_emphasis_rate = pre_emphasis_rate
        self.pre_emphasis_ratio = pre_emphasis_ratio
        self._max_event_num = max_event_num
        self.generate_noise_rate = generate_noise_rate
        self.shift_event_rate = shift_event_rate
        self.mask_percent = mask_percent
        self.noise_percent = noise_percent
        self.min_event_gap = int(min_event_gap_sec * self.sampling_rate)

        # p_position_ratio mode force-disables add/shift/noise-gen augments
        # (ref: preprocess.py:113-131).
        if 0 <= self.p_position_ratio <= 1:
            for attr in ("add_event_rate", "shift_event_rate", "generate_noise_rate"):
                if getattr(self, attr) > 0:
                    setattr(self, attr, 0.0)
                    logger.warning(
                        f"`p_position_ratio` is {p_position_ratio}, `{attr}` -> 0.0"
                    )

        self.soft_label_shape = soft_label_shape
        self.soft_label_width = soft_label_width
        self.dtype = dtype
        # (width, shape) -> window array; hot-path memo for _soft_window.
        self._window_cache: dict = {}

    # ------------------------------------------------------------------ noise
    def _clear_event_except(self, event: Event, *keep: str) -> None:
        """Blank all event fields except ``keep`` (ref: preprocess.py:136-152)."""
        for k in set(event) - set(keep):
            v = event[k]
            if isinstance(v, (list, dict)):
                v.clear()
            elif isinstance(v, np.ndarray):
                event[k] = np.array([])
            elif isinstance(v, (int, float, np.integer, np.floating)):
                event[k] = 0
            elif isinstance(v, str):
                event[k] = ""
            else:
                raise TypeError(f"Got `{v}` ({type(v)})")

    def _is_noise(self, data, ppks, spks, snr) -> bool:
        """Classify a trace as noise (ref: preprocess.py:154-170)."""
        snr = np.asarray(snr)
        is_noise = (
            (len(ppks) != len(spks))
            or len(ppks) < 1
            or len(spks) < 1
            or min(ppks + spks) < 0
            or max(ppks + spks) >= data.shape[-1]
            or bool(np.all(snr < self.min_snr))
        )
        # NB: iterate min(len) — the reference indexes spks over len(ppks)
        # (preprocess.py:168-169), which raises on mismatched lists; with a
        # mismatch is_noise is already True so the semantics are unchanged.
        for i in range(min(len(ppks), len(spks))):
            is_noise |= ppks[i] >= spks[i]
        return bool(is_noise)

    # ---------------------------------------------------------------- window
    def _cut_window(
        self,
        data: np.ndarray,
        ppks: list,
        spks: list,
        window_size: int,
        rng: np.random.Generator,
    ) -> Tuple[np.ndarray, list, list]:
        """Cut to ``window_size`` (ref: preprocess.py:172-222)."""
        input_len = data.shape[-1]

        if 0 <= self.p_position_ratio <= 1:
            # Pin the first P arrival at a fixed window fraction.
            new_data = np.zeros((data.shape[0], window_size), dtype=np.float32)
            tgt_l, tgt_r = 0, window_size
            p_idx = ppks[0]
            c_l = p_idx - int(window_size * self.p_position_ratio)
            c_r = c_l + window_size
            offset = -c_l
            if c_l < 0:
                tgt_l += abs(c_l)
                offset += c_l
                c_l = 0
            if c_r > data.shape[-1]:
                tgt_r -= c_r - data.shape[-1]
                c_r = data.shape[-1]
            new_data[:, tgt_l:tgt_r] = data[:, c_l:c_r]
            offset += tgt_l
            data = new_data
            ppks = [t + offset for t in ppks if 0 <= t + offset < window_size]
            spks = [t + offset for t in spks if 0 <= t + offset < window_size]
        else:
            if input_len > window_size:
                # Random crop; events near the left edge stay in-window
                # (ref: preprocess.py:206-215).
                c_l = int(
                    rng.integers(
                        0,
                        max(
                            min(ppks + [input_len - window_size]) - self.min_event_gap,
                            1,
                        ),
                    )
                )
                c_r = c_l + window_size
                data = data[:, c_l:c_r]
                ppks = [t - c_l for t in ppks if c_l <= t < c_r]
                spks = [t - c_l for t in spks if c_l <= t < c_r]
            elif input_len < window_size:
                data = np.concatenate(
                    [data, np.zeros((data.shape[0], window_size - input_len))], axis=1
                )
        return data, ppks, spks

    def _normalize(self, data: np.ndarray, mode: str) -> np.ndarray:
        """Per-channel demean + max/std normalize (ref: preprocess.py:224-242).

        Thin wrapper over the canonical module-level :func:`normalize`
        (signed-max semantics); kept as a method because subclass hooks and
        tests target it."""
        if mode not in ("max", "std", ""):
            raise ValueError(f"Supported mode: 'max','std', got '{mode}'")
        return normalize(data, mode, axis=1)

    # ----------------------------------------------------------- augmentation
    def _generate_noise_data(self, data, ppks, spks, rng):
        """Wipe phases+coda with white noise (ref: preprocess.py:244-263)."""
        if len(ppks) > 0 and len(spks) > 0:
            for ppk, spk in zip(ppks, spks):
                coda_end = int(
                    np.clip(int(spk + self.coda_ratio * (spk - ppk)), 0, data.shape[-1])
                )
                if ppk < coda_end:
                    data[:, ppk:coda_end] = rng.standard_normal(
                        (data.shape[0], coda_end - ppk)
                    )
        return data, [], []

    def _add_event(self, data, ppks, spks, min_gap, rng):
        """Duplicate a scaled copy of an event (ref: preprocess.py:265-292)."""
        target_idx = int(rng.integers(0, len(ppks)))
        ppk, spk = ppks[target_idx], spks[target_idx]
        coda_end = int(spk + self.coda_ratio * (spk - ppk))
        left = coda_end + min_gap
        right = data.shape[-1] - (spk - ppk) - min_gap
        if left < right:
            ppk_add = int(rng.integers(left, right))
            spk_add = ppk_add + spk - ppk
            space = min(data.shape[-1] - ppk_add, coda_end - ppk)
            scale = rng.random()
            data[:, ppk_add : ppk_add + space] += data[:, ppk : ppk + space] * scale
            ppks.append(ppk_add)
            spks.append(spk_add)
        ppks.sort()
        spks.sort()
        return data, ppks, spks

    def _shift_event(self, data, ppks, spks, rng):
        """Circular time shift (ref: preprocess.py:294-305)."""
        shift = int(rng.integers(0, data.shape[-1]))
        data = np.concatenate((data[:, -shift:], data[:, :-shift]), axis=1)
        ppks = sorted((p + shift) % data.shape[-1] for p in ppks)
        spks = sorted((s + shift) % data.shape[-1] for s in spks)
        return data, ppks, spks

    def _drop_channel(self, data, rng):
        """Zero a random subset of channels (ref: preprocess.py:307-321)."""
        if data.shape[0] < 2:
            return data
        drop_num = int(rng.choice(range(1, data.shape[0])))
        candidates = list(range(data.shape[0]))
        for _ in range(drop_num):
            c = int(rng.choice(candidates))
            candidates.remove(c)
            data[c, :] = 0.0
        return data

    def _adjust_amplitude(self, data):
        """Rescale after channel drop (ref: preprocess.py:323-333)."""
        max_amp = np.max(np.abs(data), axis=1)
        if np.count_nonzero(max_amp) > 0:
            data *= data.shape[0] / np.count_nonzero(max_amp)
        return data

    def _scale_amplitude(self, data, rng):
        """Random amplitude scale x/÷ U(1,3) (ref: preprocess.py:335-344)."""
        if rng.uniform(0, 1) < 0.5:
            data *= rng.uniform(1, 3)
        else:
            data /= rng.uniform(1, 3)
        return data

    def _pre_emphasis(self, data, pre_emphasis: float):
        """First-order pre-emphasis filter (ref: preprocess.py:346-353)."""
        emphasized = np.empty_like(data)
        emphasized[:, 0] = data[:, 0]
        emphasized[:, 1:] = data[:, 1:] - pre_emphasis * data[:, :-1]
        data[...] = emphasized
        return data

    def _add_noise(self, data, rng):
        """Add gaussian noise at random SNR in [10,50) dB
        (ref: preprocess.py:355-368)."""
        for c in range(data.shape[0]):
            x = data[c, :]
            snr = int(rng.integers(10, 50))
            px = np.sum(x**2) / len(x)
            pn = px * 10 ** (-snr / 10.0)
            data[c, :] += rng.standard_normal(len(x)) * np.sqrt(pn)
        return data

    def _add_gaps(self, data, ppks, spks, rng):
        """Zero a random span between phases (ref: preprocess.py:370-390)."""
        phases = sorted(ppks + spks)
        if len(phases) > 0:
            phases.append(data.shape[-1] - 1)
            phases = sorted(set(phases))
            insert_pos = int(rng.integers(0, len(phases) - 1))
            sgt = int(rng.integers(phases[insert_pos], phases[insert_pos + 1]))
            egt = int(rng.integers(sgt, phases[insert_pos + 1]))
        else:
            sgt = int(rng.integers(0, data.shape[-1] - 1))
            egt = int(rng.integers(sgt + 1, data.shape[-1]))
        data[:, sgt:egt] = 0
        return data

    def _add_mask_windows(self, data, percent, window_size, rng, mask_value=1.0):
        """Mask a percentage of fixed windows (ref: preprocess.py:392-412)."""
        p = np.clip(percent, 0, 100)
        num_windows = data.shape[-1] // window_size
        num_mask = int(num_windows * p // 100)
        selected = rng.choice(range(num_windows), num_mask, replace=False)
        for i in selected:
            st = i * window_size
            data[:, st : st + window_size] = mask_value
        return data

    def _add_noise_windows(self, data, percent, window_size, rng):
        """White-noise a percentage of fixed windows (ref: preprocess.py:414-430)."""
        p = np.clip(percent, 0, 100)
        num_windows = data.shape[-1] // window_size
        num_block = int(num_windows * p // 100)
        selected = rng.choice(range(num_windows), num_block, replace=False)
        for i in selected:
            st = i * window_size
            data[:, st : st + window_size] = rng.standard_normal(
                (data.shape[0], window_size)
            )
        return data

    def _data_augmentation(self, event: Event, rng: np.random.Generator) -> Event:
        """The 9-way augmentation pipeline (ref: preprocess.py:432-499)."""
        data, ppks, spks = event["data"], event["ppks"], event["spks"]

        if rng.random() < self.generate_noise_rate:
            data, ppks, spks = self._generate_noise_data(data, ppks, spks, rng)
            self._clear_event_except(event, "data")
            if rng.random() < self.drop_channel_rate:
                data = self._drop_channel(data, rng)
                data = self._adjust_amplitude(data)
            if rng.random() < self.scale_amplitude_rate:
                data = self._scale_amplitude(data, rng)
        else:
            for _ in range(self._max_event_num - len(ppks)):
                if rng.random() < self.add_event_rate and ppks:
                    data, ppks, spks = self._add_event(
                        data, ppks, spks, self.min_event_gap, rng
                    )
            if rng.random() < self.shift_event_rate:
                data, ppks, spks = self._shift_event(data, ppks, spks, rng)
            if rng.random() < self.drop_channel_rate:
                data = self._drop_channel(data, rng)
                data = self._adjust_amplitude(data)
            if rng.random() < self.scale_amplitude_rate:
                data = self._scale_amplitude(data, rng)
            if rng.random() < self.pre_emphasis_rate:
                data = self._pre_emphasis(data, self.pre_emphasis_ratio)
            if rng.random() < self.add_noise_rate:
                data = self._add_noise(data, rng)
            if rng.random() < self.add_gap_rate:
                data = self._add_gaps(data, ppks, spks, rng)

        if self.mask_percent > 0:
            data = self._add_mask_windows(
                data, self.mask_percent, self.sampling_rate // 2, rng
            )
        if self.noise_percent > 0:
            data = self._add_noise_windows(
                data, self.noise_percent, self.sampling_rate // 2, rng
            )

        event.update({"data": data, "ppks": ppks, "spks": spks})
        return event

    # ---------------------------------------------------------------- process
    def process(
        self,
        event: Event,
        augmentation: bool,
        rng: Optional[np.random.Generator] = None,
        inplace: bool = True,
    ) -> Event:
        """Full preprocessing of one event (ref: preprocess.py:501-542)."""
        if rng is None:
            # detlint: disable=unseeded-rng -- interactive-use fallback
            # only: every det-path caller (pipeline, pack, repick)
            # threads a Generator seeded from the run's root seed.
            rng = np.random.default_rng()
        if not inplace:
            event = copy.deepcopy(event)

        if self._is_noise(event["data"], event["ppks"], event["spks"], event["snr"]):
            self._clear_event_except(event, "data")

        event["ppks"], event["spks"] = pad_phases(
            event["ppks"], event["spks"], self.min_event_gap, self.in_samples
        )

        if augmentation:
            event = self._data_augmentation(event, rng)

        event["data"], event["ppks"], event["spks"] = self._cut_window(
            event["data"], event["ppks"], event["spks"], self.in_samples, rng
        )

        event["data"] = self._normalize(event["data"], self.norm_mode)
        return event

    # ------------------------------------------------------------- soft labels
    def _soft_window(self, soft_label_width: int, soft_label_shape: str) -> np.ndarray:
        """The (width+1)-sample label window (ref: preprocess.py:571-601).

        Cached per (width, shape): the window is identical for every call
        in a run and sits on the per-sample hot path."""
        key = (soft_label_width, soft_label_shape)
        window = self._window_cache.get(key)
        if window is None:
            window = self._window_cache[key] = self._make_soft_window(
                soft_label_width, soft_label_shape
            )
        return window

    def _make_soft_window(
        self, soft_label_width: int, soft_label_shape: str
    ) -> np.ndarray:
        return make_soft_window(soft_label_width, soft_label_shape)

    def _soft_label(
        self, idxs, length: int, soft_label_width: int, soft_label_shape: str
    ) -> np.ndarray:
        """Place label windows at phase indices (ref: preprocess.py:567-619)."""
        slabel = np.zeros(length)
        if len(idxs) > 0:
            left = int(soft_label_width / 2)
            right = soft_label_width - left
            window = self._soft_window(soft_label_width, soft_label_shape)

            from seist_tpu import native

            if native.soft_label_add(
                slabel, np.asarray(idxs, dtype=np.int64), window, soft_label_width
            ):
                return slabel
            for idx in idxs:
                if idx < 0:
                    pass  # out of range
                elif idx - left < 0:
                    slabel[: idx + right + 1] += window[
                        soft_label_width + 1 - (idx + right + 1) :
                    ]
                elif idx + right <= length - 1:
                    slabel[idx - left : idx + right + 1] += window
                elif idx <= length - 1:
                    slabel[-(length - (idx - left)) :] += window[: length - (idx - left)]
                else:
                    pass  # out of range
        return slabel

    def _generate_soft_label(
        self,
        name: str,
        event: Event,
        soft_label_width: Optional[int] = None,
        soft_label_shape: Optional[str] = None,
    ) -> np.ndarray:
        """Generate one soft io-item (ref: preprocess.py:544-683)."""
        width = soft_label_width or self.soft_label_width
        shape = soft_label_shape or self.soft_label_shape
        length = event["data"].shape[-1]

        def _clip(x: int) -> int:
            return min(max(x, 0), length)

        def _padded_phases():
            # Padded lists are used by 'non' and 'det' only; 'ppk'/'spk'
            # use the raw event lists (ref: preprocess.py:621-631).
            return pad_phases(
                ppks=event["ppks"],
                spks=event["spks"],
                padding_idx=width,
                num_samples=length,
            )

        if name in ("ppk", "spk"):
            key = {"ppk": "ppks", "spk": "spks"}[name]
            label = self._soft_label(event[key], length, width, shape)

        elif name == "non":
            ppks, spks = _padded_phases()
            label = (
                np.ones(length)
                - self._soft_label(ppks, length, width, shape)
                - self._soft_label(spks, length, width, shape)
            )
            label[label < 0] = 0

        elif name == "det":
            ppks, spks = _padded_phases()
            label = np.zeros(length)
            assert len(ppks) == len(spks)
            for ppk, spk in zip(ppks, spks):
                dst = ppk
                det = int(spk + self.coda_ratio * (spk - ppk))
                label_i = self._soft_label([dst, det], length, width, shape)
                label_i[_clip(dst) : _clip(det)] = 1.0
                label += label_i
            label[label > 1] = 1.0

        elif name in ("ppk+", "spk+"):
            label = np.zeros(length)
            key = {"ppk+": "ppks", "spk+": "spks"}[name]
            phases = event[key]
            for st in phases:
                label_i = self._soft_label([st], length, width, shape)
                label_i[_clip(st) :] = 1.0
                label += label_i / len(phases)

        elif name in self.data_channels:
            label = event["data"][self.data_channels.index(name)]

        elif name in [f"d{c}" for c in self.data_channels]:
            channel_data = event["data"][self.data_channels.index(name[-1])]
            label = np.zeros_like(channel_data)
            label[1:] = np.diff(channel_data)

        else:
            raise NotImplementedError(f"Unsupported label name: '{name}'")

        return label.astype(self.dtype)

    # ------------------------------------------------------------- io assembly
    def get_io_item(
        self,
        name: Union[str, tuple, list],
        event: Event,
        soft_label_width: Optional[int] = None,
        soft_label_shape: Optional[str] = None,
    ):
        """Build one io-item; groups stack channels-last to ``(L, C)``
        (the reference stacks channels-first, preprocess.py:714-717)."""
        if isinstance(name, (tuple, list)):
            # Fast path for the dominant case (waveform group == dataset
            # channel order, e.g. ("z","n","e")): a transpose VIEW of the
            # already-processed (C, L) array — the copy happens once at
            # batch assembly (_stack) instead of per sample here.
            if tuple(name) == tuple(self.data_channels):
                return event["data"].T.astype(self.dtype, copy=False)
            children = [self.get_io_item(sub, event) for sub in name]
            return np.stack(children, axis=-1)

        kind = taskspec.get_kind(name)
        if kind == taskspec.SOFT:
            return self._generate_soft_label(
                name, event, soft_label_width, soft_label_shape
            )
        if kind == taskspec.VALUE:
            return np.asarray(event[name]).astype(self.dtype)
        if kind == taskspec.ONEHOT:
            cidx = event[name]
            if not len(cidx) > 0:
                raise ValueError(f"Item:{name}, Value:{cidx}")
            nc = taskspec.get_num_classes(name)
            return np.eye(nc)[cidx[0]].astype(np.int64)
        raise NotImplementedError(f"Unknown item: {name}")

    def get_inputs(self, event: Event, input_names: Sequence):
        """Model inputs (ref: preprocess.py:806-821)."""
        inputs = [self.get_io_item(name, event) for name in input_names]
        return tuple(inputs) if len(inputs) > 1 else inputs[0]

    def get_targets_for_loss(self, event: Event, label_names: Sequence):
        """Loss targets (ref: preprocess.py:744-759)."""
        targets = [self.get_io_item(name, event) for name in label_names]
        return tuple(targets) if len(targets) > 1 else targets[0]

    def get_targets_for_metrics(
        self, event: Event, max_event_num: int, task_names: Sequence[str]
    ) -> Dict[str, np.ndarray]:
        """Metrics targets (ref: preprocess.py:761-804)."""
        targets: Dict[str, np.ndarray] = {}
        for name in task_names:
            if name in ("ppk", "spk"):
                key = {"ppk": "ppks", "spk": "spks"}[name]
                tgt = self.get_io_item(key, event)
                tgt = pad_array(tgt, max_event_num, int(-1e7)).astype(np.int64)
            elif name == "det":
                padded_ppks, padded_spks = pad_phases(
                    event["ppks"],
                    event["spks"],
                    self.soft_label_width,
                    self.in_samples,
                )
                detections: List[int] = []
                for ppk, spk in zip(padded_ppks, padded_spks):
                    st = int(np.clip(ppk, 0, self.in_samples))
                    et = int(spk + self.coda_ratio * (spk - ppk))
                    detections.extend([st, et])
                expected_num = self.expected_det_num()
                if len(detections) // 2 < expected_num:
                    detections = detections + [1, 0] * (
                        expected_num - len(detections) // 2
                    )
                tgt = np.array(detections).astype(np.int64)
            else:
                tgt = self.get_io_item(name, event)
            targets[name] = tgt
        return targets

    def expected_det_num(self) -> int:
        """Number of detection-interval slots in metrics targets
        (ref: preprocess.py:793)."""
        return (
            self._max_event_num
            + int(bool(self.add_event_rate))
            + int(bool(self.shift_event_rate))
            + int(0 <= self.p_position_ratio <= 1)
        )
