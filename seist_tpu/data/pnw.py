"""PNW dataset reader (ref datasets/pnw.py:23-201).

Curated Pacific Northwest AI-ready Seismic Dataset [Ni et al. 2023,
doi:10.26443/seismica.v2i1.368]: ComCat CSV metadata + bucketed HDF5
waveforms, 3-channel 100 Hz, channel order ``["e", "n", "z"]``. Quirks:

* trace refs are ``"bucket$n,:c,:l"`` — bucket dataset name plus the row
  index into it (ref pnw.py:102-104);
* P polarity maps positive/negative/undecidable/"" -> 0/1/2/3
  (ref pnw.py:131);
* ``trace_snr_db`` is a '|'-separated triple, NaN entries -> 0
  (ref pnw.py:136-138); NaNs in waveforms are zeroed (ref pnw.py:110).
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np
import pandas as pd

from seist_tpu.data import io_guard
from seist_tpu.data.base import DatasetBase, Event, evict_h5, open_h5
from seist_tpu.data.io_guard import CorruptSampleError
from seist_tpu.registry import register_dataset


def parse_trace_name(trace_name: str) -> Tuple[str, int]:
    """``"bucket3$42,:3,:15001"`` -> ("bucket3", 42) (ref pnw.py:102-104)."""
    bucket, array = trace_name.split("$")
    n = int(array.split(",:")[0])
    return bucket, n


class PNW(DatasetBase):
    _name = "pnw"
    _part_range = None
    _channels = ["e", "n", "z"]
    _sampling_rate = 100

    _meta_filename = "comcat_metadata.csv"

    def _load_meta_data(self) -> pd.DataFrame:
        meta_df = pd.read_csv(
            os.path.join(self._data_dir, self._meta_filename), low_memory=False
        )
        # Dtype-kind checks, not `== object`: pandas >= 3 infers text
        # columns as the dedicated `str` dtype, which is not `object` —
        # the NaN->"" fill (empty polarity cells!) and space-strip must
        # still run there (ref pnw.py normalization + polarity "" key).
        for k in meta_df.columns:
            if pd.api.types.is_numeric_dtype(meta_df[k]):
                meta_df[k] = meta_df[k].fillna(0)
            elif pd.api.types.is_string_dtype(
                meta_df[k]
            ) or meta_df[k].dtype == object:
                meta_df[k] = meta_df[k].str.replace(" ", "").fillna("")
        return self._shuffle_and_split(meta_df)

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        row = self._row_dict(idx)
        bucket, n = parse_trace_name(row["trace_name"])

        path = os.path.join(self._data_dir, "comcat_waveforms.hdf5")
        # Same classification as the DiTing reader: OSError = transient
        # (evict so the retry reopens); missing bucket / out-of-range row
        # = permanent corruption of this sample's reference.
        try:
            f = open_h5(path)
            node = f.get(f"data/{bucket}")
            if node is None:
                raise CorruptSampleError(
                    f"pnw: bucket dataset 'data/{bucket}' missing"
                )
            raw = np.array(node[n], dtype=np.float32)
            # Reference parity (ref pnw.py:110): sparse NaNs are zeroed,
            # NOT quarantined — this masking predates the io_guard and is
            # how the reference trains on PNW. A trace that is MOSTLY
            # non-finite is rotted, though, and zero-filling it would
            # manufacture a silent all-zeros sample; classify that as
            # permanent corruption before the repair. Gated on the guard:
            # SEIST_IO_GUARD=0 restores the raw reference behavior
            # (zero-fill and train) instead of introducing a new crash.
            finite = np.isfinite(raw)
            if (
                io_guard.enabled()
                and not finite.all()
                and finite.mean() < 0.5
            ):
                raise CorruptSampleError(
                    f"pnw: trace {row['trace_name']!r} is "
                    f"{100 * (1 - finite.mean()):.0f}% non-finite"
                )
            data = np.nan_to_num(raw)
        except OSError:
            evict_h5(path)
            raise
        except (IndexError, ValueError) as e:  # row n outside the bucket
            raise CorruptSampleError(
                f"pnw: bad trace ref {row['trace_name']!r} ({e})"
            ) from e

        mag_type = str(row["preferred_source_magnitude_type"]).lower()
        if mag_type != "ml":
            # Deliberately NOT sample-corruption: a non-ml magnitude type
            # means the wrong catalog was pointed at — fail the run.
            raise AssertionError(f"PNW magnitudes must be ml, got '{mag_type}'")
        # Undecodable per-row metadata (a polarity word outside the map, a
        # garbage snr cell) is sample corruption to quarantine, not a bug
        # to crash/preempt-loop on.
        try:
            motion = {"positive": 0, "negative": 1, "undecidable": 2, "": 3}[
                str(row["trace_P_polarity"]).lower()
            ]
            evmag = np.clip(row["preferred_source_magnitude"], 0, 8).astype(
                np.float32
            )
            snrs = [s.strip() for s in str(row["trace_snr_db"]).split("|")]
            snr = np.array([float(s) if s != "nan" else 0.0 for s in snrs])
        except (KeyError, ValueError, TypeError) as e:
            raise CorruptSampleError(
                f"pnw: undecodable metadata for {row['trace_name']!r} ({e})"
            ) from e

        ppk = row["trace_P_arrival_sample"]
        spk = row["trace_S_arrival_sample"]
        event: Event = {
            "data": data,
            "ppks": [ppk] if pd.notnull(ppk) else [],
            "spks": [spk] if pd.notnull(spk) else [],
            "emg": [evmag] if pd.notnull(evmag) else [],
            "pmp": [motion],
            "clr": [0],  # compatibility with other datasets (ref pnw.py:146)
            "snr": snr,
        }
        return event, row


class PNWLight(PNW):
    """PNW with undecidable-polarity events removed (ref pnw.py:153-188)."""

    _name = "pnw_light"
    _meta_filename = "comcat_metadata_light.csv"


@register_dataset
def pnw(**kwargs):
    return PNW(**kwargs)


@register_dataset
def pnw_light(**kwargs):
    return PNWLight(**kwargs)
