"""Resilient data-plane I/O: retries, corrupt-sample quarantine, stall watchdog.

PR 2 made the *step loop* self-healing; this module does the same for the
data plane that feeds it. Production TPU stacks read training data from
network filesystems where transient faults are routine — one truncated
HDF5 part, one NaN-filled trace, or one wedged loader thread must not
take down (or silently hang) a days-long run. Three mechanisms, each
independently testable (tests/test_io_guard.py, tests/test_data_plane_chaos.py):

* **Retry with exponential backoff + jitter** (:func:`read_with_retry`)
  around every sample read. Faults are classified: *transient*
  (``OSError`` — flaky NFS, stale h5py handle; the reader evicts the
  cached handle so the retry reopens) vs *permanent*
  (:class:`CorruptSampleError` — short read, bad shape, non-finite data).
  Transients that outlive the retry budget are promoted to permanent
  (:class:`RetriesExhaustedError`).
* **Corrupt-sample quarantine** (:class:`Quarantine`): a permanently-bad
  sample index is benched and *deterministically replaced* by a fallback
  index drawn from a PRNG keyed by ``(seed, epoch, idx)`` — batch shapes
  and the global sample sequence (``pipeline.epoch_indices``) stay fixed
  and resume-stable; the replacement does not depend on worker scheduling
  or discovery order (the candidate sequence is deterministic and a
  candidate is accepted iff it itself reads cleanly). Past a configurable
  quarantined fraction the run aborts loudly
  (:class:`QuarantineOverflowError`) instead of training on garbage.
* **Pipeline stall watchdog** (:class:`StallWatchdog` + :func:`watch`):
  armed while the train loop is blocked waiting for the next batch (so
  step compute / compiles / validation never count against the budget);
  if no batch arrives for ``timeout_s`` it dumps every thread's stack and
  exits with the clean-preempt code so ``tools/supervise.py`` relaunches
  from the last checkpoint instead of the run hanging forever. A loader
  worker thread dying surfaces as :class:`LoaderDeathError`, which the
  train worker converts into the same checkpoint-then-preempt exit.

Counters (reads/retries/reopens/quarantined/fallbacks/stalls) accumulate
in :data:`COUNTERS`; they surface through worker epoch logs,
``ops.metrics.data_plane_counters()`` and the BENCH ``data_plane``
section (bench.py). The guard is on by default; ``SEIST_IO_GUARD=0`` (or
the :func:`disabled` context manager) restores the raw read path — the
clean-path overhead is a try/except plus one ``np.isfinite`` pass per
sample (benched at well under 2% of loader stage time).

Fault injection for all three mechanisms lives in
``seist_tpu/utils/faults.py`` (``SEIST_FAULT_IO_*``).
"""

from __future__ import annotations

import contextlib
import logging
import os
import random
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from seist_tpu.utils.logger import logger

# Keep in sync with seist_tpu.train.checkpoint.PREEMPT_EXIT_CODE (pinned
# by tests/test_io_guard.py; importing train.checkpoint here would pull
# orbax into every data-plane import).
PREEMPT_EXIT_CODE = 75


# --------------------------------------------------------------- fault taxonomy
class CorruptSampleError(Exception):
    """Permanent per-sample fault: the bytes came back but the sample is
    unusable (short read, wrong shape/dtype, non-finite values, missing
    trace key). Never retried — the sample gets quarantined."""


class RetriesExhaustedError(CorruptSampleError):
    """A transient fault outlived the retry budget. Treated like
    corruption from the quarantine's point of view: the sample is benched
    and replaced so the run keeps its shape contract."""


class QuarantineOverflowError(RuntimeError):
    """Quarantined fraction crossed ``max_frac``: the dataset is rotted
    (or the fault classification is wrong) and silently training on
    fallback samples would be worse than dying. Crashes the run — this is
    NOT converted into a preempt/relaunch."""


class LoaderDeathError(RuntimeError):
    """A loader worker raised something that is neither transient nor
    per-sample corruption (i.e. a bug or an environment failure the retry
    ladder cannot absorb). The train worker turns this into a
    checkpoint + clean-preempt exit rather than an opaque crash."""


# ------------------------------------------------------------------- counters
class Counters:
    """Thread-safe monotonic counters for the data-plane guard."""

    _FIELDS = (
        "reads",
        "retries",
        "reopens",
        "quarantined",
        "fallback_reads",
        "stall_trips",
        "loader_deaths",
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._v: Dict[str, int] = {k: 0 for k in self._FIELDS}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._v[name] = self._v.get(name, 0) + n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._v)

    def reset(self) -> None:
        with self._lock:
            for k in self._v:
                self._v[k] = 0

    def any_faults(self) -> bool:
        s = self.snapshot()
        return any(v for k, v in s.items() if k != "reads")


COUNTERS = Counters()


# ------------------------------------------------------------- enable/disable
_ENABLED = os.environ.get("SEIST_IO_GUARD", "1") != "0"


def enabled() -> bool:
    return _ENABLED


@contextlib.contextmanager
def disabled():
    """Bypass the guard (raw reads, no validation) — bench.py uses this to
    price the clean-path overhead; not intended for production runs."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = False
    try:
        yield
    finally:
        _ENABLED = prev


# ------------------------------------------------------------------ retry core
class RetryPolicy:
    """Exponential backoff with jitter: attempt k sleeps
    ``min(base * 2**k, cap) * uniform(0.5, 1.5)``. Jitter decorrelates a
    thread-pool's retries after a shared-filesystem hiccup (every loader
    thread fails at once; synchronized retries would hammer the server in
    lockstep). The jitter only shapes *sleep time* — it never touches
    sample content, so determinism contracts are unaffected."""

    def __init__(
        self,
        attempts: Optional[int] = None,
        backoff_base_s: Optional[float] = None,
        backoff_cap_s: Optional[float] = None,
    ) -> None:
        env = os.environ
        self.attempts = max(
            1,
            int(attempts if attempts is not None
                else env.get("SEIST_IO_RETRIES", 3)),
        )
        self.backoff_base_s = float(
            backoff_base_s if backoff_base_s is not None
            else env.get("SEIST_IO_BACKOFF_MS", 50)
        ) / (1.0 if backoff_base_s is not None else 1000.0)
        self.backoff_cap_s = float(
            backoff_cap_s if backoff_cap_s is not None
            else env.get("SEIST_IO_BACKOFF_CAP_MS", 2000)
        ) / (1.0 if backoff_cap_s is not None else 1000.0)

    def sleep_s(self, attempt: int) -> float:
        base = min(self.backoff_base_s * (2.0 ** attempt), self.backoff_cap_s)
        # detlint: disable=unseeded-rng -- jitter shapes SLEEP TIME only
        # (retry decorrelation after a shared-fs hiccup needs it to be
        # uncorrelated across threads); it never touches sample content.
        return base * random.uniform(0.5, 1.5)


_DEFAULT_POLICY: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    global _DEFAULT_POLICY
    if _DEFAULT_POLICY is None:
        _DEFAULT_POLICY = RetryPolicy()
    return _DEFAULT_POLICY


def read_with_retry(
    fn: Callable[[], Any],
    *,
    desc: str = "read",
    fault_key: int = -1,
    injector=None,
    policy: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Call ``fn`` with transient-fault retries.

    * ``OSError`` -> counted, backed off, retried (readers evict stale
      h5py handles / memmaps before raising, so the retry reopens);
      exhausted retries raise :class:`RetriesExhaustedError`.
    * :class:`CorruptSampleError` -> re-raised immediately (permanent).
    * anything else -> re-raised immediately (a bug is not a fault to
      absorb).

    ``injector``/``fault_key`` hook the chaos harness in: the injected
    flaky failure fires *inside* the retry loop, exactly where a real
    flaky filesystem would.
    """
    policy = policy or default_policy()
    COUNTERS.inc("reads")
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            if injector is not None:
                injector.maybe_flaky_read(fault_key, attempt)
            return fn()
        except CorruptSampleError:
            raise
        except OSError as e:
            last = e
            COUNTERS.inc("retries")
            if attempt + 1 < policy.attempts:
                logger.warning(
                    f"[io-guard] transient fault on {desc} "
                    f"(attempt {attempt + 1}/{policy.attempts}): {e!r}; "
                    "retrying"
                )
                sleep(policy.sleep_s(attempt))
    raise RetriesExhaustedError(
        f"{desc} still failing after {policy.attempts} attempts: {last!r}"
    ) from last


def guarded_event_read(
    fn: Callable[[], Any],
    *,
    key: int,
    desc: str,
    injector=None,
) -> Any:
    """The ONE classification ladder for a sample read, shared by the
    host path (``SeismicDataset._fetch_event_slow``) and the device-aug
    ingest (``pipeline._guarded_raw_event``): transient retries
    (:func:`read_with_retry`, with injected flakiness riding the loop),
    the injected-corruption hook, then ingest validation. ``fn`` returns
    ``(event, meta)``; any permanent fault surfaces as
    :class:`CorruptSampleError` — each caller keeps only its distinct
    fallback policy (quarantine vs refusal)."""
    event, meta = read_with_retry(fn, desc=desc, fault_key=key, injector=injector)
    if injector is not None and injector.is_corrupt(key):
        raise CorruptSampleError(f"[faults] injected corrupt sample {key}")
    validate_event(event, desc=desc)
    return event, meta


# ------------------------------------------------------------------ validation
def validate_event(event: Any, *, desc: str = "sample") -> None:
    """Ingest validation: the permanent-fault classifier for a decoded
    Event dict. Raises :class:`CorruptSampleError` on a missing/empty/
    non-numeric/non-finite waveform or a non-2D shape; anything that
    passes here is safe to hand to the preprocessor.

    Runs once per sample on the clean fast path, so the checks are kept
    deliberately lean: one attribute walk plus (for float data) a single
    ``np.isfinite`` pass — a few microseconds against a loader stage
    measured in hundreds (the BENCH ``data_plane`` section prices it)."""
    try:
        data = event["data"]
    except (TypeError, KeyError, IndexError):
        raise CorruptSampleError(f"{desc}: event has no 'data' field") from None
    if type(data) is not np.ndarray:
        data = np.asarray(data)
    kind = data.dtype.kind
    if kind not in "fiu":
        raise CorruptSampleError(
            f"{desc}: non-numeric waveform dtype {data.dtype}"
        )
    if data.ndim != 2:
        raise CorruptSampleError(
            f"{desc}: waveform must be (C, L), got shape {data.shape}"
        )
    if data.shape[-1] == 0 or data.shape[0] == 0:
        raise CorruptSampleError(f"{desc}: empty waveform {data.shape}")
    if kind == "f" and not np.isfinite(data).all():
        bad = int(data.size - np.isfinite(data).sum())
        raise CorruptSampleError(
            f"{desc}: waveform has {bad} non-finite value(s)"
        )


# ------------------------------------------------------------------ quarantine
_FALLBACK_SALT = 0x5E15_7  # keys the fallback PRNG stream apart from others


class Quarantine:
    """Registry of benched raw sample indices + the deterministic
    replacement rule.

    ``candidates(raw, seed=, epoch=, idx=)`` yields the read order for
    one logical sample: the sample itself first, then fallback draws from
    ``default_rng(SeedSequence([seed, epoch, idx, salt]))``. The caller
    accepts the first candidate that reads cleanly and quarantines the
    ones that don't — so the accepted replacement is a pure function of
    (seed, epoch, idx) and the set of *actually corrupt* samples,
    independent of discovery order, worker scheduling, or resume point.

    ``add`` raises :class:`QuarantineOverflowError` once more than
    ``max_frac`` of the dataset is benched.
    """

    MAX_DRAWS = 64  # fallback draws per logical sample before giving up

    def __init__(self, n_total: int, max_frac: float = 0.05) -> None:
        if n_total <= 0:
            raise ValueError(f"n_total must be positive, got {n_total}")
        self.n_total = int(n_total)
        self.max_frac = float(max_frac)
        self._lock = threading.Lock()
        self._bad: Dict[int, str] = {}
        # Lock-free hot-path hint: False until the first add(). The clean
        # path checks this plain bool (atomic under the GIL) instead of
        # taking the lock per sample.
        self.active = False

    def __contains__(self, raw_idx: int) -> bool:
        with self._lock:
            return int(raw_idx) in self._bad

    def __len__(self) -> int:
        with self._lock:
            return len(self._bad)

    def add(self, raw_idx: int, reason: str) -> None:
        with self._lock:
            if int(raw_idx) in self._bad:
                return
            self._bad[int(raw_idx)] = str(reason)
            n_bad = len(self._bad)
            self.active = True
        COUNTERS.inc("quarantined")
        logger.warning(
            f"[io-guard] quarantined sample {raw_idx} "
            f"({n_bad}/{self.n_total}): {reason}"
        )
        limit = self.max_frac * self.n_total
        if n_bad > limit:
            # The overflow crashes the run (deliberately NOT a preempt);
            # leave the forensic record first — which samples, why, when.
            _flight_dump(
                "quarantine_overflow", quarantined=n_bad, n_total=self.n_total
            )
            raise QuarantineOverflowError(
                f"{n_bad}/{self.n_total} samples quarantined exceeds "
                f"--max-quarantine-frac {self.max_frac}: the dataset is "
                "rotted; refusing to keep training on fallback samples"
            )

    def candidates(
        self, raw_idx: int, *, seed: int, epoch: int, idx: int
    ) -> Iterator[int]:
        if raw_idx not in self:
            yield int(raw_idx)
        rng = np.random.default_rng(
            np.random.SeedSequence(
                [int(seed), int(epoch), int(idx), _FALLBACK_SALT]
            )
        )
        for _ in range(self.MAX_DRAWS):
            cand = int(rng.integers(self.n_total))
            if cand == raw_idx or cand in self:
                continue
            yield cand

    # The owning SeismicDataset is pickled into process-pool loader
    # workers; locks don't pickle, so ship the plain state. Each worker
    # process then quarantines independently — the deterministic
    # fallback rule keeps the CONTENT identical across workers (a
    # candidate is accepted iff it reads cleanly, and the corrupt set is
    # a property of the data, not of the process), but the parent's
    # epoch-end report only covers thread-pool loaders.
    def __getstate__(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "n_total": self.n_total,
                "max_frac": self.max_frac,
                "bad": dict(self._bad),
            }

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__init__(state["n_total"], state["max_frac"])
        self._bad.update(state["bad"])
        self.active = bool(self._bad)

    def report(self) -> Dict[str, Any]:
        """JSON-able epoch-end report (logged by the train worker)."""
        with self._lock:
            bad = dict(self._bad)
        return {
            "quarantined": sorted(bad),
            "reasons": {str(k): bad[k] for k in sorted(bad)},
            "n_total": self.n_total,
            "frac": round(len(bad) / self.n_total, 6),
            "max_frac": self.max_frac,
        }


# ------------------------------------------------------------- stall watchdog
def _flight_dump(reason: str, **fields) -> None:
    """Best-effort flight-recorder dump (obs/flight.py) on a death path.
    A no-op when no recorder is installed (library use outside the train
    worker) and never raises — the exit matters more than the artifact."""
    try:
        from seist_tpu.obs import flight

        flight.dump_on_death(reason, **fields)
    except Exception:  # noqa: BLE001 - death path; the exit must proceed
        pass


def hard_exit(code: int) -> None:
    """Flush log handlers and ``os._exit``. The only safe exit when
    non-daemon data-plane threads may be wedged: ``sys.exit`` would hang
    forever in ``threading._shutdown`` joining a pool thread stuck
    inside a dead read — the exact hang this module exists to eliminate.
    A separate function so in-process tests can monkeypatch it.

    Dumps the flight recorder first (docs/OBSERVABILITY.md): this is the
    funnel every hard death path drains through, so the dump happens even
    when the caller forgot (deduped when the caller already dumped a
    richer record seconds ago)."""
    _flight_dump("hard_exit", dedup_s=5.0, exit_code=code)
    logging.shutdown()
    os._exit(code)


def dump_thread_stacks(to=None) -> str:
    """Format every live thread's stack (the post-mortem a hung loader
    never gives you) — logged AND returned."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in frames.items():
        header = f"--- thread {names.get(ident, '?')} ({ident}) ---"
        chunks.append(header + "\n" + "".join(traceback.format_stack(frame)))
    text = "\n".join(chunks)
    stream = to if to is not None else sys.stderr
    try:
        print(text, file=stream, flush=True)
    # The dump is best-effort post-mortem output on a process that is
    # about to exit; a broken stderr must not mask the preempt exit.
    except Exception:
        pass
    try:
        logger.error(f"[io-guard] thread stacks at stall:\n{text}")
    except Exception:  # noqa: BLE001 - same best-effort contract as above
        pass
    return text


class StallWatchdog:
    """Background thread that trips when the consumer has been *armed*
    (blocked waiting for a batch) longer than ``timeout_s``.

    Armed/disarmed around each ``next()`` by :func:`watch`, so device
    step time, jit compiles, validation, and checkpoint saves never count
    toward the budget — only actual time spent waiting on the data plane
    does. On trip: dump all thread stacks, flush, and hard-exit with the
    clean-preempt code (``os._exit`` — a wedged loader may hold arbitrary
    locks, so a cooperative exit could itself hang; tools/supervise.py
    relaunches from the newest checkpoint). ``exit_fn`` is injectable for
    tests.
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        exit_code: int = PREEMPT_EXIT_CODE,
        exit_fn: Optional[Callable[[int], None]] = None,
        poll_s: Optional[float] = None,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.exit_code = int(exit_code)
        self._exit_fn = exit_fn if exit_fn is not None else hard_exit
        self._poll_s = (
            float(poll_s) if poll_s else max(min(self.timeout_s / 4, 5.0), 0.01)
        )
        self._armed_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.tripped = False

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="seist-data-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._poll_s)
            self._thread = None

    def arm(self) -> None:
        self._armed_since = time.monotonic()

    def disarm(self) -> None:
        self._armed_since = None

    def _run(self) -> None:
        # A watchdog that dies silently IS the failure it guards against:
        # the stall it would have caught then hangs the run forever. Log
        # loudly and re-raise (threadlint thread-target-raises).
        try:
            while not self._stop.wait(self._poll_s):
                armed = self._armed_since
                if armed is None:
                    continue
                waited = time.monotonic() - armed
                if waited > self.timeout_s:
                    self._trip(waited)
                    return
        except Exception:
            logger.exception(
                "[io-guard] stall watchdog thread died — stall protection "
                "is GONE for the rest of this run"
            )
            raise

    def _trip(self, waited: float) -> None:
        self.tripped = True
        COUNTERS.inc("stall_trips")
        logger.error(
            f"[io-guard] pipeline stall: no batch for {waited:.1f}s "
            f"(timeout {self.timeout_s}s); dumping thread stacks and "
            f"exiting {self.exit_code} for supervised relaunch"
        )
        stacks = dump_thread_stacks()
        # Explicit dump here (hard_exit would also fire one) so the stall
        # record carries the thread stacks and wait time even when a test
        # injects a custom exit_fn.
        _flight_dump("stall_watchdog", waited_s=round(waited, 1),
                     thread_stacks=stacks)
        # The default exit_fn is hard_exit (logging.shutdown + os._exit):
        # every registered handler flushes, so the stall post-mortem is
        # durable before the process dies.
        self._exit_fn(self.exit_code)


def watch(
    iterator,
    watchdog: Optional[StallWatchdog],
    on_death: Optional[Callable[[LoaderDeathError], None]] = None,
):
    """Wrap a batch iterator so the watchdog is armed exactly while
    blocked in ``next()``. ``watchdog=None`` is a passthrough for the
    arming (the wrapper stays in place so call sites need no branching).
    ``on_death`` fires when the data plane raises
    :class:`LoaderDeathError` — the train worker uses it to checkpoint
    and preempt-exit at the exact batch position reached."""
    if watchdog is None and on_death is None:
        yield from iterator
        return
    it = iter(iterator)
    while True:
        if watchdog is not None:
            watchdog.arm()
        try:
            item = next(it)
        except StopIteration:
            return
        except LoaderDeathError as e:
            if on_death is not None:
                on_death(e)
            raise
        finally:
            if watchdog is not None:
                watchdog.disarm()
        yield item
