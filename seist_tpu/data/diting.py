"""DiTing dataset reader (ref datasets/diting.py:23-324).

DiTing [Zhao et al. 2023, doi:10.1016/j.eqs.2022.01.022]: 28 CSV+HDF5 parts,
3-channel 50 Hz waveforms. Format quirks preserved from the reference:

* trace keys are ``<evid>.<suffix>`` zero-padded to 6/4 digits before the
  HDF5 lookup (ref diting.py:136-137);
* magnitudes are converted to ML — ms: (m+1.08)/1.13, mb: (1.17m+0.67)/1.13 —
  then clipped to [0, 8] (ref diting.py:183-197);
* polarity u/c -> 0, r/d -> 1; clarity 'i' -> 0 else 1; baz %= 360
  (ref diting.py:174-181).
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np
import pandas as pd

from seist_tpu.data.base import DatasetBase, Event, evict_h5, open_h5
from seist_tpu.data.io_guard import CorruptSampleError
from seist_tpu.registry import register_dataset

_META_DTYPES = {
    "part": np.int64,
    "key": str,
    "ev_id": np.int64,
    "mag_type": str,
    "p_pick": np.int64,
    "p_clarity": str,
    "p_motion": str,
    "s_pick": np.int64,
    "net": str,
    "sta_id": np.int64,
    "dis": np.float32,
    **{
        f"{c}_{ph}_{kind}_snr": np.float32
        for c in "ZNE"
        for ph in "PS"
        for kind in ("amplitude", "power")
    },
}


def convert_to_ml(mag: float, mag_type: str) -> float:
    """Magnitude-type conversion to ML (ref diting.py:183-197)."""
    mag_type = mag_type.lower()
    if mag_type == "ms":
        return (mag + 1.08) / 1.13
    if mag_type == "mb":
        return (1.17 * mag + 0.67) / 1.13
    if mag_type == "ml":
        return mag
    raise ValueError(f"Unknown 'mag_type' : '{mag_type}'")


def normalize_key(key: str) -> str:
    """Zero-pad the two halves of a DiTing trace key (ref diting.py:136-137)."""
    head, tail = key.split(".")
    return head.rjust(6, "0") + "." + tail.ljust(4, "0")


class DiTing(DatasetBase):
    _name = "diting"
    _part_range = (0, 28)  # (inclusive, exclusive)
    _channels = ["z", "n", "e"]
    _sampling_rate = 50

    # In the full release evmag/st_mag/baz arrive as strings with stray
    # spaces (ref diting.py:62-72 dtype map + :95-97 space strip).
    _string_numeric_cols = ("evmag", "st_mag", "baz")

    def _read_csvs(self) -> pd.DataFrame:
        start, end = self._part_range
        dtypes = dict(_META_DTYPES)
        for col in self._string_numeric_cols:
            dtypes[col] = str
        dtypes.update({"P_residual": str, "S_residual": str})
        frames = [
            pd.read_csv(
                os.path.join(self._data_dir, f"DiTing330km_part_{i}.csv"),
                dtype=dtypes,
                low_memory=False,
                index_col=0,
            )
            for i in range(start, end)
        ]
        return pd.concat(frames)

    def _load_meta_data(self) -> pd.DataFrame:
        meta_df = self._read_csvs()
        # Dtype-kind check, not `== object`: pandas >= 3 infers text columns
        # as the `str` dtype (not `object`), and the stray-space strip
        # (ref diting.py:95-97) must still run for them.
        for k in meta_df.columns:
            if pd.api.types.is_string_dtype(
                meta_df[k]
            ) or meta_df[k].dtype == object:
                meta_df[k] = meta_df[k].str.replace(" ", "")
        return self._shuffle_and_split(meta_df)

    def _load_event_data(self, idx: int) -> Tuple[Event, dict]:
        row = self._row_dict(idx)
        key = normalize_key(str(row["key"]))
        path = os.path.join(self._data_dir, f"DiTing330km_part_{row['part']}.hdf5")

        # Fault classification (data/io_guard.py): an OSError anywhere in
        # the open/lookup/decode is transient — evict the cached handle so
        # the pipeline-level retry reopens instead of re-hitting a stale
        # fd; a missing trace key or a broken file layout is permanent
        # (CorruptSampleError -> quarantine).
        try:
            grp = open_h5(path, group="earthquake")
            node = grp.get(key)
            if node is None:
                raise CorruptSampleError(
                    f"diting part {row['part']}: trace key {key!r} missing"
                )
            data = np.array(node).astype(np.float32).T
        except OSError:
            evict_h5(path)
            raise
        except KeyError as e:  # no 'earthquake' group: structurally broken
            raise CorruptSampleError(
                f"diting part {row['part']}: bad file layout ({e})"
            ) from e

        # Metadata decode is part of the sample read: an undecodable row
        # (unknown polarity letter, garbage magnitude string, unknown
        # mag_type) is per-sample corruption to quarantine, not a bug to
        # crash (or preempt-relaunch-loop) the run on.
        try:
            motion = row["p_motion"]
            if pd.notnull(motion) and str(motion).lower() not in ("", "n"):
                motion = {"u": 0, "c": 0, "r": 1, "d": 1}[str(motion).lower()]
            clarity = row["p_clarity"]
            if pd.notnull(clarity):
                clarity = 0 if str(clarity).lower() == "i" else 1
            baz = row["baz"]
            if pd.notnull(baz):
                baz = float(baz) % 360

            evmag, stmag = row["evmag"], row["st_mag"]
            if pd.notnull(evmag):
                evmag = np.clip(
                    convert_to_ml(float(evmag), row["mag_type"]), 0, 8
                ).astype(np.float32)
            if pd.notnull(stmag):
                stmag = np.clip(
                    convert_to_ml(float(stmag), row["mag_type"]), 0, 8
                ).astype(np.float32)
        except (KeyError, ValueError, TypeError) as e:
            raise CorruptSampleError(
                f"diting: undecodable metadata for trace {key!r} ({e})"
            ) from e

        snr = np.array(
            [row["Z_P_power_snr"], row["N_S_power_snr"], row["E_S_power_snr"]]
        )
        event: Event = {
            "data": data,
            "ppks": [row["p_pick"]] if pd.notnull(row["p_pick"]) else [],
            "spks": [row["s_pick"]] if pd.notnull(row["s_pick"]) else [],
            "emg": [evmag] if pd.notnull(row["evmag"]) else [],
            "smg": [stmag] if pd.notnull(row["st_mag"]) else [],
            "pmp": [motion] if pd.notnull(motion) else [],
            "clr": [clarity] if pd.notnull(clarity) else [],
            "baz": [baz] if pd.notnull(baz) else [],
            "dis": [row["dis"]] if pd.notnull(row["dis"]) else [],
            "snr": snr,
        }
        return event, row


class DiTingLight(DiTing):
    """Single-CSV "light" release with numeric columns (ref diting.py:217-311)."""

    _name = "diting_light"
    _part_range = None
    _string_numeric_cols = ()

    def _read_csvs(self) -> pd.DataFrame:
        dtypes = dict(_META_DTYPES)
        dtypes.update(
            {
                "evmag": np.float32,
                "st_mag": np.float32,
                "baz": np.float32,
                "P_residual": np.float32,
                "S_residual": np.float32,
            }
        )
        return pd.read_csv(
            os.path.join(self._data_dir, "DiTing330km_light.csv"),
            dtype=dtypes,
            low_memory=False,
            index_col=0,
        )


@register_dataset
def diting(**kwargs):
    return DiTing(**kwargs)


@register_dataset
def diting_light(**kwargs):
    return DiTingLight(**kwargs)
