"""Datasets + preprocessing. Importing this package registers all datasets."""

from seist_tpu.data.preprocess import DataPreprocessor, pad_array, pad_phases  # noqa: F401
