"""Datasets + preprocessing. Importing this package registers all datasets."""

from seist_tpu.data.preprocess import DataPreprocessor, pad_array, pad_phases  # noqa: F401
from seist_tpu.data.base import DatasetBase  # noqa: F401
from seist_tpu.data import diting, packed, pnw, sos, synthetic  # noqa: F401  (registration)
from seist_tpu.data.pipeline import (  # noqa: F401
    Batch,
    Loader,
    SeismicDataset,
    from_task_spec,
    prefetch_to_device,
)
