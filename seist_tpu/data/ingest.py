"""Direct shard->device ingest: packed shards -> staging batch -> HBM.

The final hop of the packed data plane. The host Loader path spends its
per-sample budget on Event-dict assembly, numpy preprocessing and
``_stack`` batch assembly; the device-aug 'step' path already moved the
preprocessing on-device but still pays a full :class:`RawStore` upload —
every waveform decoded through the Event reader into a resident host
array. On a packed dataset BOTH costs are artifacts of the format
conversion: the shard file *is* the contiguous float32 batch source.

:class:`PackedRawStore` therefore feeds the device-aug step path straight
from the shards:

* **build** is metadata-only — phases/labels come from the columnar
  index (the same ``host_prepare`` row contract, vectorized over the
  index; parity is test-pinned against ``RawStore.build``), no waveform
  is decoded and host RAM stays O(index), not O(dataset);
* **row_batch_at** slices each sample's bytes out of the per-shard
  ``np.memmap`` directly into a preallocated staging batch — ONE memcpy
  per sample from page cache to the slab ``prefetch_raw_to_device``
  hands to ``jax.device_put``; no per-sample Event dict, no ``_stack``,
  no intermediate numpy copies;
* **io_guard parity** — every row fill runs the same fault ladder as the
  HDF5 readers (data/io_guard.py): transient ``OSError`` retried with
  the memmap re-mapped, short reads / NaN-poisoned waveforms / injected
  ``SEIST_FAULT_IO_*`` faults quarantined and deterministically replaced
  via the dataset's shared :class:`~seist_tpu.data.io_guard.Quarantine`
  (fallbacks keyed ``(seed, epoch, logical idx)`` — resume-stable), so
  the worker's epoch-end quarantine report covers this path too;
* **accounting** — ``data_ingest_batches/samples/bytes`` counters and a
  ``data_ingest_fill`` span on the bus; the bounded prefetch queue's
  backpressure lands in ``data_ingest_backpressure_s`` (pipeline.py).

Staging reuse: on accelerator backends ``device_put`` always copies
host->HBM, so a small ring of staging slabs is recycled. On the CPU
backend jax may *alias* host memory into the device array, so reuse is
disabled there (a recycled slab would corrupt an in-flight batch) —
``SEIST_INGEST_REUSE_STAGING=0/1`` overrides the auto choice.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from seist_tpu import taskspec
from seist_tpu.data import io_guard
from seist_tpu.data.packed import (
    INT8_POISON,
    PackedDataset,
    read_waveform_slice,
)
from seist_tpu.data.pipeline import RawStore, SeismicDataset
from seist_tpu.data.preprocess import pad_phases

# Invalid phase-slot sentinel — MUST match device_aug._BIG (the device
# kernels treat it as "no phase"); re-declared to keep this module free
# of the jax import device_aug pulls.
_BIG = 2**30

_SCALAR = ("ppks", "spks", "emg", "smg", "pmp", "clr", "baz", "dis")


def packed_dataset_of(sds: SeismicDataset) -> Optional[PackedDataset]:
    """The underlying :class:`PackedDataset` when ``sds`` reads packed
    shards, else None — the direct-ingest eligibility check."""
    ds = getattr(sds, "_dataset", None)
    return ds if isinstance(ds, PackedDataset) else None


class PackedRawStore(RawStore):
    """A :class:`RawStore` whose waveforms stay on disk: the small
    per-sample arrays (phases, values, onehots) are resident, the
    ``data`` rows are filled per batch straight from the shard memmaps.
    Duck-compatible with ``pipeline.iter_raw_batches`` /
    ``prefetch_raw_to_device`` / the device-aug step train path."""

    def __init__(
        self,
        arrays: Dict[str, Any],
        *,
        n_raw: int,
        augmentation: bool,
        raw_len: int,
        phase_slots: int,
        n_ch: int,
        data_dir: str,
        shards: np.ndarray,
        offsets: np.ndarray,
        seed: int,
        quarantine: io_guard.Quarantine,
        injector=None,
        batch_size: int = 0,
        prefetch: int = 2,
        reuse_staging: Optional[bool] = None,
        storage_dtype: Optional[np.dtype] = None,
        scales: Optional[np.ndarray] = None,
        stage_raw: bool = False,
    ) -> None:
        # On-disk dtype (bf16 shard variants halve the read bandwidth,
        # int8 v3 shards quarter it); default fills dequant/upcast into
        # the float32 staging slab so everything downstream of the fill
        # stays dtype-blind. ``stage_raw`` (int8 only) instead stages
        # the int8 rows AS-IS — one memcpy, no host widening — plus a
        # resident per-row ``data_scale`` column; the consuming device
        # program dequantizes (the repick engine's int8 end-to-end
        # path; bytes stay 4x narrow across the host->device transfer).
        self.storage_dtype = (
            np.dtype(storage_dtype)
            if storage_dtype is not None
            else np.dtype(np.float32)
        )
        self.stage_raw = bool(stage_raw)
        if self.stage_raw and self.storage_dtype != np.int8:
            raise ValueError(
                "stage_raw staging is the int8 device-dequant path; "
                f"this pack stores {self.storage_dtype}"
            )
        if self.storage_dtype == np.int8:
            if scales is None:
                raise ValueError(
                    "int8 packs need the per-row scale sidecar columns "
                    "(scale_0..); this index has none — repack (v3)"
                )
            scales = np.ascontiguousarray(scales, np.float32)
            if self.stage_raw:
                # Resident like the labels so the quarantine-fallback
                # tree-gather (a[actual]) keeps row<->scale consistent.
                arrays = dict(arrays)
                arrays["data_scale"] = scales
        super().__init__(
            arrays,
            n_raw=n_raw,
            augmentation=augmentation,
            raw_len=raw_len,
            phase_slots=phase_slots,
        )
        self.n_ch = int(n_ch)
        self._scales = scales
        self.row_nbytes = self.n_ch * self.raw_len * self.storage_dtype.itemsize
        self._data_dir = data_dir
        self._shards = np.asarray(shards, np.int64)
        self._offsets = np.asarray(offsets, np.int64)
        self._seed = int(seed)
        self._quarantine = quarantine
        self._injector = injector
        self._injector_enabled = bool(getattr(injector, "enabled", False))
        self._mmaps: Dict[int, np.memmap] = {}
        if reuse_staging is None:
            env = os.environ.get("SEIST_INGEST_REUSE_STAGING", "auto")
            if env in ("0", "1"):
                reuse_staging = env == "1"
            else:
                import jax

                # CPU device_put may alias host memory into the device
                # array; recycling the slab would then corrupt the batch
                # still referenced by the in-flight step.
                reuse_staging = jax.default_backend() != "cpu"
        self._reuse = bool(reuse_staging) and batch_size > 0
        self._batch_size = int(batch_size)
        self._staging_dtype = (
            np.dtype(np.int8) if self.stage_raw else np.dtype(np.float32)
        )
        self._ring: List[np.ndarray] = (
            [
                np.empty(
                    (self._batch_size, self.n_ch, self.raw_len),
                    self._staging_dtype,
                )
                # one slab filling + `prefetch` queued + one in the step
                for _ in range(prefetch + 2)
            ]
            if self._reuse
            else []
        )
        self._ring_i = 0
        from seist_tpu.obs.bus import BUS

        self._c_batches = BUS.counter("data_ingest_batches")
        self._c_samples = BUS.counter("data_ingest_samples")
        self._c_bytes = BUS.counter("data_ingest_bytes")
        self._c_int8 = BUS.counter("data_ingest_int8_rows")

    # ------------------------------------------------------------- build
    @classmethod
    def build(
        cls,
        sds: SeismicDataset,
        *,
        batch_size: int = 0,
        prefetch: int = 2,
        reuse_staging: Optional[bool] = None,
        stage_raw: bool = False,
    ) -> "PackedRawStore":
        """Metadata-only construction from a packed-backed
        :class:`SeismicDataset`. Mirrors ``RawStore.build``'s row
        contract (``host_prepare``) and its refusal semantics — every
        refusal raises ``ValueError`` so the worker falls back to the
        host path. No waveform is read."""
        ds = packed_dataset_of(sds)
        if ds is None:
            raise ValueError(
                "direct ingest requires a packed dataset "
                "(--dataset-name packed; see docs/DATA.md)"
            )
        pre = sds.preprocessor
        frame = ds._meta_data
        n = len(ds)
        if n == 0:
            raise ValueError("empty packed split")
        col = {c: frame[c].to_numpy() for c in frame.columns}
        n_ch_col, n_samp_col = col["n_ch"], col["n_samp"]
        if (n_ch_col != n_ch_col[0]).any() or (
            n_samp_col != n_samp_col[0]
        ).any():
            raise ValueError(
                "direct ingest needs uniform raw trace shapes; this pack "
                "mixes them"
            )
        n_ch, raw_len = int(n_ch_col[0]), int(n_samp_col[0])

        scales = None
        if ds.storage_dtype == np.int8:
            missing = [
                f"scale_{c}" for c in range(n_ch) if f"scale_{c}" not in col
            ]
            if missing:
                raise ValueError(
                    "int8 packs need the per-row scale sidecar columns "
                    f"({', '.join(missing)}); this index has none — "
                    "repack (format v3)"
                )
            scales = np.stack(
                [col[f"scale_{c}"] for c in range(n_ch)], axis=1
            ).astype(np.float32)

        names = taskspec.flatten_io_names(
            sds.input_names + sds.label_names
        )
        value_names = sorted(
            {m for m in names if taskspec.get_kind(m) == taskspec.VALUE}
        )
        onehot_names = sorted(
            {m for m in names if taskspec.get_kind(m) == taskspec.ONEHOT}
        )

        snr = np.stack(
            [col["snr_0"], col["snr_1"], col["snr_2"]], axis=1
        )
        # data only feeds _is_noise's shape check; one zero-size proxy
        # with the right trailing dim serves every row.
        shape_proxy = np.empty((0, raw_len), np.float32)

        def row_phases(i):
            p, s = col["ppks"][i], col["spks"][i]
            ppks = [] if p != p else [int(p)]
            spks = [] if s != s else [int(s)]
            if pre._is_noise(shape_proxy, ppks, spks, snr[i]):
                return [], [], True
            pp, ss = pad_phases(
                ppks, spks, pre.min_event_gap, pre.in_samples
            )
            return pp, ss, False

        # One pass over the metadata (it IS the build cost here — there
        # is no per-sample decode to hide behind); phase_slots is sized
        # from the cached results exactly like RawStore.build.
        phases = [row_phases(i) for i in range(n)]
        max_phases = max(
            [1]
            + [max(len(pp), len(ss)) for pp, ss, noise in phases if not noise]
        )
        phase_slots = max(max_phases, pre._max_event_num)

        arrays: Dict[str, Any] = {
            "ppks": np.full((n, phase_slots), _BIG, np.int32),
            "np_p": np.empty((n,), np.int32),
            "spks": np.full((n, phase_slots), _BIG, np.int32),
            "np_s": np.empty((n,), np.int32),
        }
        vals = {m: np.zeros((n, 1), np.float32) for m in value_names}
        oh = {m: np.zeros((n,), np.int32) for m in onehot_names}
        for i, (pp, ss, is_noise) in enumerate(phases):
            arrays["ppks"][i, : len(pp)] = pp
            arrays["np_p"][i] = len(pp)
            arrays["spks"][i, : len(ss)] = ss
            arrays["np_s"][i] = len(ss)
            if is_noise and (value_names or onehot_names):
                # Same refusal as RawStore.build: never fabricate
                # VALUE/ONEHOT labels for a noise-classified trace.
                raise ValueError(
                    f"sample {i} is noise-classified but the task has "
                    f"VALUE/ONEHOT labels "
                    f"({value_names + onehot_names}); the device path "
                    "will not fabricate label values for it"
                )
            for m in value_names:
                v = col[m][i]
                if v != v:  # NaN = absent; host path crashes at stacking
                    raise ValueError(
                        f"sample {i} has no '{m}' value; refusing to "
                        "fabricate a device-path label"
                    )
                vals[m][i] = np.float32(v)
            for m in onehot_names:
                v = col[m][i]
                if v != v:
                    raise ValueError(
                        f"sample {i} has no '{m}' class; refusing to "
                        "fabricate a device-path label"
                    )
                oh[m][i] = int(v)
        if value_names:
            arrays["values"] = vals
        if onehot_names:
            arrays["onehots"] = oh
        return cls(
            arrays,
            n_raw=n,
            augmentation=sds.augmentation,
            raw_len=raw_len,
            phase_slots=phase_slots,
            n_ch=n_ch,
            data_dir=ds._data_dir,
            shards=col["shard"],
            offsets=col["offset"],
            seed=sds._seed,
            quarantine=sds.quarantine,
            injector=sds.io_faults,
            batch_size=batch_size,
            prefetch=prefetch,
            reuse_staging=reuse_staging,
            storage_dtype=ds.storage_dtype,
            scales=scales,
            stage_raw=stage_raw,
        )

    # ---------------------------------------------------------- raw read
    def _read_into(self, out: np.ndarray, r: int, validate: bool) -> None:
        """Fill ``out`` (C, L) with raw sample ``r`` — the one memcpy of
        the fast path. Fault classification (transient OSError with
        memmap evict vs permanent short-read corruption) is the shared
        :func:`~seist_tpu.data.packed.read_waveform_slice` ladder; a
        non-finite waveform is permanent corruption too."""
        raw = read_waveform_slice(
            self._mmaps,
            self._data_dir,
            int(self._shards[r]),
            int(self._offsets[r]),
            self.row_nbytes,
            desc=f"packed.direct (sample {r})",
        )
        row = np.frombuffer(raw, self.storage_dtype).reshape(
            self.n_ch, self.raw_len
        )
        if self.storage_dtype == np.int8:
            # int8 can't hold NaN: corruption is the out-of-contract
            # -128 byte (the symmetric quantizer emits [-127, 127]
            # only) or a non-finite sidecar scale.
            if validate:
                if (row == INT8_POISON).any():
                    bad = int((row == INT8_POISON).sum())
                    raise io_guard.CorruptSampleError(
                        f"packed.direct: int8 sample {r} has {bad} "
                        f"poison byte(s) ({INT8_POISON})"
                    )
                if not np.isfinite(self._scales[r]).all():
                    raise io_guard.CorruptSampleError(
                        f"packed.direct: int8 sample {r} has a "
                        "non-finite dequant scale"
                    )
            if self.stage_raw:
                out[...] = row  # bytes stay narrow; device dequantizes
            else:
                out[...] = row
                out *= self._scales[r][:, None]
            return
        # Cast-assignment upcasts bf16 shard variants in place (no
        # intermediate copy); f32 packs keep the plain memcpy.
        out[...] = row
        if validate and not np.isfinite(out).all():
            bad = int(out.size - np.isfinite(out).sum())
            raise io_guard.CorruptSampleError(
                f"packed.direct: sample {r} has {bad} non-finite value(s)"
            )

    def _fill_row(self, out: np.ndarray, raw: int, *, epoch: int, key: int) -> int:
        """Guarded fill of one staging row; returns the index actually
        read (== ``raw`` unless a quarantine fallback replaced it) so the
        caller gathers the matching phase/label rows."""
        if not io_guard.enabled():
            self._read_into(out, raw, validate=False)
            return raw
        if not (self._quarantine.active or self._injector_enabled):
            try:
                self._read_into(out, raw, validate=True)
                io_guard.COUNTERS.inc("reads")
                return raw
            except (OSError, io_guard.CorruptSampleError):
                pass  # enter the retrying/quarantining ladder below
        for cand in self._quarantine.candidates(
            raw, seed=self._seed, epoch=epoch, idx=key
        ):
            try:
                io_guard.read_with_retry(
                    lambda c=cand: self._read_into(out, c, validate=True),
                    desc=f"packed.direct[{cand}]",
                    fault_key=cand,
                    injector=self._injector,
                )
                if self._injector is not None and self._injector.is_corrupt(
                    cand
                ):
                    raise io_guard.CorruptSampleError(
                        f"[faults] injected corrupt sample {cand}"
                    )
            except io_guard.CorruptSampleError as e:
                self._quarantine.add(cand, repr(e))
                continue
            if cand != raw:
                io_guard.COUNTERS.inc("fallback_reads")
            return cand
        raise io_guard.CorruptSampleError(
            f"no clean fallback found for packed sample {raw} "
            f"(quarantined: {len(self._quarantine)}/{self.n_raw})"
        )

    # --------------------------------------------------------- batch fill
    def _staging(self, batch: int) -> np.ndarray:
        if not self._reuse:
            return np.empty(
                (batch, self.n_ch, self.raw_len), self._staging_dtype
            )
        buf = self._ring[self._ring_i]
        self._ring_i = (self._ring_i + 1) % len(self._ring)
        return buf[:batch]

    def row_batch_at(
        self,
        raw_idx: np.ndarray,
        *,
        epoch: int = 0,
        idx: Optional[np.ndarray] = None,
    ) -> Dict[str, Any]:
        """Fill one staging batch straight from the shards and gather the
        matching resident rows. ``idx`` (the logical epoch indices) keys
        quarantine fallbacks exactly like the host path."""
        import jax

        from seist_tpu.obs.bus import BUS

        raw_idx = np.asarray(raw_idx)
        batch = int(raw_idx.shape[0])
        if self._reuse and batch > self._batch_size:
            raise ValueError(
                f"batch {batch} exceeds the staging ring's {self._batch_size}"
            )
        buf = self._staging(batch)
        actual = np.empty(batch, np.int64)
        with BUS.span("data_ingest_fill"):
            for j in range(batch):
                key = int(idx[j]) if idx is not None else int(raw_idx[j])
                actual[j] = self._fill_row(
                    buf[j], int(raw_idx[j]), epoch=int(epoch), key=key
                )
        rows = jax.tree.map(lambda a: a[actual], self.arrays)
        rows["data"] = buf
        self._c_batches.inc()
        self._c_samples.inc(batch)
        self._c_bytes.inc(batch * self.row_nbytes)
        if self.storage_dtype == np.int8:
            self._c_int8.inc(batch)
        return rows

    def row_batch(self, raw_idx: np.ndarray) -> Dict[str, Any]:
        return self.row_batch_at(raw_idx)

    @property
    def disk_bytes(self) -> int:
        """Waveform bytes that STAY on disk (the RawStore would hold
        these resident)."""
        return int(self.n_raw) * self.row_nbytes


def describe(store: PackedRawStore) -> str:
    return (
        f"packed direct ingest: {store.n_raw} samples, "
        f"{store.disk_bytes / 2**20:.1f} MiB on-disk waveforms, "
        f"{store.nbytes / 2**20:.2f} MiB resident metadata, "
        f"staging {'ring' if store._reuse else 'per-batch'} "
        f"({store.n_ch}x{store.raw_len} {store._staging_dtype.name} rows"
        + (", device dequant" if store.stage_raw else "")
        + ")"
    )
