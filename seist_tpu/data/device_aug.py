"""Device-side augmentation & soft-label synthesis — jit/vmap mirror of
the numpy :class:`~seist_tpu.data.preprocess.DataPreprocessor` hot path.

Why: bench r02-r04 all profiled the same shape — every training sample
crosses the host each step after *per-sample numpy* augmentation and
Python batch stacking, pinning the step at ~2.4% MFU with the chip idle
behind the input pipeline. This module moves the full train-time
preprocessing — window cut, event shift/add, noise-sample generation,
channel drop, amplitude scale, pre-emphasis, SNR noise, gaps,
normalization (signed-max / std semantics of ``preprocess.normalize``)
and soft-label curve synthesis — into the jitted train step, so the only
per-step host work left is (at most) a raw-row gather.

RNG contract (resume-stability)
-------------------------------
Every sample's randomness derives from ``(seed, epoch, index)`` only::

    key = fold_in(fold_in(PRNGKey(seed), epoch), index)

and each stochastic decision consumes a NAMED subkey
(``fold_in(key, TAG)``), never a positional stream. Named draws make the
consumption order-free: a sample is augmented identically whether it is
processed in step 3 of a fresh run or step 3 after a preempt/restore,
and independently of batch geometry, ``steps_per_call`` chunking, or
device count. (The host path's numpy analogue is
``default_rng(SeedSequence([seed, epoch, idx]))`` — same keying idea,
different generator, so host and device runs are each reproducible but
not bit-identical to each other.)

Golden parity
-------------
Integer draws are derived as ``low + min(floor(u * (high-low)),
high-low-1)`` computed in float32 on BOTH sides, so a device run's draws
can be replayed into the numpy ``DataPreprocessor`` exactly:
:func:`build_replay_script` walks the reference pipeline's documented
branch structure (preprocess.py:432-499 + 172-222) with the named draws
and emits the response queue a :class:`ScriptedRNG` feeds to
``DataPreprocessor.process`` — the golden parity suite
(tests/test_device_aug.py) asserts the device output matches the numpy
output within float tolerance, per-op and end-to-end.

Known tolerated deviations (documented, tested):

* float32 vs float64 accumulation order (normalize / SNR power) — rtol.
* coda boundaries ``int(spk + coda_ratio*(spk-ppk))`` are computed in
  f32 on device; a non-f32-exact ``coda_ratio`` (e.g. the reference's
  1.4) can land one sample off the f64 truncation near integer products.
* gate compares use f32 rates on device, f64 on host — divergence needs
  the drawn uniform to equal the rate's f32 rounding (p ~ 2^-24/gate).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from seist_tpu import taskspec
from seist_tpu.data.preprocess import (
    DataPreprocessor,
    make_soft_window,
    pad_phases,
)

# Invalid phase-slot sentinel: sorts after every real sample index.
_BIG = 2**30

# Named-draw tags (fold_in constants). Values are arbitrary but FROZEN:
# changing one silently re-randomizes every historical (seed, epoch, idx)
# augmentation stream.
_T_GEN_GATE = 1
_T_GEN_FIELD = 2
_T_ADD_GATE = 3
_T_ADD_TARGET = 4
_T_ADD_POS = 5
_T_ADD_SCALE = 6
_T_SHIFT_GATE = 7
_T_SHIFT = 8
_T_DROP_GATE = 9
_T_DROP_NUM = 10
_T_DROP_CH = 11
_T_SCALE_GATE = 12
_T_SCALE_FLIP = 13
_T_SCALE_FACTOR = 14
_T_PRE_GATE = 15
_T_NOISE_GATE = 16
_T_SNR = 17
_T_NOISE_FIELD = 18
_T_GAP_GATE = 19
_T_GAP_POS = 20
_T_GAP_START = 21
_T_GAP_END = 22
_T_CROP = 23

# SOFT io-items the device label synthesizer implements ('ppk+'/'spk+'
# and 'det+' are in the catalog but referenced by no model spec).
_SOFT_SUPPORTED = {"ppk", "spk", "non", "det"}


@dataclasses.dataclass(frozen=True)
class AugConfig:
    """Static (trace-time) configuration of the device pipeline. Field
    names/semantics match :class:`DataPreprocessor` constructor args."""

    seed: int
    window: int              # in_samples
    raw_len: int             # uniform raw trace length of the dataset
    channels: int
    phase_slots: int         # P: capacity of the phase arrays
    data_channels: Tuple[str, ...]
    sampling_rate: int
    norm_mode: str = "std"
    coda_ratio: float = 1.4
    min_event_gap: int = 0   # samples (DataPreprocessor.min_event_gap)
    max_event_num: int = 1
    add_event_rate: float = 0.0
    shift_event_rate: float = 0.0
    generate_noise_rate: float = 0.0
    drop_channel_rate: float = 0.0
    scale_amplitude_rate: float = 0.0
    pre_emphasis_rate: float = 0.0
    pre_emphasis_ratio: float = 0.97
    add_noise_rate: float = 0.0
    add_gap_rate: float = 0.0
    soft_label_shape: str = "gaussian"
    soft_label_width: int = 50

    @classmethod
    def from_preprocessor(
        cls,
        pre: DataPreprocessor,
        *,
        seed: int,
        raw_len: int,
        phase_slots: int,
    ) -> "AugConfig":
        return cls(
            seed=int(seed),
            window=int(pre.in_samples),
            raw_len=int(raw_len),
            channels=len(pre.data_channels),
            phase_slots=int(phase_slots),
            data_channels=tuple(pre.data_channels),
            sampling_rate=int(pre.sampling_rate),
            norm_mode=pre.norm_mode,
            coda_ratio=float(pre.coda_ratio),
            min_event_gap=int(pre.min_event_gap),
            max_event_num=int(pre._max_event_num),
            add_event_rate=float(pre.add_event_rate),
            shift_event_rate=float(pre.shift_event_rate),
            generate_noise_rate=float(pre.generate_noise_rate),
            drop_channel_rate=float(pre.drop_channel_rate),
            scale_amplitude_rate=float(pre.scale_amplitude_rate),
            pre_emphasis_rate=float(pre.pre_emphasis_rate),
            pre_emphasis_ratio=float(pre.pre_emphasis_ratio),
            add_noise_rate=float(pre.add_noise_rate),
            add_gap_rate=float(pre.add_gap_rate),
            soft_label_shape=pre.soft_label_shape,
            soft_label_width=int(pre.soft_label_width),
        )


# --------------------------------------------------------------------- draws
def sample_key(seed, epoch, idx) -> jax.Array:
    """Per-sample PRNG key — a pure function of (seed, epoch, idx)."""
    key = jax.random.PRNGKey(seed)
    key = jax.random.fold_in(key, epoch)
    return jax.random.fold_in(key, idx)


def _u2i(u, n):
    """``floor(u * n)`` clamped to ``[0, n-1]`` with the product computed
    in float32 — the ONE integer-draw formula shared (bit-exactly, via
    :func:`u2i_np`) with the host replay side."""
    n = jnp.asarray(n, jnp.int32)
    v = jnp.floor(u * n.astype(jnp.float32)).astype(jnp.int32)
    return jnp.minimum(v, n - 1)


def u2i_np(u, n: int) -> int:
    """Host mirror of :func:`_u2i` (same float32 product, same clamp)."""
    return min(int(np.float32(u) * np.float32(n)), int(n) - 1)


def draw_all(cfg: AugConfig, key: jax.Array) -> Dict[str, jax.Array]:
    """Every named draw of one sample, derived from its key. All uniforms
    are in [0, 1); fields are standard normal float32."""

    def u(tag, shape=()):
        return jax.random.uniform(
            jax.random.fold_in(key, tag), shape, jnp.float32
        )

    def norm(tag, shape):
        return jax.random.normal(
            jax.random.fold_in(key, tag), shape, jnp.float32
        )

    K = max(cfg.max_event_num, 1)
    C, L = cfg.channels, cfg.raw_len
    draws = {
        "gen_gate": u(_T_GEN_GATE),
        "add_gate": u(_T_ADD_GATE, (K,)),
        "add_target": u(_T_ADD_TARGET, (K,)),
        "add_pos": u(_T_ADD_POS, (K,)),
        "add_scale": u(_T_ADD_SCALE, (K,)),
        "shift_gate": u(_T_SHIFT_GATE),
        "shift_u": u(_T_SHIFT),
        "drop_gate": u(_T_DROP_GATE),
        "drop_num_u": u(_T_DROP_NUM),
        "drop_ch_u": u(_T_DROP_CH, (max(C - 1, 1),)),
        "scale_gate": u(_T_SCALE_GATE),
        "scale_flip": u(_T_SCALE_FLIP),
        "scale_factor_u": u(_T_SCALE_FACTOR),
        "pre_gate": u(_T_PRE_GATE),
        "noise_gate": u(_T_NOISE_GATE),
        "snr_u": u(_T_SNR, (C,)),
        "gap_gate": u(_T_GAP_GATE),
        "gap_pos_u": u(_T_GAP_POS),
        "gap_start_u": u(_T_GAP_START),
        "gap_end_u": u(_T_GAP_END),
        "crop_u": u(_T_CROP),
    }
    # The (C, L) normal fields are the expensive draws — only materialize
    # them when their op can actually fire (named keying means skipping
    # them cannot shift any other draw).
    if cfg.generate_noise_rate > 0:
        draws["gen_field"] = norm(_T_GEN_FIELD, (C, L))
    if cfg.add_noise_rate > 0:
        draws["noise_field"] = norm(_T_NOISE_FIELD, (C, L))
    return draws


# ----------------------------------------------------------------- phase ops
def _sorted_insert(vals, n, new):
    """Insert ``new`` at slot ``n`` of a sorted-valid-prefix array and
    re-sort (invalid slots hold _BIG and stay at the tail)."""
    P = vals.shape[0]
    return jnp.sort(jnp.where(jnp.arange(P) == n, new, vals))


def _coda_end(cfg: AugConfig, ppk, spk):
    """``int(spk + coda_ratio * (spk - ppk))`` — f32, trunc-toward-zero
    like python ``int()`` (astype truncates)."""
    v = spk.astype(jnp.float32) + jnp.float32(cfg.coda_ratio) * (
        spk - ppk
    ).astype(jnp.float32)
    return v.astype(jnp.int32)


# ------------------------------------------------------------- augment ops
def normalize(data, mode: str):
    """jnp mirror of ``preprocess.normalize`` (per-channel over the last
    axis): demean, then divide by the SIGNED max ('max' — the reference's
    training quirk), the std ('std'), or nothing ('')."""
    data = data - jnp.mean(data, axis=-1, keepdims=True)
    if mode == "":
        return data
    if mode == "max":
        scale = jnp.max(data, axis=-1, keepdims=True)
    elif mode == "std":
        scale = jnp.std(data, axis=-1, keepdims=True)
    else:
        raise ValueError(f"Supported modes: 'max', 'std', '', got '{mode}'")
    return data / jnp.where(scale == 0, 1.0, scale)


def generate_noise(cfg: AugConfig, data, ppks, np_p, spks, np_s, field):
    """Wipe every phase+coda span with white noise (ref preprocess.py:
    244-263). ``field`` is position-indexed: column ``t`` of the span gets
    ``field[:, t]`` — overlapping spans agree, matching numpy's sequential
    overwrite."""
    L = data.shape[-1]
    cols = jnp.arange(L)
    npair = jnp.minimum(np_p, np_s)
    for j in range(cfg.phase_slots):
        ppk, spk = ppks[j], spks[j]
        ce = jnp.clip(_coda_end(cfg, ppk, spk), 0, L)
        wipe = (j < npair) & (cols >= ppk) & (cols < ce)
        data = jnp.where(wipe[None, :], field, data)
    return data


def add_event_once(
    cfg: AugConfig, data, ppks, np_p, spks, np_s, u_t, u_pos, u_scale, active
):
    """One iteration of the event-duplication augment (ref preprocess.py:
    265-292): pick event ``floor(u_t * n)``, add a ``u_scale``-scaled copy
    at ``left + floor(u_pos * (right-left))`` when a slot exists."""
    L = data.shape[-1]
    j = _u2i(u_t, jnp.maximum(np_p, 1))
    ppk = jnp.take(ppks, j)
    spk = jnp.take(spks, j)
    ce = _coda_end(cfg, ppk, spk)
    left = ce + cfg.min_event_gap
    right = L - (spk - ppk) - cfg.min_event_gap
    fire = active & (np_p > 0) & (left < right)
    pos = left + _u2i(u_pos, jnp.maximum(right - left, 1))
    spk_add = pos + spk - ppk
    space = jnp.minimum(L - pos, ce - ppk)
    cols = jnp.arange(L)
    seg = (cols >= pos) & (cols < pos + space)
    rolled = jnp.roll(data, pos - ppk, axis=1)
    data = jnp.where(fire & seg[None, :], data + rolled * u_scale, data)
    ppks = jnp.where(fire, _sorted_insert(ppks, np_p, pos), ppks)
    spks = jnp.where(fire, _sorted_insert(spks, np_s, spk_add), spks)
    return data, ppks, np_p + fire, spks, np_s + fire


def shift_event(data, ppks, np_p, spks, np_s, shift):
    """Circular time shift (ref preprocess.py:294-305)."""
    L = data.shape[-1]
    P = ppks.shape[0]
    data = jnp.roll(data, shift, axis=1)
    ar = jnp.arange(P)

    def sh(vals, n):
        return jnp.sort(jnp.where(ar < n, (vals + shift) % L, _BIG))

    return data, sh(ppks, np_p), np_p, sh(spks, np_s), np_s


def drop_channel(data, u_num, u_ch):
    """Zero ``1 + floor(u_num*(C-1))`` channels, chosen sequentially from
    the ascending remaining-candidate list (ref preprocess.py:307-321)."""
    C = data.shape[0]
    if C < 2:
        return data
    drop_num = 1 + _u2i(u_num, C - 1)
    cand = jnp.ones((C,), bool)
    chans = jnp.arange(C)
    for i in range(C - 1):
        active = i < drop_num
        k = _u2i(u_ch[i], C - i)
        rank = jnp.cumsum(cand) - 1
        sel = jnp.argmax((rank == k) & cand)
        hit = active & (chans == sel)
        data = jnp.where(hit[:, None], 0.0, data)
        cand = cand & ~hit
    return data


def adjust_amplitude(data):
    """Post-drop rescale by C / nonzero-channel-count (ref 323-333)."""
    max_amp = jnp.max(jnp.abs(data), axis=1)
    nnz = jnp.sum(max_amp != 0)
    factor = jnp.where(
        nnz > 0, data.shape[0] / jnp.maximum(nnz, 1).astype(jnp.float32), 1.0
    )
    return data * factor


def scale_amplitude(data, u_flip, u_factor):
    """x/÷ U(1,3) amplitude scale (ref preprocess.py:335-344)."""
    factor = 1.0 + 2.0 * u_factor
    return jnp.where(u_flip < 0.5, data * factor, data / factor)


def pre_emphasis(data, ratio: float):
    """First-order pre-emphasis filter (ref preprocess.py:346-353)."""
    return jnp.concatenate(
        [data[:, :1], data[:, 1:] - ratio * data[:, :-1]], axis=1
    )


def add_noise(data, u_snr, field):
    """Per-channel gaussian noise at SNR ``10 + floor(u*40)`` dB
    (ref preprocess.py:355-368)."""
    L = data.shape[-1]
    snr = 10 + _u2i(u_snr, 40)
    px = jnp.sum(data**2, axis=1) / L
    pn = px * 10.0 ** (-snr.astype(jnp.float32) / 10.0)
    return data + field * jnp.sqrt(pn)[:, None]


def add_gaps(data, ppks, np_p, spks, np_s, u_pos, u_start, u_end):
    """Zero a random span between phases (ref preprocess.py:370-390):
    unique sorted phases + (L-1), pick an inter-phase interval, zero a
    random sub-span of it."""
    L = data.shape[-1]
    P = ppks.shape[0]
    ar = jnp.arange(P)
    vals = jnp.concatenate(
        [
            jnp.where(ar < np_p, ppks, _BIG),
            jnp.where(ar < np_s, spks, _BIG),
            jnp.array([L - 1], jnp.int32),
        ]
    )
    vals = jnp.sort(vals)
    # set()-dedup: mark repeats invalid, re-sort so uniques pack the front.
    dup = jnp.concatenate([jnp.array([False]), vals[1:] == vals[:-1]])
    uniq = jnp.sort(jnp.where(dup, _BIG, vals))
    n_u = jnp.sum(uniq < _BIG).astype(jnp.int32)
    has = (np_p + np_s) > 0

    ip = _u2i(u_pos, jnp.maximum(n_u - 1, 1))
    lo = jnp.take(uniq, ip)
    hi = jnp.take(uniq, jnp.minimum(ip + 1, uniq.shape[0] - 1))
    sgt_p = lo + _u2i(u_start, jnp.maximum(hi - lo, 1))
    egt_p = sgt_p + _u2i(u_end, jnp.maximum(hi - sgt_p, 1))

    sgt_n = _u2i(u_start, L - 1)
    egt_n = sgt_n + 1 + _u2i(u_end, jnp.maximum(L - 1 - sgt_n, 1))

    sgt = jnp.where(has, sgt_p, sgt_n)
    egt = jnp.where(has, egt_p, egt_n)
    cols = jnp.arange(L)
    return jnp.where(((cols >= sgt) & (cols < egt))[None, :], 0.0, data)


def cut_window(cfg: AugConfig, data, ppks, np_p, spks, np_s, u_crop):
    """Cut the raw trace to ``cfg.window`` (ref preprocess.py:172-222,
    random-crop branch; the p_position_ratio mode is host-only). Shorter
    traces are zero-padded; equal lengths pass through — both draw-free,
    exactly like numpy."""
    L, W, P = cfg.raw_len, cfg.window, cfg.phase_slots
    C = data.shape[0]
    if L == W:
        return data, ppks, np_p, spks, np_s
    if L < W:
        pad = jnp.zeros((C, W - L), data.dtype)
        return jnp.concatenate([data, pad], axis=1), ppks, np_p, spks, np_s
    ar = jnp.arange(P)
    min_ppk = jnp.min(jnp.where(ar < np_p, ppks, _BIG))
    bound = jnp.maximum(
        jnp.minimum(min_ppk, L - W) - cfg.min_event_gap, 1
    )
    c_l = _u2i(u_crop, bound)
    win = jax.lax.dynamic_slice(data, (0, c_l), (C, W))

    def cutp(vals, n):
        keep = (ar < n) & (vals >= c_l) & (vals < c_l + W)
        return (
            jnp.sort(jnp.where(keep, vals - c_l, _BIG)),
            jnp.sum(keep).astype(jnp.int32),
        )

    ppks2, np_p2 = cutp(ppks, np_p)
    spks2, np_s2 = cutp(spks, np_s)
    return win, ppks2, np_p2, spks2, np_s2


# ------------------------------------------------------------- soft labels
def pad_phases_dev(ppks, np_p, spks, np_s, padding_idx: int, num_samples):
    """Device mirror of ``preprocess.pad_phases`` positional pairing:
    returns 2P-slot arrays carrying the REAL sentinel values (-pad /
    num_samples+pad) plus the padded count."""
    P = ppks.shape[0]
    pad = abs(int(padding_idx))
    ar = jnp.arange(P)
    a, b = np_p, np_s
    # k = longest prefix with ppk[i] < spk[b-idx-1+i] for all i <= idx.
    cont = jnp.bool_(True)
    k = jnp.int32(0)
    for idx in range(P):
        sp_idx = jnp.clip(b - idx - 1 + ar, 0, P - 1)
        ok = jnp.all(
            jnp.where(ar <= idx, ppks < jnp.take(spks, sp_idx), True)
        )
        cont = cont & (idx < jnp.minimum(a, b)) & ok
        k = k + cont.astype(jnp.int32)
    n_lead = b - k            # sentinel ppks prepended
    n_tot = a + b - k
    i2 = jnp.arange(2 * P)
    ppks_pad = jnp.where(
        i2 < n_lead,
        -pad,
        jnp.take(ppks, jnp.clip(i2 - n_lead, 0, P - 1)),
    )
    spks_pad = jnp.where(
        i2 < b, jnp.take(spks, jnp.clip(i2, 0, P - 1)), num_samples + pad
    )
    return ppks_pad, spks_pad, n_tot


def soft_label_place(idxs, valid, window_arr, length: int):
    """Sum label windows centered at ``idxs`` (ref preprocess.py:567-619):
    out-of-range indices (idx < 0 or idx > length-1) contribute NOTHING
    (the reference skips them entirely, not partially); in-range windows
    are edge-cropped."""
    width = window_arr.shape[0] - 1
    left = width // 2
    off = width + 1
    buf = jnp.zeros((length + 2 * off,), jnp.float32)
    wf = window_arr.astype(jnp.float32)
    for j in range(idxs.shape[0]):
        idx = idxs[j]
        ok = valid[j] & (idx >= 0) & (idx <= length - 1)
        start = jnp.where(ok, idx - left + off, 0)
        seg = jax.lax.dynamic_slice(buf, (start,), (width + 1,))
        buf = jax.lax.dynamic_update_slice(
            buf, seg + jnp.where(ok, wf, 0.0), (start,)
        )
    return buf[off : off + length]


def label_pick(cfg: AugConfig, vals, n, window_arr):
    """'ppk' / 'spk' soft label from the raw phase list."""
    valid = jnp.arange(cfg.phase_slots) < n
    return soft_label_place(vals, valid, window_arr, cfg.window)


def label_non(cfg: AugConfig, ppks, np_p, spks, np_s, window_arr):
    """'non' = 1 - soft(padded ppks) - soft(padded spks), clipped at 0."""
    W = cfg.window
    pp, ss, n_tot = pad_phases_dev(
        ppks, np_p, spks, np_s, cfg.soft_label_width, W
    )
    valid = jnp.arange(pp.shape[0]) < n_tot
    lbl = (
        1.0
        - soft_label_place(pp, valid, window_arr, W)
        - soft_label_place(ss, valid, window_arr, W)
    )
    return jnp.maximum(lbl, 0.0)


def label_det(cfg: AugConfig, ppks, np_p, spks, np_s, window_arr):
    """'det': per padded pair, soft windows at (ppk, coda-end) plus a 1.0
    fill over [clip(ppk), clip(coda-end)); summed and clipped at 1."""
    W = cfg.window
    pp, ss, n_tot = pad_phases_dev(
        ppks, np_p, spks, np_s, cfg.soft_label_width, W
    )
    cols = jnp.arange(W)
    label = jnp.zeros((W,), jnp.float32)
    for j in range(pp.shape[0]):
        ok = j < n_tot
        dst = pp[j]
        det = _coda_end(cfg, dst, ss[j])
        li = soft_label_place(
            jnp.stack([dst, det]),
            jnp.stack([ok, ok]),
            window_arr,
            W,
        )
        fill = ok & (cols >= jnp.clip(dst, 0, W)) & (cols < jnp.clip(det, 0, W))
        li = jnp.where(fill, 1.0, li)
        label = label + li
    return jnp.minimum(label, 1.0)


# ------------------------------------------------------------- composition
def process_event(cfg: AugConfig, data, ppks, np_p, spks, np_s, draws, augment):
    """Full train-time preprocessing of ONE event: augmentation (when
    ``augment``), window cut, normalization. Input phase arrays are the
    post-``_is_noise``/``pad_phases`` state the upload precomputed
    (both are draw-free and static per raw sample).

    Returns ``dict(win, ppks, np_p, spks, np_s, gen_fired)`` with ``win``
    the normalized ``(C, window)`` waveform and window-relative phases.
    """
    augment = jnp.asarray(augment, bool)

    def gate(name, rate):
        return augment & (draws[name] < jnp.float32(rate))

    # Every op below is guarded by a TRACE-time `cfg.rate > 0` check:
    # rates are static, so a disabled op costs nothing in the compiled
    # program (XLA cannot fold `u < 0.0` selects away by itself, and the
    # (C, L) noise fields in particular are real work). Named draw keying
    # makes the elision stream-invariant for the enabled ops.

    # -- generate-noise branch (ref 418-425): wipe, clear, drop?, scale?
    if cfg.generate_noise_rate > 0:
        gen_fired = gate("gen_gate", cfg.generate_noise_rate)
        gdata = generate_noise(
            cfg, data, ppks, np_p, spks, np_s, draws["gen_field"]
        )
        if cfg.drop_channel_rate > 0:
            g_drop = gate("drop_gate", cfg.drop_channel_rate)
            gd = adjust_amplitude(
                drop_channel(gdata, draws["drop_num_u"], draws["drop_ch_u"])
            )
            gdata = jnp.where(g_drop, gd, gdata)
        if cfg.scale_amplitude_rate > 0:
            g_scale = gate("scale_gate", cfg.scale_amplitude_rate)
            gdata = jnp.where(
                g_scale,
                scale_amplitude(
                    gdata, draws["scale_flip"], draws["scale_factor_u"]
                ),
                gdata,
            )
    else:
        gen_fired = jnp.zeros((), bool)

    # -- regular branch (ref 426-444): add*, shift?, drop?, scale?, pre?,
    # noise?, gap?
    e, epp, enp, ess, ens = data, ppks, np_p, spks, np_s
    n0 = np_p
    if cfg.add_event_rate > 0:
        for i in range(cfg.max_event_num):
            act = (
                augment
                & (i < cfg.max_event_num - n0)
                & (draws["add_gate"][i] < jnp.float32(cfg.add_event_rate))
            )
            e, epp, enp, ess, ens = add_event_once(
                cfg, e, epp, enp, ess, ens,
                draws["add_target"][i], draws["add_pos"][i],
                draws["add_scale"][i], act,
            )
    if cfg.shift_event_rate > 0:
        sh_fire = gate("shift_gate", cfg.shift_event_rate)
        shift = _u2i(draws["shift_u"], cfg.raw_len)
        se, sepp, _, sess, _ = shift_event(e, epp, enp, ess, ens, shift)
        e = jnp.where(sh_fire, se, e)
        epp = jnp.where(sh_fire, sepp, epp)
        ess = jnp.where(sh_fire, sess, ess)
    if cfg.drop_channel_rate > 0:
        d_fire = gate("drop_gate", cfg.drop_channel_rate)
        de = adjust_amplitude(
            drop_channel(e, draws["drop_num_u"], draws["drop_ch_u"])
        )
        e = jnp.where(d_fire, de, e)
    if cfg.scale_amplitude_rate > 0:
        s_fire = gate("scale_gate", cfg.scale_amplitude_rate)
        e = jnp.where(
            s_fire,
            scale_amplitude(e, draws["scale_flip"], draws["scale_factor_u"]),
            e,
        )
    if cfg.pre_emphasis_rate > 0:
        p_fire = gate("pre_gate", cfg.pre_emphasis_rate)
        e = jnp.where(p_fire, pre_emphasis(e, cfg.pre_emphasis_ratio), e)
    if cfg.add_noise_rate > 0:
        n_fire = gate("noise_gate", cfg.add_noise_rate)
        e = jnp.where(
            n_fire, add_noise(e, draws["snr_u"], draws["noise_field"]), e
        )
    if cfg.add_gap_rate > 0:
        gp_fire = gate("gap_gate", cfg.add_gap_rate)
        e = jnp.where(
            gp_fire,
            add_gaps(
                e, epp, enp, ess, ens,
                draws["gap_pos_u"], draws["gap_start_u"], draws["gap_end_u"],
            ),
            e,
        )

    # -- branch select (non-augmented samples fall through untouched:
    # every gate above is &augment).
    if cfg.generate_noise_rate > 0:
        data = jnp.where(gen_fired, gdata, e)
        big = jnp.full_like(ppks, _BIG)
        ppks = jnp.where(gen_fired, big, epp)
        spks = jnp.where(gen_fired, big, ess)
        np_p = jnp.where(gen_fired, 0, enp)
        np_s = jnp.where(gen_fired, 0, ens)
    else:
        data, ppks, spks, np_p, np_s = e, epp, ess, enp, ens

    win, ppks, np_p, spks, np_s = cut_window(
        cfg, data, ppks, np_p, spks, np_s, draws["crop_u"]
    )
    win = normalize(win, cfg.norm_mode)
    return {
        "win": win,
        "ppks": ppks,
        "np_p": np_p,
        "spks": spks,
        "np_s": np_s,
        "gen_fired": gen_fired,
    }


def _soft_item(cfg: AugConfig, name: str, proc, window_arr):
    if name == "ppk":
        return label_pick(cfg, proc["ppks"], proc["np_p"], window_arr)
    if name == "spk":
        return label_pick(cfg, proc["spks"], proc["np_s"], window_arr)
    if name == "non":
        return label_non(
            cfg, proc["ppks"], proc["np_p"], proc["spks"], proc["np_s"],
            window_arr,
        )
    if name == "det":
        return label_det(
            cfg, proc["ppks"], proc["np_p"], proc["spks"], proc["np_s"],
            window_arr,
        )
    if name in cfg.data_channels:
        return proc["win"][cfg.data_channels.index(name)]
    if name in [f"d{c}" for c in cfg.data_channels]:
        ch = proc["win"][cfg.data_channels.index(name[-1])]
        return jnp.concatenate([jnp.zeros((1,), ch.dtype), jnp.diff(ch)])
    raise NotImplementedError(f"device-aug: unsupported soft item '{name}'")


def assemble_io(cfg: AugConfig, names, proc, values, onehots, window_arr):
    """Device mirror of ``DataPreprocessor.get_inputs`` /
    ``get_targets_for_loss``: grouped names stack channels-last; the
    waveform group is the processed window transposed to (L, C)."""
    items = []
    for name in names:
        if isinstance(name, (tuple, list)):
            if tuple(name) == tuple(cfg.data_channels):
                items.append(proc["win"].T)
            else:
                items.append(
                    jnp.stack(
                        [_soft_item(cfg, sub, proc, window_arr) for sub in name],
                        axis=-1,
                    )
                )
            continue
        kind = taskspec.get_kind(name)
        if kind == taskspec.SOFT:
            items.append(_soft_item(cfg, name, proc, window_arr))
        elif kind == taskspec.VALUE:
            # generate_noise clears value fields (ref _clear_event_except).
            items.append(
                jnp.where(proc["gen_fired"], 0.0, values[name])
            )
        elif kind == taskspec.ONEHOT:
            nc = taskspec.get_num_classes(name)
            items.append(
                jax.nn.one_hot(onehots[name], nc, dtype=jnp.int32)
            )
        else:  # pragma: no cover - catalog has exactly three kinds
            raise NotImplementedError(name)
    return tuple(items) if len(items) > 1 else items[0]


def make_row_processor(cfg: AugConfig, input_names, label_names):
    """Build ``process(rows, idx, aug, epoch) -> (inputs, loss_targets)``
    — the vmapped per-batch device preprocessing used INSIDE the jitted
    train step. ``rows`` is the raw-row pytree (see pipeline.RawStore),
    ``idx`` the (B,) global epoch indices keying the RNG, ``aug`` the
    (B,) augment flags (2x-epoch rule), ``epoch`` a scalar."""
    window_arr = jnp.asarray(
        make_soft_window(cfg.soft_label_width, cfg.soft_label_shape),
        jnp.float32,
    )

    def one(row, idx, aug, epoch):
        key = sample_key(cfg.seed, epoch, idx)
        draws = draw_all(cfg, key)
        proc = process_event(
            cfg, row["data"], row["ppks"], row["np_p"], row["spks"],
            row["np_s"], draws, aug,
        )
        values = row.get("values", {})
        onehots = row.get("onehots", {})
        inputs = assemble_io(cfg, input_names, proc, values, onehots, window_arr)
        targets = assemble_io(cfg, label_names, proc, values, onehots, window_arr)
        return inputs, targets

    def process(rows, idx, aug, epoch):
        return jax.vmap(lambda r, i, a: one(r, i, a, epoch))(rows, idx, aug)

    return process


def make_cache_processor(
    cfg: AugConfig, input_names, label_names, n_raw: int, augmentation: bool
):
    """Cache-resident variant: ``process(cache, idx, epoch)`` gathers the
    raw rows from the HBM-resident store by ``idx % n_raw`` (the 2x-epoch
    rule maps ``idx >= n_raw`` to the augmented replica) and runs the
    row processor — zero per-step host involvement beyond the tiny idx
    upload."""
    row_proc = make_row_processor(cfg, input_names, label_names)

    def process(cache, idx, epoch):
        if augmentation:
            raw_idx = idx % n_raw
            aug = idx >= n_raw
        else:
            raw_idx = idx
            aug = jnp.zeros(idx.shape, bool)
        rows = jax.tree.map(lambda a: jnp.take(a, raw_idx, axis=0), cache)
        # RNG keys use the GLOBAL epoch index (matching the host path's
        # SeedSequence([seed, epoch, idx])), so the raw and augmented
        # replicas of a sample draw from different streams.
        return row_proc(rows, idx, aug, epoch)

    return process


# ------------------------------------------------------- support / fallback
def unsupported_reasons(
    pre: DataPreprocessor, input_names, label_names
) -> List[str]:
    """Config features the device pipeline does not implement (the worker
    falls back to the host path and logs these)."""
    reasons = []
    if pre.mask_percent > 0 or pre.noise_percent > 0:
        reasons.append("mask_percent/noise_percent window masking")
    if 0 <= pre.p_position_ratio <= 1:
        reasons.append("p_position_ratio pinned-P windowing")
    if pre.norm_mode not in ("std", "max", ""):
        reasons.append(f"norm_mode '{pre.norm_mode}'")
    names = taskspec.flatten_io_names(list(input_names) + list(label_names))
    diff_names = {f"d{c}" for c in pre.data_channels}
    for name in names:
        kind = taskspec.get_kind(name)
        if kind == taskspec.SOFT and name not in (
            _SOFT_SUPPORTED | set(pre.data_channels) | diff_names
        ):
            reasons.append(f"soft io-item '{name}'")
        if kind in (taskspec.VALUE, taskspec.ONEHOT) and (
            pre.generate_noise_rate > 0
        ):
            # The host path CRASHES here (cleared value lists stack as
            # shape-(0,)); refuse rather than invent semantics.
            reasons.append(
                f"generate_noise_rate > 0 with {kind} label '{name}'"
            )
    return reasons


def hbm_budget_bytes(explicit_gb: float = 0.0) -> int:
    """HBM budget for the resident epoch cache: an explicit --device-aug-
    hbm-gb wins; otherwise half the device's reported bytes_limit; 4 GiB
    when the backend exposes no memory stats (CPU)."""
    if explicit_gb and explicit_gb > 0:
        return int(explicit_gb * (1 << 30))
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit // 2
    except Exception:  # noqa: BLE001 - backends without memory_stats
        pass
    return 4 << 30


def select_device_aug_mode(
    requested: str,
    est_bytes: int,
    budget_bytes: int,
    reasons: Sequence[str],
) -> Tuple[str, str]:
    """Resolve the effective --device-aug mode with automatic fallback:
    unsupported config -> 'off' (host path); 'cached' over the HBM budget
    -> 'step' (device aug, host-fed raw rows). Returns (mode, reason).

    Multi-host runs no longer force the step fallback: the cache places
    each host's addressable sample-axis slices itself
    (``pipeline.DeviceEpochCache``) and the epoch index stream is
    host-sharded under the same deterministic global shard contract as
    the host Loader (``epoch_index_chunks(num_shards=, shard_index=)``)
    — the invariant the old fallback existed to protect."""
    if requested not in ("off", "step", "cached"):
        raise ValueError(f"--device-aug must be off|step|cached, got '{requested}'")
    if requested == "off":
        return "off", ""
    if reasons:
        return "off", "unsupported by device pipeline: " + "; ".join(reasons)
    if requested == "cached":
        if est_bytes > budget_bytes:
            return "step", (
                f"epoch cache ~{est_bytes / 2**20:.0f} MiB exceeds HBM "
                f"budget {budget_bytes / 2**20:.0f} MiB"
            )
        return "cached", ""
    return "step", ""


# ----------------------------------------------------------- golden parity
class ScriptedRNG:
    """``np.random.Generator`` stand-in replaying a prepared response
    queue — the injection side of the golden parity suite. Raises on any
    call-kind mismatch, so a branch misprediction in the replay script
    fails loudly instead of silently desynchronizing."""

    def __init__(self, script: Sequence[Tuple[str, Any]]):
        self._q = deque(script)

    def _pop(self, kind: str):
        if not self._q:
            raise AssertionError(f"replay script exhausted at '{kind}' call")
        k, v = self._q.popleft()
        if k != kind:
            raise AssertionError(f"replay script expected '{k}', got '{kind}'")
        return v

    def random(self) -> float:
        return float(self._pop("random"))

    def integers(self, low, high=None) -> int:
        v = int(self._pop("integers"))
        lo, hi = (0, low) if high is None else (low, high)
        if not lo <= v < hi:
            raise AssertionError(f"scripted int {v} outside [{lo}, {hi})")
        return v

    def uniform(self, low=0.0, high=1.0) -> float:
        return float(self._pop("uniform"))

    def standard_normal(self, shape):
        v = np.asarray(self._pop("normal"), np.float32)
        want = (shape,) if isinstance(shape, (int, np.integer)) else tuple(shape)
        if v.shape != want:
            raise AssertionError(f"scripted normal {v.shape} != asked {want}")
        return v

    def choice(self, seq, size=None, replace=True):
        return self._pop("choice")

    def assert_exhausted(self) -> None:
        if self._q:
            raise AssertionError(f"{len(self._q)} scripted draws unconsumed")


def build_replay_script(
    pre: DataPreprocessor, event: dict, draws: dict, augmentation: bool = True
) -> List[Tuple[str, Any]]:
    """Translate one sample's named device draws into the numpy
    ``DataPreprocessor.process`` consumption order. This walks the
    reference pipeline's branch structure (preprocess.py:432-499 +
    172-222) with shadow phase bookkeeping; the real numpy code still
    computes every result — a branch mismatch surfaces as a ScriptedRNG
    kind error, never as silent desync."""
    data = np.asarray(event["data"])
    C, L = data.shape
    d = {k: np.asarray(v) for k, v in draws.items()}
    ppks, spks = list(event["ppks"]), list(event["spks"])
    if pre._is_noise(data, ppks, spks, event["snr"]):
        ppks, spks = [], []
    ppks, spks = pad_phases(ppks, spks, pre.min_event_gap, pre.in_samples)
    q: List[Tuple[str, Any]] = []

    def gate(name, rate):
        u = float(d[name])
        q.append(("random", u))
        return u < rate

    def drop_block():
        if C < 2:
            return
        drop_num = 1 + u2i_np(d["drop_num_u"], C - 1)
        q.append(("choice", drop_num))
        cands = list(range(C))
        for i in range(drop_num):
            c = cands[u2i_np(d["drop_ch_u"][i], len(cands))]
            q.append(("choice", c))
            cands.remove(c)

    def scale_block():
        q.append(("uniform", float(d["scale_flip"])))
        q.append(("uniform", 1.0 + 2.0 * float(d["scale_factor_u"])))

    if augmentation:
        if pre.mask_percent > 0 or pre.noise_percent > 0:
            raise NotImplementedError(
                "mask/noise window augments are host-only"
            )
        if gate("gen_gate", pre.generate_noise_rate):
            for ppk, spk in zip(ppks, spks):
                ce = int(
                    np.clip(int(spk + pre.coda_ratio * (spk - ppk)), 0, L)
                )
                if ppk < ce:
                    q.append(("normal", d["gen_field"][:, ppk:ce]))
            ppks, spks = [], []
            if gate("drop_gate", pre.drop_channel_rate):
                drop_block()
            if gate("scale_gate", pre.scale_amplitude_rate):
                scale_block()
        else:
            n0 = len(ppks)
            for i in range(max(0, pre._max_event_num - n0)):
                u = float(d["add_gate"][i])
                q.append(("random", u))
                if u < pre.add_event_rate and ppks:
                    t = u2i_np(d["add_target"][i], len(ppks))
                    q.append(("integers", t))
                    ppk, spk = ppks[t], spks[t]
                    ce = int(spk + pre.coda_ratio * (spk - ppk))
                    left = ce + pre.min_event_gap
                    right = L - (spk - ppk) - pre.min_event_gap
                    if left < right:
                        pos = left + u2i_np(d["add_pos"][i], right - left)
                        q.append(("integers", pos))
                        q.append(("random", float(d["add_scale"][i])))
                        ppks.append(pos)
                        spks.append(pos + spk - ppk)
                    ppks.sort()
                    spks.sort()
            if gate("shift_gate", pre.shift_event_rate):
                s = u2i_np(d["shift_u"], L)
                q.append(("integers", s))
                ppks = sorted((p + s) % L for p in ppks)
                spks = sorted((x + s) % L for x in spks)
            if gate("drop_gate", pre.drop_channel_rate):
                drop_block()
            if gate("scale_gate", pre.scale_amplitude_rate):
                scale_block()
            gate("pre_gate", pre.pre_emphasis_rate)
            if gate("noise_gate", pre.add_noise_rate):
                for c in range(C):
                    snr = 10 + u2i_np(d["snr_u"][c], 40)
                    q.append(("integers", snr))
                    q.append(("normal", d["noise_field"][c]))
            if gate("gap_gate", pre.add_gap_rate):
                phases = sorted(ppks + spks)
                if len(phases) > 0:
                    phases.append(L - 1)
                    phases = sorted(set(phases))
                    ip = u2i_np(d["gap_pos_u"], len(phases) - 1)
                    q.append(("integers", ip))
                    sgt = phases[ip] + u2i_np(
                        d["gap_start_u"], phases[ip + 1] - phases[ip]
                    )
                    q.append(("integers", sgt))
                    egt = sgt + u2i_np(d["gap_end_u"], phases[ip + 1] - sgt)
                    q.append(("integers", egt))
                else:
                    sgt = u2i_np(d["gap_start_u"], L - 1)
                    q.append(("integers", sgt))
                    egt = sgt + 1 + u2i_np(d["gap_end_u"], L - 1 - sgt)
                    q.append(("integers", egt))

    if L > pre.in_samples:
        bound = max(min(ppks + [L - pre.in_samples]) - pre.min_event_gap, 1)
        q.append(("integers", u2i_np(d["crop_u"], bound)))
    return q


def make_replay_rng(
    pre: DataPreprocessor, event: dict, draws: dict, augmentation: bool = True
) -> ScriptedRNG:
    """ScriptedRNG that makes ``pre.process(event, augmentation, rng=...)``
    consume exactly the device pipeline's named draws."""
    return ScriptedRNG(build_replay_script(pre, event, draws, augmentation))


def host_prepare(
    pre: DataPreprocessor, event: dict, phase_slots: int
) -> Dict[str, Any]:
    """The draw-free host half of the device pipeline, applied ONCE at
    upload: ``_is_noise`` classification (clearing noise traces' labels)
    and ``pad_phases`` — both static per raw sample. Returns the fixed-
    shape row dict the device processor consumes."""
    data = np.ascontiguousarray(np.asarray(event["data"], np.float32))
    ppks, spks = list(event["ppks"]), list(event["spks"])
    is_noise = pre._is_noise(data, ppks, spks, event["snr"])
    if is_noise:
        ppks, spks = [], []
    ppks, spks = pad_phases(ppks, spks, pre.min_event_gap, pre.in_samples)
    if max(len(ppks), len(spks)) > phase_slots:
        raise ValueError(
            f"event has {max(len(ppks), len(spks))} phases > "
            f"phase_slots {phase_slots}"
        )

    def arr(vals):
        return np.asarray(
            list(vals) + [_BIG] * (phase_slots - len(vals)), np.int32
        )

    return {
        "data": data,
        "ppks": arr(ppks),
        "np_p": np.int32(len(ppks)),
        "spks": arr(spks),
        "np_s": np.int32(len(spks)),
        "is_noise": bool(is_noise),
    }
