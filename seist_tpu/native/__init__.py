"""ctypes bindings for the native wavekit kernels (see wavekit.cpp).

Loads ``libwavekit.so`` from this directory if present (build with
``make native``); all callers fall back to the pure-numpy implementations
when the library is absent, so the build is optional. Set
``SEIST_TPU_NATIVE=0`` to force the numpy path even when built.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libwavekit.so")
_lib: Optional[ctypes.CDLL] = None

if os.environ.get("SEIST_TPU_NATIVE", "auto") != "0" and os.path.exists(_LIB_PATH):
    try:
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.znorm_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        _lib.soft_label_add_f64.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
    except OSError:
        _lib = None


def available() -> bool:
    return _lib is not None


_NORM_MODES = {"std": 0, "max": 1, "": 2}


def znorm(data: np.ndarray, mode: str) -> bool:
    """In-place per-channel normalize of a C-contiguous (C, L) float32
    array. Returns False (caller should use numpy) when unsupported."""
    if (
        _lib is None
        or data.dtype != np.float32
        or not data.flags.c_contiguous
        or data.ndim != 2
        or mode not in _NORM_MODES
    ):
        return False
    _lib.znorm_f32(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        data.shape[0],
        data.shape[1],
        _NORM_MODES[mode],
    )
    return True


def soft_label_add(
    out: np.ndarray, idxs: np.ndarray, window: np.ndarray, width: int
) -> bool:
    """Add label windows into ``out`` (float64, length L) at ``idxs``.
    Returns False when the native path is unavailable (including windows
    wider than the array — the numpy path raises loudly on that config and
    the native kernel must not silently clip it)."""
    if (
        _lib is None
        or out.dtype != np.float64
        or not out.flags.c_contiguous
        or width + 1 > out.shape[0]
    ):
        return False
    idxs = np.ascontiguousarray(idxs, dtype=np.int64)
    window = np.ascontiguousarray(window, dtype=np.float64)
    _lib.soft_label_add_f64(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.shape[0],
        idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        idxs.shape[0],
        window.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        width,
    )
    return True
