// wavekit — native host-side kernels for the input pipeline.
//
// The loader's per-sample cost is dominated by many small numpy ops with
// Python dispatch overhead (normalize + several soft-label placements per
// sample; ref training/preprocess.py:224-242,567-619). These C++ kernels do
// the same math in one call each; seist_tpu/native/__init__.py binds them
// via ctypes and seist_tpu/data/preprocess.py uses them when built
// (numerically equal to the numpy path within fp32 accumulation tolerance —
// verified by tests/test_native.py).
//
// Build: `make native` at the repo root (g++ -O3, no dependencies).

#include <cmath>
#include <cstdint>

extern "C" {

// Per-channel demean + scale. mode: 0 = std, 1 = max (SIGNED max, matching
// the reference's np.max at preprocess.py:228 — not abs-max), 2 = demean
// only. data is (C, L) float32, modified in place; zero std/max divides by
// 1 (reference's `[denom == 0] = 1` guard).
void znorm_f32(float* data, int64_t channels, int64_t length, int mode) {
  for (int64_t c = 0; c < channels; ++c) {
    float* row = data + c * length;
    double mean = 0.0;
    for (int64_t i = 0; i < length; ++i) mean += row[i];
    mean /= static_cast<double>(length);
    for (int64_t i = 0; i < length; ++i) row[i] -= static_cast<float>(mean);
    if (mode == 2) continue;
    double denom = 0.0;
    if (mode == 0) {
      for (int64_t i = 0; i < length; ++i)
        denom += static_cast<double>(row[i]) * row[i];
      denom = std::sqrt(denom / static_cast<double>(length));
    } else {
      denom = row[0];
      for (int64_t i = 1; i < length; ++i)
        if (row[i] > denom) denom = row[i];
    }
    if (denom == 0.0) denom = 1.0;
    float inv = static_cast<float>(1.0 / denom);
    for (int64_t i = 0; i < length; ++i) row[i] *= inv;
  }
}

// Add a (width+1)-sample label window into `out` (length L) at each index,
// with the reference's edge-truncation rules (preprocess.py:567-619):
//   idx < 0                      -> skipped
//   idx - left < 0               -> right-aligned head slice
//   idx + right <= L - 1         -> full window
//   idx <= L - 1                 -> tail slice
//   idx > L - 1                  -> skipped
void soft_label_add_f64(double* out, int64_t length, const int64_t* idxs,
                        int64_t n_idx, const double* window, int64_t width) {
  const int64_t left = width / 2;
  const int64_t right = width - left;
  for (int64_t k = 0; k < n_idx; ++k) {
    const int64_t idx = idxs[k];
    if (idx < 0 || idx > length - 1) continue;
    if (idx - left < 0) {
      int64_t count = idx + right + 1;  // head slice
      if (count > length) count = length;  // window wider than the array
      const double* w = window + (width + 1 - count);
      for (int64_t i = 0; i < count; ++i) out[i] += w[i];
    } else if (idx + right <= length - 1) {
      double* o = out + (idx - left);
      for (int64_t i = 0; i < width + 1; ++i) o[i] += window[i];
    } else {
      const int64_t count = length - (idx - left);  // tail slice
      double* o = out + (length - count);
      for (int64_t i = 0; i < count; ++i) o[i] += window[i];
    }
  }
}

}  // extern "C"
