"""Durable streaming state: per-station session journals + an alert WAL.

Failover story (docs/FAULT_TOLERANCE.md "Streaming faults"): when a
replica dies, the router re-homes its stations to survivors by rendezvous
hash; the survivor's first packet for an orphaned station finds the dead
replica's last journal entry here and resumes the session mid-record —
the snapshot/restore parity pin means picks continue exactly where the
journal watermark left them. No journal (never written, corrupt, version
skew) degrades to a fresh session: the stream plane already stitches
through sequence gaps, so the station re-warms instead of erroring.

Two artifacts, two durability contracts:

* :class:`StationJournal` — one ``<station>.npz`` per station under
  ``<root>/<model>/stations/``, REPLACED atomically on every write
  (dotfile + ``os.replace``, the ``obs/flight.py`` idiom): a reader
  never sees a torn file, and a crash mid-write leaves the previous
  journal intact. Entries are O(window) by construction — the session's
  ring/curve trims bound the snapshot, so journal size is independent of
  stream length. Router affinity guarantees a single writer per station
  file; the directory itself is shared by the fleet (that sharing IS the
  failover channel).
* :class:`AlertWAL` — append-only JSONL, one fsync'd line per emitted
  alert, written BEFORE the alert becomes visible to any consumer
  (durable-before-visible). Replay after a restart seeds the
  associator's dedup window so a re-formed event hypothesis is
  suppressed instead of double-alerting; corrupt trailing lines (torn
  final append) are skipped, never fatal.

State bytes are ``np.savez_compressed`` with the JSON meta riding as a
uint8 array — one self-describing blob, no sidecar files to tear.
"""

from __future__ import annotations

import io
import json
import os
import threading
from typing import Dict, List, Mapping, Optional

import numpy as np

from seist_tpu.utils.faults import stream_faults

__all__ = [
    "AlertWAL",
    "StationJournal",
    "state_from_bytes",
    "state_to_bytes",
]


# ----------------------------------------------------------- state codec
def state_to_bytes(state: Mapping[str, object]) -> bytes:
    """Pack a ``StreamSession.snapshot()`` dict into one npz blob."""
    meta = json.dumps(state["meta"], separators=(",", ":")).encode()
    arrays = {k: np.asarray(v) for k, v in state["arrays"].items()}
    buf = io.BytesIO()
    np.savez_compressed(
        buf, __meta__=np.frombuffer(meta, np.uint8), **arrays
    )
    return buf.getvalue()


def state_from_bytes(blob: bytes) -> Dict[str, object]:
    """Inverse of :func:`state_to_bytes`. Raises on any corruption —
    callers map that to "no journal" (fresh session re-warm)."""
    with np.load(io.BytesIO(blob)) as z:
        meta = json.loads(z["__meta__"].tobytes().decode())
        arrays = {k: np.array(z[k]) for k in z.files if k != "__meta__"}
    return {"meta": meta, "arrays": arrays}


def _slug(s: str) -> str:
    out = "".join(c if (c.isalnum() or c in "-_.") else "_" for c in s)
    return out[:128] or "default"


# ------------------------------------------------------- station journal
class StationJournal:
    """Atomic per-station session journal under ``<root>/<model>/stations``.

    ``write`` is the hot path (one per station per journal interval):
    serialize, write a dotfile (invisible to ``*.npz`` listings), rename
    into place. ``load`` returns ``None`` for missing OR unreadable
    journals — the caller cannot do anything smarter with a corrupt file
    than with an absent one, and the distinction is surfaced through the
    ``corrupt_reads`` counter instead of an exception."""

    def __init__(self, root: str, model: str = "default") -> None:
        self.root = os.path.join(root, _slug(model), "stations")
        os.makedirs(self.root, exist_ok=True)
        self.writes = 0
        self.corrupt_reads = 0

    def _path(self, station_id: str) -> str:
        return os.path.join(self.root, _slug(station_id) + ".npz")

    def write(self, station_id: str, state: Mapping[str, object]) -> str:
        path = self._path(station_id)
        blob = state_to_bytes(state)
        # Fault lane: SEIST_FAULT_STREAM_JOURNAL_CORRUPT_P truncates the
        # blob mid-write for hash-selected stations so failover exercises
        # the torn-journal -> fresh-session path deterministically.
        inj = stream_faults()
        if inj.corrupt_journal(station_id):
            blob = blob[: max(1, len(blob) // 2)]
        tmp = os.path.join(
            self.root, "." + os.path.basename(path) + ".tmp"
        )
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        self.writes += 1
        return path

    def load(self, station_id: str) -> Optional[Dict[str, object]]:
        path = self._path(station_id)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            return state_from_bytes(blob)
        except Exception:  # noqa: BLE001 - corrupt journal == no journal
            self.corrupt_reads += 1
            return None

    def remove(self, station_id: str) -> None:
        try:
            os.remove(self._path(station_id))
        except OSError:
            pass

    def station_ids(self) -> List[str]:
        """Slugged station ids with a journal on disk (sorted)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(
            n[: -len(".npz")]
            for n in names
            if n.endswith(".npz") and not n.startswith(".")
        )


# ------------------------------------------------------------- alert WAL
class AlertWAL:
    """Append-only JSONL alert log, one fsync'd line per alert.

    The associator appends INSIDE its emit path, before the alert is
    returned to any caller — an alert a consumer could have seen is
    always on disk first, so a crash between emit and delivery re-emits
    (at-least-once) and the dedup window turns that into exactly-once
    for the consumer."""

    def __init__(self, path: str) -> None:
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self.appends = 0

    def append(self, record: Mapping[str, object]) -> None:
        line = json.dumps(record, separators=(",", ":"), default=str)
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
            self.appends += 1

    def replay(self) -> List[Dict[str, object]]:
        """All intact records, oldest first; torn lines are skipped."""
        out: List[Dict[str, object]] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            return out
        return out
