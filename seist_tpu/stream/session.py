"""Stateful sliding-window inference for one station's unbounded stream.

A :class:`StreamSession` is the streaming twin of ``ops/stream.annotate``:
feed packets of arbitrary size with :meth:`push`, forward the due windows
it hands back through any model, return the probabilities with
:meth:`integrate`, and the picks that come out are *identical* to running
offline ``annotate`` on the concatenated record — the parity pin
(tests/test_stream_session.py) that makes the subsystem trustworthy.

How the pin is engineered, piece by piece:

* **Windowing**: regular offsets ``0, stride, 2*stride, ...`` become due
  the moment ``offset + window`` samples exist — exactly the set
  ``window_offsets`` enumerates offline. The right-aligned tail window
  (and the padded window of a record shorter than ``window``) depends on
  the final record length, so it is emitted by :meth:`finish`.
* **State**: the session keeps (a) a raw ring buffer from the earliest
  sample any future window can need — ``min(next_offset, n - window)`` —
  and (b) the running stitch accumulators. Per-window z-normalization is
  recomputed from the ring buffer when a window falls due (the same
  ``normalize(chunk, "std", axis=1)`` numpy reduction annotate applies),
  so normalization state *is* the ring buffer + per-window moments;
  a streaming mean/var would diverge bitwise from the offline pin.
* **Stitching**: ``combine='mean'`` accumulates float32 value/hit sums in
  ascending offset order; ``'max'`` keeps a running elementwise max in
  event-evidence space for ``channel0='non'`` — both mirror
  ``stitch_probs`` op for op, including the double ``1 - x`` inversion of
  the non channel that annotate performs (NOT algebraically simplified:
  ``1-(1-m)`` need not equal ``m`` in float32).
* **Finality frontier**: a stitched sample is final once no future window
  can cover it: ``t < min(next_offset, n - window)`` (the tail window of
  a stream ending *right now* starts at ``n - window``). Pickers only
  ever read final samples, so nothing emitted is ever retracted.
* **Incremental picking**: host-side re-implementations of the exact
  ``ops/postprocess.pick_peaks`` / ``detect_events`` semantics (rising
  edge candidates, first/last sample excluded, >= threshold, greedy NMS
  in height order with |dist| <= mpd inclusive, dead peaks don't
  suppress; detection runs strictly > threshold). Greedy NMS looks
  global, but candidates partition into components separated by
  candidate-free gaps > mpd; kills never cross components, so a
  component whose trailing gap is final is itself final — emitted
  immediately, provably identical to the batch kernel.

The ONE divergence from offline: ``annotate``'s ``max_events`` capacity
(auto-scaled to 4 picks per window span, rounded up to a power of two)
truncates to the topk *tallest* when it binds; the session is unbounded.
The auto-scale makes the cap effectively unreachable — parity holds
whenever the offline cap does not bind, which the parity tests assert.

Cost model: one packet costs at most ``ceil(packet/stride)`` window
forwards plus O(packet) host stitching — never a re-annotation of the
record so far.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Mapping, Optional

import numpy as np

__all__ = ["DueWindow", "SessionConfig", "StreamSession", "STATE_VERSION"]

#: Version tag carried in every snapshot; bump on ANY layout change to the
#: state dict so a restore from an older journal fails loud (fresh session
#: + gap-stitch re-warm) instead of resurrecting subtly-wrong state.
STATE_VERSION = 1


@dataclass(frozen=True)
class SessionConfig:
    """Pick/stitch parameters — mirror ``annotate``'s keyword surface so a
    session and an offline re-annotation can be configured identically."""

    window: int = 8192
    stride: int = 4096
    in_channels: int = 3
    channel0: str = "non"  # 'non' (phasenet) | 'det' (seist dpk family)
    combine: str = "mean"  # 'mean' | 'max'
    sampling_rate: int = 50
    ppk_threshold: float = 0.3
    spk_threshold: float = 0.3
    det_threshold: float = 0.5
    min_peak_dist: float = 1.0

    def __post_init__(self) -> None:
        if self.channel0 not in ("non", "det"):
            raise ValueError(f"channel0 must be 'non'|'det', got {self.channel0!r}")
        if self.combine not in ("mean", "max"):
            raise ValueError(f"combine must be 'mean'|'max', got {self.combine!r}")
        if not (0 < self.stride <= self.window):
            raise ValueError(f"need 0 < stride <= window, got {self.stride}/{self.window}")

    @property
    def peak_dist(self) -> int:
        return int(self.min_peak_dist * self.sampling_rate)


@dataclass(frozen=True)
class DueWindow:
    """One model-ready window: normalized (window, C) float32 at ``offset``.

    ``pad`` > 0 only for the final window of a record shorter than one
    window (zero right-padding; picks inside the pad are trimmed)."""

    offset: int
    data: np.ndarray
    pad: int = 0


class _PeakPicker:
    """Incremental, exact ``pick_peaks``: emits a peak the moment its NMS
    component closes (candidate-free final gap > mpd), never retracts."""

    def __init__(self, threshold: float, mpd: int) -> None:
        self.threshold = float(threshold)
        self.mpd = int(mpd)
        self._comp: List[tuple] = []  # open component: (pos, height)
        self._scanned = 1  # t=0 is never a candidate (first sample excluded)
        self.out: List[int] = []

    def _close(self) -> List[int]:
        comp, self._comp = self._comp, []
        if not comp:
            return []
        if self.mpd <= 1:  # kernel skips NMS entirely for mpd <= 1
            return [p for p, _ in comp]
        # Greedy NMS in height order, ties toward the earlier index
        # (lax.top_k order); kills are |dpos| <= mpd inclusive and dead
        # candidates don't suppress — ops/postprocess.py:78-90 verbatim.
        order = sorted(range(len(comp)), key=lambda i: (-comp[i][1], comp[i][0]))
        alive = [True] * len(comp)
        for k in order:
            if not alive[k]:
                continue
            pk = comp[k][0]
            for j in range(len(comp)):
                if j != k and alive[j] and abs(comp[j][0] - pk) <= self.mpd:
                    alive[j] = False
        return sorted(p for (p, _), a in zip(comp, alive) if a)

    def scan(self, curve: np.ndarray, base: int, upto: int, at_end: bool) -> List[int]:
        """Consume final curve samples ``[base, base+len(curve))`` covering
        positions up to ``upto`` (exclusive); decide candidates t with
        t+1 < upto. ``at_end``: ``upto`` is the record length — flush."""
        emitted: List[int] = []
        hi = upto - 1  # t needs t+1 final; also excludes the last sample
        lo = self._scanned
        if hi > lo:
            seg = curve[lo - base - 1 : hi - base + 1]  # values at [lo-1, hi]
            dx = np.diff(seg)
            cand = (dx[:-1] > 0) & (dx[1:] <= 0) & (seg[1:-1] >= self.threshold)
            for p in (np.nonzero(cand)[0] + lo):
                p = int(p)
                if self._comp and p - self._comp[-1][0] > self.mpd:
                    emitted.extend(self._close())
                self._comp.append((p, float(curve[p - base])))
            self._scanned = hi
            if self._comp and (hi - 1) - self._comp[-1][0] > self.mpd:
                emitted.extend(self._close())
        if at_end:
            emitted.extend(self._close())
        return emitted


class _Detector:
    """Incremental, exact ``detect_events``: maximal runs strictly above
    threshold; a run is emitted when a final below-threshold sample (or
    the record end) closes it. Single-sample on == off runs are kept,
    matching annotate's ``det[:, 1] >= det[:, 0]`` filter."""

    def __init__(self, threshold: float) -> None:
        self.threshold = float(threshold)
        self._on: Optional[int] = None
        self._off = -1
        self._scanned = 0

    def scan(self, strength: np.ndarray, base: int, upto: int, at_end: bool) -> List[tuple]:
        emitted: List[tuple] = []
        seg = strength[self._scanned - base : upto - base]
        above = seg > self.threshold
        t = self._scanned
        # Run-length walk via transition indices (host cost O(runs)).
        bounds = np.nonzero(np.diff(above.astype(np.int8)))[0] + 1
        pieces = np.split(above, bounds)
        for piece in pieces:
            if piece.size == 0:
                continue
            if piece[0]:
                if self._on is None:
                    self._on = t
                self._off = t + piece.size - 1
            elif self._on is not None:
                emitted.append((self._on, self._off))
                self._on = None
            t += piece.size
        self._scanned = upto
        if at_end and self._on is not None:
            emitted.append((self._on, self._off))
            self._on = None
        return emitted


class StreamSession:
    """One station's streaming annotate state. Not thread-safe; the mux
    holds one lock per session.

    Protocol::

        due = session.push(packet)           # 0+ DueWindow, ascending offset
        for w in due:
            picks = session.integrate(w.offset, model(w.data[None])[0])
        ...
        for w in session.finish():           # tail / short-record window
            picks = session.integrate(w.offset, ...)
        picks = session.finalize()           # flush pickers

    Every ``integrate``/``finalize`` returns only *newly final* picks
    ({"ppk": [...], "spk": [...], "det": [(on, off), ...]}, absolute
    sample positions); their union over the session's lifetime equals
    offline ``annotate`` output on the concatenated record.
    """

    def __init__(self, config: SessionConfig) -> None:
        self.config = c = config
        self.n_samples = 0  # total samples pushed
        self.n_windows = 0  # windows handed out
        self._next_offset = 0  # first regular offset not yet due
        self._base = 0  # absolute position of ring buffer start
        self._ring = np.zeros((0, c.in_channels), np.float32)
        self._curve_base = 0  # absolute position of accumulator start
        dt = np.float32
        if c.combine == "mean":
            self._acc = np.zeros((0, 3), dt)
            self._hits = np.zeros((0,), dt)
        else:
            self._evmax = np.zeros((0, 3), dt)
        self._final_upto = 0  # samples < this are stitch-final
        self._pending: List[int] = []  # offsets handed out, not integrated
        self._finished = False
        self._finalized = False
        self._total_len: Optional[int] = None  # padded length for short records
        mpd = c.peak_dist
        self._ppk = _PeakPicker(c.ppk_threshold, mpd)
        self._spk = _PeakPicker(c.spk_threshold, mpd)
        self._det = _Detector(c.det_threshold)
        # Retained final curve for picker context: pickers keep their own
        # scan cursors, so we only retain from min(scanned)-1 backwards.
        self._picks: Dict[str, list] = {"ppk": [], "spk": [], "det": []}

    # ------------------------------------------------------------ ingest
    def push(self, data: np.ndarray) -> List[DueWindow]:
        """Append a packet ((L, C) float32, any L >= 0); return the windows
        that became due, ascending offset, each z-normalized model-ready."""
        if self._finished:
            raise RuntimeError("push after finish()")
        c = self.config
        data = np.asarray(data, np.float32)
        if data.ndim != 2 or data.shape[1] != c.in_channels:
            raise ValueError(
                f"packet must be (L, {c.in_channels}), got {data.shape}"
            )
        if data.shape[0]:
            self._ring = np.concatenate([self._ring, data], axis=0)
            self.n_samples += data.shape[0]
        due: List[DueWindow] = []
        while self._next_offset + c.window <= self.n_samples:
            o = self._next_offset
            due.append(DueWindow(o, self._normalized(o, c.window)))
            self._pending.append(o)
            self._next_offset = o + c.stride
        self._trim_ring()
        self.n_windows += len(due)
        return due

    def finish(self) -> List[DueWindow]:
        """Mark end-of-stream; return the remaining due window, if any:
        the right-aligned tail (when distinct from the last regular
        offset) or the zero-padded window of a short record."""
        if self._finished:
            return []
        self._finished = True
        c = self.config
        n = self.n_samples
        if n == 0:
            self._total_len = 0
            return []
        if n < c.window:
            # annotate's pad-and-trim contract for short records: zero
            # right-pad to one window, normalize the PADDED window.
            pad = c.window - n
            self._total_len = c.window
            raw = np.concatenate(
                [self._ring, np.zeros((pad, c.in_channels), np.float32)], axis=0
            )
            self.n_windows += 1
            self._pending.append(0)
            return [DueWindow(0, _znorm(raw), pad=pad)]
        tail = n - c.window
        last_regular = self._next_offset - c.stride
        if self._next_offset == 0 or tail != last_regular:
            self.n_windows += 1
            self._pending.append(tail)
            return [DueWindow(tail, self._normalized(tail, c.window))]
        return []

    # --------------------------------------------------------- integrate
    def integrate(self, offset: int, probs: np.ndarray) -> Dict[str, list]:
        """Stitch one window's (window, 3) probabilities at ``offset``;
        advance the finality frontier; return newly final picks."""
        c = self.config
        probs = np.asarray(probs, np.float32)
        if probs.shape != (c.window, 3):
            raise ValueError(f"probs must be ({c.window}, 3), got {probs.shape}")
        if c.combine == "max" and c.channel0 == "non":
            # Event-evidence space (annotate's max/'non' branch).
            probs = probs.copy()
            probs[:, 0] = 1.0 - probs[:, 0]
        try:
            self._pending.remove(offset)
        except ValueError:
            raise ValueError(f"no window pending at offset {offset}") from None
        self._ensure_curve(offset + c.window)
        lo = offset - self._curve_base
        if lo < 0:
            raise ValueError(f"window at {offset} precedes retained curve")
        if c.combine == "mean":
            self._acc[lo : lo + c.window] += probs
            self._hits[lo : lo + c.window] += 1.0
        else:
            np.maximum(
                self._evmax[lo : lo + c.window],
                probs,
                out=self._evmax[lo : lo + c.window],
            )
        return self._advance()

    def abandon(self, offset: int) -> Dict[str, list]:
        """Drop a handed-out window whose forward failed (shed, queue
        full, replica dying). The slot leaves ``_pending`` so the
        finality frontier can keep advancing — without this, one dropped
        window wedges the frontier forever and the station never emits
        another pick. The un-stitched span becomes a coverage hole
        (rendered as pure noise by :meth:`_curve`); newly final picks on
        either side are returned exactly like :meth:`integrate`."""
        try:
            self._pending.remove(offset)
        except ValueError:
            raise ValueError(f"no window pending at offset {offset}") from None
        # Zero-fill the accumulators across the hole: the frontier may
        # now advance past territory no integrate() ever grew the curve
        # for, and pickers must see explicit zeros, not a short slice.
        self._ensure_curve(offset + self.config.window)
        return self._advance()

    def finalize(self) -> Dict[str, list]:
        """After integrating :meth:`finish`'s windows: flush the pickers
        over the (now fully final) record tail."""
        if not self._finished:
            raise RuntimeError("finalize before finish()")
        if self._pending:
            raise RuntimeError(
                f"finalize with {len(self._pending)} un-integrated windows"
            )
        if self._finalized:
            return {"ppk": [], "spk": [], "det": []}
        self._finalized = True
        return self._advance(at_end=True)

    @property
    def picks(self) -> Dict[str, list]:
        """All picks emitted so far (the running union)."""
        return {k: list(v) for k, v in self._picks.items()}

    @property
    def context_samples(self) -> int:
        """Raw samples currently retained (the ring buffer)."""
        return self._ring.shape[0]

    # -------------------------------------------------- snapshot/restore
    def snapshot(self) -> Dict[str, object]:
        """Serializable session state: ``{"meta": <JSON-able dict>,
        "arrays": <name -> ndarray>}``. Bounded by design: the ring and
        retained curve are already trimmed to O(window), so a journal
        entry costs the same regardless of stream length.

        Only quiescent sessions snapshot — ``_pending`` must be empty
        (the mux journals between feeds, under the entry lock, where
        every handed-out window has been integrated or abandoned). A
        mid-flight snapshot would need the un-integrated window replayed
        on restore, which nothing can do after the process died."""
        if self._pending:
            raise RuntimeError(
                f"snapshot with {len(self._pending)} in-flight windows"
            )
        c = self.config
        meta: Dict[str, object] = {
            "version": STATE_VERSION,
            "config": asdict(c),
            "n_samples": self.n_samples,
            "n_windows": self.n_windows,
            "next_offset": self._next_offset,
            "base": self._base,
            "curve_base": self._curve_base,
            "final_upto": self._final_upto,
            "finished": self._finished,
            "finalized": self._finalized,
            "total_len": self._total_len,
            "ppk": {"comp": self._ppk._comp, "scanned": self._ppk._scanned},
            "spk": {"comp": self._spk._comp, "scanned": self._spk._scanned},
            "det": {
                "on": self._det._on,
                "off": self._det._off,
                "scanned": self._det._scanned,
            },
        }
        arrays: Dict[str, np.ndarray] = {"ring": self._ring.copy()}
        if c.combine == "mean":
            arrays["acc"] = self._acc.copy()
            arrays["hits"] = self._hits.copy()
        else:
            arrays["evmax"] = self._evmax.copy()
        return {"meta": meta, "arrays": arrays}

    @classmethod
    def restore(cls, state: Mapping[str, object]) -> "StreamSession":
        """Rebuild a session from :meth:`snapshot` output. Parity-pinned:
        restore at any packet boundary then feed the remaining packets
        and the emitted pick stream is bit-identical to the session that
        never died (tests/test_stream_session.py). Raises ``ValueError``
        on version/shape mismatch — callers treat that as journal loss
        and fall back to a fresh session (gap-stitch re-warm)."""
        meta = state["meta"]
        arrays = state["arrays"]
        if meta.get("version") != STATE_VERSION:
            raise ValueError(
                f"session state version {meta.get('version')!r}, "
                f"want {STATE_VERSION}"
            )
        cfg = SessionConfig(**dict(meta["config"]))
        sess = cls(cfg)
        sess.n_samples = int(meta["n_samples"])
        sess.n_windows = int(meta["n_windows"])
        sess._next_offset = int(meta["next_offset"])
        sess._base = int(meta["base"])
        sess._curve_base = int(meta["curve_base"])
        sess._final_upto = int(meta["final_upto"])
        sess._finished = bool(meta["finished"])
        sess._finalized = bool(meta["finalized"])
        tl = meta["total_len"]
        sess._total_len = None if tl is None else int(tl)
        ring = np.asarray(arrays["ring"], np.float32)
        if ring.ndim != 2 or ring.shape[1] != cfg.in_channels:
            raise ValueError(f"ring shape {ring.shape} != (*, {cfg.in_channels})")
        sess._ring = ring.copy()
        if cfg.combine == "mean":
            sess._acc = np.asarray(arrays["acc"], np.float32).copy()
            sess._hits = np.asarray(arrays["hits"], np.float32).copy()
            if sess._acc.shape != (sess._hits.shape[0], 3):
                raise ValueError("acc/hits shape mismatch")
        else:
            sess._evmax = np.asarray(arrays["evmax"], np.float32).copy()
        for picker, key in ((sess._ppk, "ppk"), (sess._spk, "spk")):
            pm = meta[key]
            picker._comp = [(int(p), float(h)) for p, h in pm["comp"]]
            picker._scanned = int(pm["scanned"])
        dm = meta["det"]
        sess._det._on = None if dm["on"] is None else int(dm["on"])
        sess._det._off = int(dm["off"])
        sess._det._scanned = int(dm["scanned"])
        return sess

    # ---------------------------------------------------------- plumbing
    def _normalized(self, offset: int, length: int) -> np.ndarray:
        s = offset - self._base
        return _znorm(self._ring[s : s + length])

    def _trim_ring(self) -> None:
        # Keep raw samples any future window can need: the next regular
        # offset, or the tail window of a stream ending right now.
        keep_from = min(self._next_offset, max(0, self.n_samples - self.config.window))
        drop = keep_from - self._base
        if drop > 0:
            self._ring = self._ring[drop:]
            self._base = keep_from

    def _ensure_curve(self, upto: int) -> None:
        have = self._curve_base + (
            self._hits.shape[0] if self.config.combine == "mean" else self._evmax.shape[0]
        )
        grow = upto - have
        if grow <= 0:
            return
        grow = max(grow, self.config.window)  # amortize
        if self.config.combine == "mean":
            self._acc = np.concatenate(
                [self._acc, np.zeros((grow, 3), np.float32)], axis=0
            )
            self._hits = np.concatenate(
                [self._hits, np.zeros((grow,), np.float32)], axis=0
            )
        else:
            self._evmax = np.concatenate(
                [self._evmax, np.zeros((grow, 3), np.float32)], axis=0
            )

    def _frontier(self) -> int:
        """First sample a FUTURE window could still cover: pending
        (handed out, not yet integrated) windows gate finality exactly
        like un-pushed ones."""
        pend = min(self._pending) if self._pending else None
        if self._finished:
            total = self._total_len if self._total_len is not None else self.n_samples
            return total if pend is None else pend
        cands = [self._next_offset, self.n_samples - self.config.window]
        if pend is not None:
            cands.append(pend)
        return max(0, min(cands))

    def _curve(self, a: int, b: int) -> np.ndarray:
        """Final stitched curve over absolute [a, b) — the exact float32
        op sequence annotate applies to the stitched accumulators."""
        c = self.config
        lo, hi = a - self._curve_base, b - self._curve_base
        if c.combine == "mean":
            cur = self._acc[lo:hi] / np.maximum(self._hits[lo:hi], 1.0)[:, None]
            if c.channel0 == "non":
                # Coverage holes (abandoned windows) have zero hits, so
                # the raw quotient reads noise=0 -> strength 1-0 = 1.0:
                # a phantom full-strength detection spanning the hole.
                # Render holes as pure noise instead. Non-degraded
                # sessions never have zero-hit final samples, so the
                # offline-parity pin is untouched.
                hole = self._hits[lo:hi] == 0.0
                if hole.any():
                    cur[hole, 0] = 1.0
        else:
            cur = self._evmax[lo:hi].copy()
            if c.channel0 == "non":
                cur[:, 0] = np.float32(1.0) - cur[:, 0]
        return cur

    def _advance(self, at_end: bool = False) -> Dict[str, list]:
        c = self.config
        new_final = self._frontier()
        if at_end:
            new_final = self._total_len if self._total_len is not None else self.n_samples
        if new_final < self._final_upto:
            new_final = self._final_upto
        self._final_upto = max(self._final_upto, new_final)
        out: Dict[str, list] = {"ppk": [], "spk": [], "det": []}
        if new_final <= 0:
            return out
        # Pickers re-read a little context behind their cursors (peak
        # candidates need t-1); hand them the curve from the earliest
        # cursor - 1. Curve memory stays O(window + stride): cursors trail
        # the frontier by at most one component span.
        lo = max(0, min(self._ppk._scanned, self._spk._scanned, self._det._scanned) - 1)
        cur = self._curve(lo, new_final)
        strength = (
            np.float32(1.0) - cur[:, 0] if c.channel0 == "non" else cur[:, 0]
        )
        trim = self.n_samples if self._total_len == c.window else None
        for name, picker, chan in (("ppk", self._ppk, 1), ("spk", self._spk, 2)):
            got = picker.scan(cur[:, chan], lo, new_final, at_end)
            if trim is not None:  # short record: drop picks inside the pad
                got = [p for p in got if p < trim]
            out[name].extend(got)
            self._picks[name].extend(got)
        runs = self._det.scan(strength, lo, new_final, at_end)
        if trim is not None:  # clip detections at the true record end
            runs = [(on, min(off, trim - 1)) for on, off in runs if on < trim]
        out["det"].extend(runs)
        self._picks["det"].extend(runs)
        self._trim_curve()
        return out

    def _trim_curve(self) -> None:
        keep_from = max(
            0,
            min(self._ppk._scanned, self._spk._scanned, self._det._scanned) - 1,
        )
        # Never trim past unstitched territory either.
        keep_from = min(keep_from, self._final_upto)
        drop = keep_from - self._curve_base
        if drop > 256:  # amortize the copies
            if self.config.combine == "mean":
                self._acc = self._acc[drop:]
                self._hits = self._hits[drop:]
            else:
                self._evmax = self._evmax[drop:]
            self._curve_base = keep_from


def _znorm(win: np.ndarray) -> np.ndarray:
    """Per-window z-normalization, bit-identical to annotate's
    ``normalize(chunk, "std", axis=1)``: the reductions are per-window
    along the time axis, so a (1, window, C) batch of one reproduces the
    offline batch row exactly."""
    from seist_tpu.data.preprocess import normalize  # heavy import (pandas)

    return normalize(win[None], "std", axis=1)[0]
