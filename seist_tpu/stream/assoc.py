"""Cross-station association: co-detections -> event hypotheses -> alerts.

A single station's P pick is weak evidence; the early-warning decision
is made by the *network*. The :class:`Associator` keeps a moving window
of recent picks across all stations and, whenever enough distinct
stations co-detect, grid-searches candidate origins over the station
footprint: a hypothesis is the grid node that makes the most picks'
back-projected origin times (``t_pick - dist/velocity``) agree. When the
coherent set reaches ``min_stations``, an :class:`Alert` is emitted and
its contributing picks are consumed (one event does not re-alert as
later phases trickle in).

This is deliberately the coarse end of association — a plane-wave/grid
origin scorer, not a full locator: good enough to separate "N stations
saw the same event" from "N stations each saw noise," deterministic
(fixed grid order, explicit tie-breaks) so the digital twin
(tools/twin.py) can gate on exact alert behavior, and cheap (host-side,
O(picks x grid) per trigger).

Latency accounting: every pick carries its stage stamps (arrival ->
window-due -> queue -> device -> pick); the associator adds
``t_assoc``/``t_alert`` so an alert's ``latency_ms`` breaks the whole
sample->alert budget down per stage (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["AssocConfig", "Alert", "Associator", "StationPick"]

_EARTH_R_KM = 6371.0


@dataclass(frozen=True)
class AssocConfig:
    window_s: float = 30.0  # co-detection window across stations
    min_stations: int = 4  # distinct stations to form an event
    velocity_kms: float = 6.0  # P-wave moveout for back-projection
    grid_step_deg: float = 0.25  # origin search resolution
    margin_deg: float = 0.5  # search bbox margin past the footprint
    tolerance_s: float = 2.0  # origin-time coherence tolerance
    max_recent_alerts: int = 256  # alert ring retained for GET /stream/alerts
    # Exactly-once surface (docs/SERVING.md "Alert dedup"): a new
    # hypothesis within dedup_window_s AND one id grid cell of a recent
    # alert is the SAME event re-forming (failover replay, late phases
    # after a WAL'd emit) and is suppressed. Deliberately smaller than
    # any plausible inter-event time at one location — the digital
    # twin's aftershock refractory is 3 s, so distinct events never
    # fall inside the default window.
    dedup_window_s: float = 2.0
    dedup_dist_deg: float = 0.5  # spatial slack: subsets shift the origin
    id_grid_deg: float = 0.25  # alert-id origin cell size
    id_time_bucket_s: float = 5.0  # alert-id origin-time bucket


@dataclass(frozen=True)
class StationPick:
    station_id: str
    network: str
    lat: float
    lon: float
    t_s: float  # pick time in stream seconds (sample / sampling_rate)
    phase: str = "P"
    stamps: Dict[str, float] = field(default_factory=dict)


@dataclass
class Alert:
    event_id: int
    origin_lat: float
    origin_lon: float
    origin_t_s: float  # back-projected origin time (stream seconds)
    n_stations: int
    picks: List[StationPick] = field(default_factory=list)
    t_alert: float = 0.0  # wall-clock emission time
    latency_ms: Dict[str, float] = field(default_factory=dict)
    # Deterministic content-derived id, "ev-<cell>-<bucket>-<hash8>":
    # origin grid cell + origin-time bucket + station-set hash. A
    # failover replay that re-forms the event from the same picks mints
    # the SAME id (a consumer deduping on alert_id counts it once); two
    # replicas alerting on disjoint station subsets share the
    # cell+bucket prefix, which is what consumers group on to count
    # distinct events.
    alert_id: str = ""

    def to_dict(self) -> Dict:
        return {
            "event_id": self.event_id,
            "alert_id": self.alert_id,
            "origin": {
                "lat": round(self.origin_lat, 4),
                "lon": round(self.origin_lon, 4),
                "t_s": round(self.origin_t_s, 3),
            },
            "n_stations": self.n_stations,
            "picks": [
                {
                    "station": p.station_id,
                    "network": p.network,
                    "t_s": round(p.t_s, 3),
                    "phase": p.phase,
                }
                for p in self.picks
            ],
            "latency_ms": {k: round(v, 3) for k, v in self.latency_ms.items()},
        }


def _dist_km(lat1: float, lon1: float, lat2: float, lon2: float) -> float:
    """Equirectangular distance — plenty for regional association and
    monotone in true distance at these scales."""
    la1, la2 = math.radians(lat1), math.radians(lat2)
    dlat = la2 - la1
    dlon = math.radians(lon2 - lon1) * math.cos(0.5 * (la1 + la2))
    return _EARTH_R_KM * math.hypot(dlat, dlon)


class Associator:
    """Thread-safe pick buffer + grid origin scorer. ``add`` returns the
    alert it triggered, if any.

    Exactly-once surface ("never double-counts, never misses"): a
    hypothesis proximate to a recently emitted (or WAL-replayed) alert
    — within ``dedup_window_s`` and ``dedup_dist_deg`` — whose station
    set adds NOTHING over what those alerts already reported is a
    re-emission (the failover-replay signature) and is suppressed: its
    picks are consumed, ``on_dedup`` fires (the mux counts it into
    ``seist_alert_dedup_total``), but no second alert reaches any
    consumer. A proximate hypothesis that carries at least one NEW
    station is a genuine follow-up (a later moveout wave cohering) and
    is emitted — suppressing those would trade a duplicate for a missed
    detection, the wrong side of the alert-tier bargain. With a ``wal``
    attached, every alert is fsync'd to the WAL BEFORE ``add`` returns
    it (durable-before-visible); :meth:`seed_from_wal` replays the log
    after a restart so the dedup window survives the process."""

    def __init__(
        self,
        config: Optional[AssocConfig] = None,
        clock=None,
        wal=None,
        on_dedup=None,
    ) -> None:
        import time

        self.config = config or AssocConfig()
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._picks: List[StationPick] = []  # pending, time-ordered-ish
        self._alerts: List[Alert] = []
        self._next_event_id = 1
        self.alerts_total = 0
        self.alerts_deduped = 0
        self.wal = wal  # journal.AlertWAL-shaped: .append(dict), .replay()
        self.on_dedup = on_dedup  # called (no args) per suppressed alert
        # (lat, lon, t0, alert_id, station_ids) of recent emissions,
        # newest last; station_ids accumulate the dedup subset check.
        self._recent_events: List[tuple] = []

    # ------------------------------------------------------------- feed
    def add(self, pick: StationPick) -> Optional[Alert]:
        c = self.config
        with self._lock:
            self._picks.append(pick)
            horizon = pick.t_s - c.window_s
            self._picks = [p for p in self._picks if p.t_s >= horizon]
            if len({p.station_id for p in self._picks}) < c.min_stations:
                return None
            hypo = self._best_origin(self._picks)
            if hypo is None:
                return None
            lat, lon, t0, coherent = hypo
            if len({p.station_id for p in coherent}) < c.min_stations:
                return None
            # Consume the coherent picks either way: a suppressed
            # duplicate must not leave its picks around to re-form the
            # same hypothesis on the very next add().
            consumed = set(id(p) for p in coherent)
            self._picks = [p for p in self._picks if id(p) not in consumed]
            sids = {p.station_id for p in coherent}
            if self._is_duplicate(lat, lon, t0, sids):
                self.alerts_deduped += 1
                hook = self.on_dedup
                if hook is not None:
                    hook()
                return None
            return self._emit(lat, lon, t0, coherent)

    def recent_alerts(self, n: int = 50) -> List[Dict]:
        with self._lock:
            return [a.to_dict() for a in self._alerts[-n:]]

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "alerts": float(self.alerts_total),
                "alerts_deduped": float(self.alerts_deduped),
                "pending_picks": float(len(self._picks)),
            }

    # ------------------------------------------------------ exactly-once
    def alert_id_for(self, lat: float, lon: float, t0: float,
                     station_ids) -> str:
        """Deterministic alert id — see :class:`Alert`. Public so the
        chaos lane and consumers can recompute/group ids."""
        import hashlib

        c = self.config
        ci = int(round(lat / c.id_grid_deg))
        cj = int(round(lon / c.id_grid_deg))
        bt = int(math.floor(t0 / c.id_time_bucket_s))
        sids = ",".join(sorted(set(str(s) for s in station_ids)))
        h = hashlib.sha1(sids.encode()).hexdigest()[:8]
        return f"ev-{ci}:{cj}-{bt}-{h}"

    def _is_duplicate(self, lat: float, lon: float, t0: float,
                      sids) -> bool:
        """True iff the hypothesis is proximate to recent emissions AND
        its stations are all already reported by them (union over every
        proximate entry: an event whose picks arrived in two waves has
        two entries, and a replay re-forming from their union must still
        dedup)."""
        c = self.config
        seen: set = set()
        proximate = False
        for rlat, rlon, rt0, _rid, rsids in self._recent_events:
            if (
                abs(t0 - rt0) <= c.dedup_window_s
                and abs(lat - rlat) <= c.dedup_dist_deg
                and abs(lon - rlon) <= c.dedup_dist_deg
            ):
                proximate = True
                seen |= rsids
        return proximate and set(sids) <= seen

    def _note_recent(self, lat: float, lon: float, t0: float,
                     alert_id: str, sids) -> None:
        self._recent_events.append((lat, lon, t0, alert_id,
                                    frozenset(sids)))
        if len(self._recent_events) > 4 * self.config.max_recent_alerts:
            self._recent_events = self._recent_events[
                -self.config.max_recent_alerts :
            ]

    def seed_from_wal(self) -> int:
        """Replay the attached WAL into the dedup window (restart path).
        Returns the number of records seeded. Does not touch
        ``alerts_total`` — these alerts were already counted by the
        process that emitted them."""
        if self.wal is None:
            return 0
        n = 0
        with self._lock:
            for rec in self.wal.replay():
                origin = rec.get("origin") or {}
                try:
                    self._note_recent(
                        float(origin["lat"]),
                        float(origin["lon"]),
                        float(origin["t_s"]),
                        str(rec.get("alert_id") or ""),
                        {str(pk["station"])
                         for pk in rec.get("picks") or []},
                    )
                except (KeyError, TypeError, ValueError):
                    continue
                n += 1
        return n

    # ---------------------------------------------------------- scoring
    def _slack_s(self, step_deg: float) -> float:
        """Origin-time error from grid discretization: the true origin can
        sit half a grid diagonal from the nearest node."""
        return 0.5 * math.sqrt(2.0) * step_deg * 111.19 / self.config.velocity_kms

    def _score(self, picks: List[StationPick], glat: float, glon: float,
               tol: float):
        """(count, -spread, t0, coherent) at one candidate node: how many
        picks' back-projected origin times agree within ``tol`` of their
        median."""
        c = self.config
        ots = sorted(
            (
                (p.t_s - _dist_km(glat, glon, p.lat, p.lon) / c.velocity_kms, p)
                for p in picks
            ),
            key=lambda x: (x[0], x[1].station_id),
        )
        t_med = ots[len(ots) // 2][0]
        coherent = [(ot, p) for ot, p in ots if abs(ot - t_med) <= tol]
        if not coherent:
            return None
        # Residual-weighted soft count: a pick scores 1 at zero residual,
        # 0 at the tolerance edge. A raw count is degenerate — a far-away
        # node compresses moveout until unrelated picks BARELY cohere; a
        # node near the true origin fits fewer-or-equal picks nearly
        # exactly and must win.
        # fsum: exactly-rounded regardless of pairing order, so the score
        # (and the alert IDs downstream of t0) cannot drift by an ulp
        # when the coherent-pick list arrives chunked differently.
        soft = math.fsum(1.0 - abs(ot - t_med) / tol for ot, _ in coherent)
        spread = coherent[-1][0] - coherent[0][0]
        t0 = math.fsum(ot for ot, _ in coherent) / len(coherent)
        return (soft, len(coherent), -spread, t0, [p for _, p in coherent])

    def _best_origin(self, picks: List[StationPick]):
        """Deterministic two-stage grid search. The coarse pass needs its
        coherence tolerance widened by the discretization slack — but that
        widened tolerance is exactly what lets a far-away node fake
        coherence for unrelated picks (back-projected times compress with
        distance). So the coarse pass only NOMINATES nodes (top-8 by
        count/spread); the fine pass (step/5, proportionally tighter
        slack) around each nominee makes the final coherence decision.
        Ties break to the smaller spread, then grid order."""
        c = self.config
        lats = [p.lat for p in picks]
        lons = [p.lon for p in picks]
        lat0, lat1 = min(lats) - c.margin_deg, max(lats) + c.margin_deg
        lon0, lon1 = min(lons) - c.margin_deg, max(lons) + c.margin_deg
        step = c.grid_step_deg
        coarse_tol = c.tolerance_s + self._slack_s(step)
        steps = lambda a, b: max(1, int(round((b - a) / step)) + 1)
        scored = []
        for i in range(steps(lat0, lat1)):
            glat = lat0 + i * step
            for j in range(steps(lon0, lon1)):
                glon = lon0 + j * step
                got = self._score(picks, glat, glon, coarse_tol)
                if got is not None:
                    scored.append((got[0], got[1], got[2], i, j, glat, glon))
        if not scored:
            return None
        scored.sort(key=lambda s: (-s[0], -s[1], -s[2], s[3], s[4]))
        fine_step = step / 5.0
        fine_tol = c.tolerance_s + self._slack_s(fine_step)
        best = None  # ((soft, count, -spread), lat, lon, t0, coherent)
        for _, _, _, _, _, nlat, nlon in scored[:8]:
            for di in range(-5, 6):
                for dj in range(-5, 6):
                    glat = nlat + di * fine_step
                    glon = nlon + dj * fine_step
                    got = self._score(picks, glat, glon, fine_tol)
                    if got is None:
                        continue
                    soft, count, nspread, t0, coherent = got
                    key = (soft, count, nspread)
                    if best is None or key > best[0]:
                        best = (key, glat, glon, t0, coherent)
        if best is None:
            return None
        _, glat, glon, t0, coherent = best
        return glat, glon, t0, coherent

    def _emit(self, lat, lon, t0, coherent: List[StationPick]) -> Alert:
        now = self._clock()
        latency: Dict[str, float] = {}
        # Per-stage budget: worst (max) stage latency over contributing
        # picks — the straggler is what the alert actually waited on.
        for a, b, name in (
            ("arrival", "due", "arrival_to_due"),
            ("due", "submitted", "due_to_queue"),
            ("submitted", "returned", "queue_device"),
            ("returned", "picked", "pick"),
        ):
            vals = [
                (p.stamps[b] - p.stamps[a]) * 1000.0
                for p in coherent
                if a in p.stamps and b in p.stamps
            ]
            if vals:
                latency[name] = max(vals)
        picked = [p.stamps.get("picked") for p in coherent]
        picked = [t for t in picked if t is not None]
        if picked:
            latency["association"] = (now - max(picked)) * 1000.0
        arrivals = [p.stamps.get("arrival") for p in coherent]
        arrivals = [t for t in arrivals if t is not None]
        if arrivals:
            latency["sample_to_alert"] = (now - min(arrivals)) * 1000.0
        alert = Alert(
            event_id=self._next_event_id,
            origin_lat=lat,
            origin_lon=lon,
            origin_t_s=t0,
            n_stations=len({p.station_id for p in coherent}),
            picks=sorted(coherent, key=lambda p: (p.t_s, p.station_id)),
            t_alert=now,
            latency_ms=latency,
            alert_id=self.alert_id_for(
                lat, lon, t0, (p.station_id for p in coherent)
            ),
        )
        self._next_event_id += 1
        self.alerts_total += 1
        self._alerts.append(alert)
        if len(self._alerts) > self.config.max_recent_alerts:
            self._alerts = self._alerts[-self.config.max_recent_alerts :]
        self._note_recent(lat, lon, t0, alert.alert_id,
                          (p.station_id for p in coherent))
        if self.wal is not None:
            # Durable-before-visible: the WAL line lands (fsync) before
            # any caller can observe the alert. A crash right here
            # re-forms and re-suppresses on replay; a crash after is a
            # delivered alert that replay dedups. Either way the
            # consumer sees exactly one.
            self.wal.append(alert.to_dict())
        return alert
