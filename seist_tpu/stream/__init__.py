"""Continuous-stream early-warning engine.

Three layers on top of the offline ``ops/stream.annotate`` path:

* :mod:`seist_tpu.stream.session` — per-station :class:`StreamSession`
  carrying overlap context between packets so each packet costs one
  stride of model compute, with picks provably identical to offline
  ``annotate`` on the concatenated record (the parity pin).
* :mod:`seist_tpu.stream.mux` — :class:`StationMux` funnels thousands
  of sessions' due windows through the serve replica's MicroBatcher/AOT
  pool as one tenant (zero new compiles).
* :mod:`seist_tpu.stream.assoc` — :class:`Associator` clusters
  co-detections across stations into event hypotheses and emits alerts
  with per-stage latency stamps.

Serve endpoint: ``POST /stream`` (seist_tpu/serve/server.py).
Acceptance harness: ``tools/twin.py`` (the network digital twin) and
``tools/stream_smoke.py``; see docs/SERVING.md "Streaming inference".
"""

from seist_tpu.stream.assoc import Alert, Associator, AssocConfig
from seist_tpu.stream.mux import MuxConfig, StationMux
from seist_tpu.stream.session import DueWindow, SessionConfig, StreamSession

__all__ = [
    "Alert",
    "Associator",
    "AssocConfig",
    "DueWindow",
    "MuxConfig",
    "SessionConfig",
    "StationMux",
    "StreamSession",
]
