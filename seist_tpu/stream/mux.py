"""StationMux: thousands of StreamSessions through ONE model tenant.

Sessions are host-side state (ring buffer + stitch accumulators, a few
hundred KB each); the device never learns stations exist. Every due
window is submitted through the serve replica's MicroBatcher as an
ordinary single-window request, so thousands of stations' windows
coalesce into the SAME warm AOT bucket programs the /predict path runs —
zero new compiles (CompileBudget-pinned in tests/test_stream_mux.py).

Concurrency model: one lock per station keeps each session's
push -> submit -> integrate sequence ordered (a session is not
thread-safe); different stations proceed in parallel, and the batcher
flush is where their windows meet. A packet's handler thread blocks in
``submit`` exactly like a /predict caller — per-station backpressure is
the batcher's bounded queue + the shed ladder, surfaced per station:

* a QueueFull/Overloaded on a due window counts into
  ``windows_dropped`` and marks the session DEGRADED (its stitched
  curve now has a coverage hole; picks remain well-defined — the mean
  stitch divides by actual hits — but the offline-parity pin no longer
  holds for that station), and the error propagates so the transport
  returns 429/503 and the station backs off;
* duplicate packets (``seq`` <= last seen) are dropped idempotently;
  sequence gaps are counted but the stream continues (the session
  stitches what actually arrived).

Stage stamps (arrival -> due -> queue -> device -> pick) ride every
emitted pick into the associator, which completes the
sample -> alert latency budget (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional

import numpy as np

from seist_tpu.stream.assoc import Associator, StationPick
from seist_tpu.stream.session import SessionConfig, StreamSession

__all__ = ["MuxClosed", "MuxConfig", "StationMux", "StationLimit"]


class StationLimit(Exception):
    """New station rejected: the mux is at ``max_stations``."""


class MuxClosed(Exception):
    """Packet rejected: the mux is shut down (``close_all`` ran).

    The structured answer to the close-vs-feed race: a feed that loses
    the race gets THIS (the server maps it to 503 shutting_down, which
    the router retries on a survivor) — it never integrates into a
    session that shutdown already journaled and released."""


@dataclass(frozen=True)
class MuxConfig:
    session: SessionConfig = field(default_factory=SessionConfig)
    max_stations: int = 4096
    idle_timeout_s: float = 900.0  # reap sessions idle this long
    journal_every_s: float = 5.0  # per-station journal cadence (with journal)
    model: str = ""  # metrics label


class _Entry:
    __slots__ = (
        "session", "lock", "last_seq", "degraded", "dropped",
        "duplicates", "gaps", "last_feed", "station", "closed",
        "last_journal",
    )

    def __init__(self, session: StreamSession, station: Dict[str, object]):
        self.session = session
        self.lock = threading.Lock()
        self.last_seq: Optional[int] = None
        self.degraded = False
        self.dropped = 0
        self.duplicates = 0
        self.gaps = 0
        self.last_feed = 0.0
        self.station = station
        self.closed = False
        self.last_journal = 0.0


class StationMux:
    """Funnel per-station packets into due windows, through ``submit``
    (the batcher), back into sessions, and picks into the associator.

    ``submit``: (window, C) float32 -> (window, 3) float32 probabilities
    — typically ``lambda x: batcher.submit(x, timeout_ms=...)[0]``.
    """

    def __init__(
        self,
        submit: Callable[[np.ndarray], np.ndarray],
        config: MuxConfig,
        assoc: Optional[Associator] = None,
        clock: Callable[[], float] = time.monotonic,
        journal=None,  # journal.StationJournal; None = no durability
    ) -> None:
        self.config = config
        self.assoc = assoc or Associator()
        self._submit = submit
        self._clock = clock
        self._journal = journal
        self._lock = threading.Lock()
        self._closed = False
        self._entries: Dict[str, _Entry] = {}
        self._counts = {
            "packets": 0, "windows": 0, "windows_dropped": 0,
            "duplicates": 0, "gaps": 0, "picks": 0, "alerts": 0,
            "alerts_deduped": 0, "journal_writes": 0, "restores": 0,
            "restores_failed": 0,
            "sessions_opened": 0, "sessions_closed": 0, "sessions_reaped": 0,
        }
        from seist_tpu.obs.bus import BUS

        lbl = {"model": config.model or "default"}
        # Counter names WITHOUT the _total suffix: the prometheus
        # renderer appends it (seist_stream_packets_total on the wire).
        self._m_packets = BUS.counter("stream_packets", **lbl)
        self._m_windows = BUS.counter("stream_windows", **lbl)
        self._m_dropped = BUS.counter("stream_windows_dropped", **lbl)
        self._m_dups = BUS.counter("stream_duplicate_packets", **lbl)
        self._m_gaps = BUS.counter("stream_sequence_gaps", **lbl)
        self._m_picks = BUS.counter("stream_picks", **lbl)
        self._m_alerts = BUS.counter("assoc_alerts", **lbl)
        self._m_dedup = BUS.counter("alert_dedup", **lbl)
        self._m_journal = BUS.counter("stream_journal_writes", **lbl)
        self._m_restores = BUS.counter("stream_session_restores", **lbl)
        self._m_restore_failed = BUS.counter("stream_restore_failed", **lbl)
        self._m_sessions = BUS.gauge("stream_sessions", **lbl)
        self._m_window_ms = BUS.histogram("stream_window_latency_ms", **lbl)
        self._m_alert_ms = BUS.histogram("assoc_sample_to_alert_ms", **lbl)
        if self.assoc.on_dedup is None:
            # Surface the associator's exactly-once suppressions as
            # seist_alert_dedup_total. Lock order stays acyclic: the
            # hook runs under assoc._lock and takes mux._lock — the
            # established order is entry.lock -> assoc._lock ->
            # mux._lock, and nothing takes them the other way around
            # (stats() reads the associator AFTER dropping mux._lock).
            self.assoc.on_dedup = self._on_dedup

    def _on_dedup(self) -> None:
        self._count("alerts_deduped", self._m_dedup)

    # ------------------------------------------------------------- feed
    def feed(
        self,
        station: Mapping[str, object],
        data: np.ndarray,
        *,
        seq: Optional[int] = None,
        end: bool = False,
        t_arrival: Optional[float] = None,
    ) -> Dict[str, object]:
        """Process one packet for ``station`` (needs at least ``id``;
        ``lat``/``lon`` enable association). Returns the per-packet
        result: windows run, newly final picks, any alerts triggered."""
        sid = str(station.get("id") or "")
        if not sid:
            raise ValueError("station.id is required")
        now = self._clock()
        t_arrival = now if t_arrival is None else t_arrival
        entry = self._entry_for(sid, station)
        with entry.lock:
            if entry.closed:
                # Lost the race against close_all(): the session was
                # journaled and released; integrating now would mutate
                # state the failover successor has already adopted.
                raise MuxClosed(f"station mux closed (station {sid!r})")
            entry.last_feed = now
            self._count("packets", self._m_packets)
            if seq is not None:
                if entry.last_seq is not None and seq <= entry.last_seq:
                    entry.duplicates += 1
                    self._count("duplicates", self._m_dups)
                    return self._result(sid, entry, duplicate=True)
                if entry.last_seq is not None and seq > entry.last_seq + 1:
                    entry.gaps += 1
                    self._count("gaps", self._m_gaps)
                entry.last_seq = seq
            sess = entry.session
            picks = {"ppk": [], "spk": [], "det": []}
            alerts: List[Dict] = []
            n_windows = 0
            due = sess.push(np.asarray(data, np.float32))
            if end:
                due = due + sess.finish()
            for i, w in enumerate(due):
                n_windows += 1
                try:
                    self._run_window(entry, w, t_arrival, picks, alerts)
                except Exception:
                    # The batcher refused this window; the transport is
                    # about to surface that. The REST of this packet's
                    # due windows would otherwise sit in _pending
                    # forever (the retried packet is a duplicate seq and
                    # is dropped idempotently) — abandon them too, so
                    # the frontier keeps moving past the coverage hole.
                    for w2 in due[i + 1 :]:
                        self._abandon_window(
                            entry, w2.offset, t_arrival, picks, alerts
                        )
                    raise
            if end:
                t_fin = self._clock()
                tail = sess.finalize()
                self._merge(picks, tail)
                self._route_picks(entry, tail, alerts, stamps={
                    "arrival": t_arrival, "due": t_fin, "submitted": t_fin,
                    "returned": t_fin, "picked": t_fin,
                })
                self._close(sid, "sessions_closed")
            n_picks = sum(len(v) for v in picks.values())
            if n_picks:
                self._count("picks", self._m_picks, n_picks)
            if (
                self._journal is not None
                and not end
                and now - entry.last_journal >= self.config.journal_every_s
            ):
                self._journal_entry(sid, entry, now)
            return self._result(
                sid, entry, windows=n_windows, picks=picks, alerts=alerts,
                closed=end,
            )

    # ------------------------------------------------------- inspection
    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = {k: float(v) for k, v in self._counts.items()}
            out["sessions"] = float(len(self._entries))
            out["degraded_sessions"] = float(
                sum(1 for e in self._entries.values() if e.degraded)
            )
        out.update({f"assoc_{k}": v for k, v in self.assoc.stats().items()})
        return out

    def reap_idle(self) -> int:
        """Drop sessions idle past ``idle_timeout_s`` (no tail forward —
        an idle station's final partial window is stale by definition;
        the journal goes with it, so a resurrected station re-warms
        fresh instead of restoring ancient state)."""
        cutoff = self._clock() - self.config.idle_timeout_s
        reaped: List[str] = []
        with self._lock:
            for sid in [
                s for s, e in self._entries.items() if e.last_feed < cutoff
            ]:
                del self._entries[sid]
                self._counts["sessions_reaped"] += 1
                reaped.append(sid)
            self._m_sessions.set(float(len(self._entries)))
        if self._journal is not None:
            for sid in reaped:
                self._journal.remove(sid)
        return len(reaped)

    def close_all(self) -> None:
        """Shut the mux down for good: drain or reject every in-flight
        feed, journal each session's final state (the failover handoff),
        release the registry. Three phases so the lock order stays
        acyclic (feed holds entry.lock and then takes mux._lock inside
        ``_count`` — close_all must NEVER hold mux._lock while waiting
        on an entry lock, or the two deadlock; ``make lockgraph`` pins
        this):

        1. under mux._lock: latch ``_closed`` (new stations bounce with
           :class:`MuxClosed`), snapshot the entries;
        2. per entry, under entry.lock only: waiting for the lock IS the
           drain — an in-flight feed finishes its push -> submit ->
           integrate sequence first; then mark the entry closed (a feed
           that was still waiting on the lock rejects on wake) and
           journal the now-quiescent session;
        3. under mux._lock: clear the registry.
        """
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._entries.items())
        now = self._clock()
        for sid, entry in entries:
            with entry.lock:
                entry.closed = True
                self._journal_entry(sid, entry, now)
        with self._lock:
            self._counts["sessions_closed"] += len(self._entries)
            self._entries.clear()
            self._m_sessions.set(0.0)

    @property
    def n_sessions(self) -> int:
        with self._lock:
            return len(self._entries)

    # ---------------------------------------------------------- innards
    def _entry_for(self, sid: str, station: Mapping[str, object]) -> _Entry:
        with self._lock:
            if self._closed:
                raise MuxClosed("station mux closed")
            entry = self._entries.get(sid)
            if entry is None:
                if len(self._entries) >= self.config.max_stations:
                    raise StationLimit(
                        f"station mux at capacity ({self.config.max_stations})"
                    )
                entry = self._restored_entry_locked(sid, station)
                if entry is None:
                    entry = _Entry(
                        StreamSession(self.config.session), dict(station)
                    )
                self._entries[sid] = entry
                self._counts["sessions_opened"] += 1
                self._m_sessions.set(float(len(self._entries)))
            else:
                # Latest metadata wins (a station can learn its coords late).
                for k in ("network", "lat", "lon"):
                    if k in station:
                        entry.station[k] = station[k]
            return entry

    def _restored_entry_locked(
        self, sid: str, station: Mapping[str, object]
    ) -> Optional[_Entry]:
        """Failover adoption: a station this mux has never seen whose
        journal exists was homed on a dead replica — resume its session
        at the journal watermark. Any failure (corrupt file, version
        skew, config drift) falls back to a fresh session: the stream
        plane already stitches through sequence gaps, so re-warming is
        degraded, not broken. Called under ``self._lock`` (first packet
        of a station only), so counters are bumped inline."""
        if self._journal is None:
            return None
        state = self._journal.load(sid)
        if state is None:
            return None
        try:
            sess = StreamSession.restore(state)
            if sess.config != self.config.session:
                raise ValueError("journaled config != mux session config")
        except Exception:  # noqa: BLE001 - journal loss => fresh session
            self._counts["restores_failed"] += 1
            self._m_restore_failed.inc()
            return None
        mx = state["meta"].get("mux") or {}
        st = dict(mx.get("station") or {})
        st.update(station)
        entry = _Entry(sess, st)
        last_seq = mx.get("last_seq")
        entry.last_seq = None if last_seq is None else int(last_seq)
        entry.degraded = bool(mx.get("degraded", False))
        entry.dropped = int(mx.get("dropped", 0))
        entry.duplicates = int(mx.get("duplicates", 0))
        entry.gaps = int(mx.get("gaps", 0))
        self._counts["restores"] += 1
        self._m_restores.inc()
        return entry

    def _journal_entry(self, sid: str, entry: _Entry, now: float) -> None:
        """Write one station's journal record (caller holds entry.lock,
        so the session is quiescent — no pending windows). Best-effort:
        a failed write costs durability, not the stream."""
        if self._journal is None or entry.session._finished:
            return
        try:
            state = entry.session.snapshot()
            state["meta"]["mux"] = {
                "last_seq": entry.last_seq,
                "station": dict(entry.station),
                "degraded": entry.degraded,
                "dropped": entry.dropped,
                "duplicates": entry.duplicates,
                "gaps": entry.gaps,
            }
            self._journal.write(sid, state)
        except Exception:  # noqa: BLE001 - durability is best-effort
            return
        entry.last_journal = now
        self._count("journal_writes", self._m_journal)

    def _run_window(self, entry, w, t_arrival, picks, alerts) -> None:
        t_due = self._clock()
        try:
            t_sub = self._clock()
            probs = self._submit(w.data)
            t_ret = self._clock()
        except Exception:
            # Backpressure: the batcher queue (QueueFull) or the shed
            # ladder (Overloaded) refused the window. The curve keeps a
            # coverage hole; parity for this station is gone — say so.
            self._abandon_window(entry, w.offset, t_arrival, picks, alerts)
            raise
        probs = np.asarray(probs, np.float32)
        if probs.ndim == 3:  # batcher returns the leading-dim-1 slice
            probs = probs[0]
        got = entry.session.integrate(w.offset, probs)
        t_picked = self._clock()
        self._count("windows", self._m_windows)
        self._m_window_ms.observe((t_ret - t_sub) * 1000.0)
        stamps = {
            "arrival": t_arrival, "due": t_due, "submitted": t_sub,
            "returned": t_ret, "picked": t_picked,
        }
        self._merge(picks, got)
        self._route_picks(entry, got, alerts, stamps=stamps)

    def _abandon_window(
        self, entry, offset, t_arrival, picks, alerts
    ) -> None:
        """Account a refused window and un-wedge the finality frontier:
        without ``session.abandon`` the offset would gate finality
        forever and the station never emits another pick. Picks that
        became final across the new coverage hole still flow to the
        associator — a degraded station keeps contributing."""
        entry.dropped += 1
        entry.degraded = True
        self._count("windows_dropped", self._m_dropped)
        try:
            got = entry.session.abandon(offset)
        except Exception:  # noqa: BLE001 - the transport error wins
            return
        t_now = self._clock()
        self._merge(picks, got)
        self._route_picks(entry, got, alerts, stamps={
            "arrival": t_arrival, "due": t_now, "submitted": t_now,
            "returned": t_now, "picked": t_now,
        })

    def _route_picks(self, entry, got, alerts, stamps) -> None:
        """P picks with known coordinates go to the associator."""
        if stamps is None:
            return
        st = entry.station
        lat, lon = st.get("lat"), st.get("lon")
        if lat is None or lon is None:
            return
        fs = self.config.session.sampling_rate
        for p in got.get("ppk", ()):
            alert = self.assoc.add(
                StationPick(
                    station_id=str(st.get("id")),
                    network=str(st.get("network") or ""),
                    lat=float(lat),
                    lon=float(lon),
                    t_s=p / fs,
                    phase="P",
                    stamps=dict(stamps),
                )
            )
            if alert is not None:
                alerts.append(alert.to_dict())
                self._count("alerts", self._m_alerts)
                s2a = alert.latency_ms.get("sample_to_alert")
                if s2a is not None:
                    self._m_alert_ms.observe(s2a)

    @staticmethod
    def _merge(into: Dict[str, list], got: Dict[str, list]) -> None:
        for k in ("ppk", "spk", "det"):
            into[k].extend(got.get(k, ()))

    def _close(self, sid: str, key: str) -> None:
        with self._lock:
            if sid in self._entries:
                del self._entries[sid]
                self._counts[key] += 1
                self._m_sessions.set(float(len(self._entries)))
        if self._journal is not None:
            # A cleanly finished stream needs no failover handoff.
            self._journal.remove(sid)

    def _count(self, key: str, metric, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n
        metric.inc(n)

    def _result(self, sid, entry, windows=0, picks=None, alerts=None,
                duplicate=False, closed=False) -> Dict[str, object]:
        return {
            "station": sid,
            "windows": windows,
            "picks": picks or {"ppk": [], "spk": [], "det": []},
            "alerts": alerts or [],
            "duplicate": duplicate,
            "closed": closed,
            "degraded": entry.degraded,
            "dropped_windows": entry.dropped,
            "n_samples": entry.session.n_samples,
        }
