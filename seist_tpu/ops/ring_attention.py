"""Ring attention: sequence-parallel exact attention over the ``seq`` mesh axis.

The reference has no long-context support at all (SURVEY.md §5: sequence
length is a flag and the whole window lives on one device). This module is
the TPU-native capability the mesh's ``seq`` axis exists for: shard the
sequence over devices, keep Q blocks resident, and rotate K/V blocks around
the ring with ``lax.ppermute`` while accumulating the softmax online
(flash-attention style running max/denominator), so attention over a
sequence of length L uses O(L/D) memory per device and the K/V transfers
ride ICI neighbor links.

Math: per-block scores s_i = q k_i^T * scale; with running (o, m, l):
    m' = max(m, max_j s_ij);  corr = exp(m - m')
    l' = l * corr + sum_j exp(s_ij - m')
    o' = o * corr + exp(s_i - m') v_i
and o / l at the end equals exact softmax attention — every device sees
every K/V block after axis_size rotations, so no approximation is made.

Post-softmax probability dropout (ref seist.py:383-388) is exact under the
online accumulation too: dense applies ``mask/(1-rate)`` to the softmax
probabilities p_ij = exp(s_ij - m_final)/l_final and then multiplies by V.
Masking is linear in the numerator and the softmax denominator is built
from the *unmasked* probabilities, so the ring applies the mask (with the
survivor scale) to each block's exp-numerator contribution to ``o`` while
``l`` keeps accumulating unmasked — ``o/l`` then equals dense-with-dropout
numerically up to fp reassociation of the online sums (the tests assert
rtol/atol ~2e-5..2e-4), with the *same* dropout mask. The mask comes
from the same counter-based
PRNG the fused/einsum paths share (pallas_attention._mix_to_uniform),
indexed by *global* (batch, head, row, col) so every device regenerates
exactly its slice of the dense mask.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from seist_tpu.parallel.mesh import AXIS_SEQ


def _rotate(x, axis_name: str, axis_size: int):
    """Send this device's block to the next ring neighbor."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis_name, perm)


def _block_dropout_mult(
    seed,
    rate: float,
    n: int,
    h: int,
    lq: int,
    mk: int,
    n0,
    row0,
    col0,
    l_total: int,
    m_total: int,
):
    """(n, h, lq, mk) multiplier — 0 where dropped, 1/(1-rate) where kept —
    equal to the dense path's mask slice at global offsets (n0, row0, col0).

    Dense (_einsum_attention) hashes x = (n*H + h)·(L·M) + row·M + col in
    wrapping int32; regenerating with global indices reproduces it exactly
    (heads are never sharded here — the mesh's model axis is size 1 by
    design — so the local ``h`` is the global head count).
    """
    from seist_tpu.ops.pallas_attention import _mix_to_uniform, _wrap_i32

    ni = lax.broadcasted_iota(jnp.int32, (n, h, lq, mk), 0) + n0
    hi = lax.broadcasted_iota(jnp.int32, (n, h, lq, mk), 1)
    ri = lax.broadcasted_iota(jnp.int32, (n, h, lq, mk), 2) + row0
    ci = lax.broadcasted_iota(jnp.int32, (n, h, lq, mk), 3) + col0
    # _wrap_i32: counters wrap mod 2^32 identically to the dense path even
    # when global L*M exceeds int32 (long-context --seq-shards runs).
    x = (
        (ni * _wrap_i32(h) + hi) * _wrap_i32(l_total * m_total)
        + ri * _wrap_i32(m_total)
        + ci
    )
    u = _mix_to_uniform(x, seed)
    keep = u >= jnp.float32(rate)
    return jnp.where(keep, jnp.float32(1.0 / (1.0 - rate)), 0.0)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS_SEQ,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jnp.ndarray] = None,
    batch_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Per-device body (call inside ``shard_map``): local blocks
    ``q (N, Lq, H, E)``, ``k/v (N, Lk, H, E)`` sharded on the sequence axis.

    Returns the local ``(N, Lq, H, E)`` output block of exact attention over
    the *global* sequence. ``dropout_rate`` > 0 applies the dense path's
    post-softmax probability dropout exactly (see module docstring);
    ``batch_axis`` must name the batch-sharding mesh axis (or None) so the
    global batch index offsets the mask stream.
    """
    n, lq, h, e = q.shape
    mk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    axis_size = lax.psum(1, axis_name)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        seq_idx = lax.axis_index(axis_name)
        n0 = (
            lax.axis_index(batch_axis) * n
            if batch_axis is not None
            else jnp.int32(0)
        )
        row0 = seq_idx * lq
        l_total = lq * axis_size
        m_total = mk * axis_size

    def accumulate(o, m, l, k_blk, v_blk, src_idx):
        s = jnp.einsum(
            "nlhe,nmhe->nhlm", q * scale, k_blk, preferred_element_type=jnp.float32
        )
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        if dropout_rate > 0.0:
            # Mask the numerator contribution only; `l` stays unmasked —
            # post-softmax dropout divides by the full softmax denominator.
            mult = _block_dropout_mult(
                dropout_seed[0],
                float(dropout_rate),
                n,
                h,
                lq,
                mk,
                n0,
                row0,
                src_idx * mk,
                l_total,
                m_total,
            )
            p = p * mult
        o_new = o * corr[..., None] + jnp.einsum(
            "nhlm,nmhe->nhle", p, v_blk, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o = jnp.zeros((n, h, lq, e), dtype=jnp.float32)
    m = jnp.full((n, h, lq), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((n, h, lq), dtype=jnp.float32)
    if hasattr(lax, "pcast"):
        # Newer shard_map tracks varying-axis types through scan: the carry
        # becomes seq-varying after one step, so the initial values must be
        # marked varying too. (pcast replaced the deprecated lax.pvary.)
        o, m, l = (lax.pcast(t, (axis_name,), to="varying") for t in (o, m, l))
    elif "pvary" in dir(lax):  # pragma: no cover - pre-pcast jax
        o, m, l = (lax.pvary(t, (axis_name,)) for t in (o, m, l))

    # Peel the first (local-block) step so the scan rotates BEFORE each
    # accumulation — axis_size-1 rotations total, none wasted on a block
    # that would be discarded.
    my_idx = lax.axis_index(axis_name)
    o, m, l = accumulate(
        o, m, l, k.astype(jnp.float32), v.astype(jnp.float32), my_idx
    )

    def body(carry, t):
        o, m, l, k_blk, v_blk = carry
        k_blk = _rotate(k_blk, axis_name, axis_size)
        v_blk = _rotate(v_blk, axis_name, axis_size)
        # After t forward rotations this device holds the block that
        # originated at ring position (my_idx - t) mod axis_size.
        src_idx = lax.rem(my_idx - t + axis_size, axis_size)
        o, m, l = accumulate(o, m, l, k_blk, v_blk, src_idx)
        return (o, m, l, k_blk, v_blk), None

    if axis_size > 1:
        (o, m, l, _, _), _ = lax.scan(
            body,
            (o, m, l, k.astype(jnp.float32), v.astype(jnp.float32)),
            jnp.arange(1, axis_size, dtype=jnp.int32),
        )
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = AXIS_SEQ,
    batch_axis: Optional[str] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Exact attention with Q/K/V ``(N, L, H, E)`` sequence-sharded over
    ``mesh[seq_axis]``. Global L (and K/V's M) must divide evenly by the
    axis size. ``batch_axis`` additionally shards the batch dim — pass
    ``'data'`` when calling inside a data-parallel jitted step so the
    shard_map composes with DP instead of gathering the batch.

    ``dropout_rate`` > 0 applies post-softmax probability dropout with
    semantics (and the exact mask) of the dense/fused paths — pass the same
    (1,) int32 ``dropout_seed`` the fused kernel takes."""
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if dropout_seed is None:
        dropout_seed = jnp.zeros((1,), jnp.int32)
    dropout_seed = dropout_seed.astype(jnp.int32)
    spec = P(batch_axis, seq_axis, None, None)
    seed_spec = P()  # replicated
    body = partial(
        ring_attention_local,
        axis_name=seq_axis,
        scale=scale,
        dropout_rate=float(dropout_rate),
        batch_axis=batch_axis,
    )

    def wrapped(q, k, v, seed):
        return body(q, k, v, dropout_seed=seed)

    in_specs = (spec, spec, spec, seed_spec)
    try:
        from jax import shard_map

        fn = shard_map(
            wrapped, mesh=mesh, in_specs=in_specs, out_specs=spec
        )
    except ImportError:  # older jax keeps the experimental path + check_rep
        from jax.experimental.shard_map import shard_map

        fn = shard_map(
            wrapped,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=spec,
            check_rep=False,
        )
    return fn(q, k, v, dropout_seed)


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-device reference: plain softmax attention over (N, L, H, E).
    Shared implementation — see pallas_attention._einsum_attention."""
    from seist_tpu.ops.pallas_attention import _einsum_attention

    e = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    return _einsum_attention(q, k, v, scale)
