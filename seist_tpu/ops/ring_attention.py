"""Ring attention: sequence-parallel exact attention over the ``seq`` mesh axis.

The reference has no long-context support at all (SURVEY.md §5: sequence
length is a flag and the whole window lives on one device). This module is
the TPU-native capability the mesh's ``seq`` axis exists for: shard the
sequence over devices, keep Q blocks resident, and rotate K/V blocks around
the ring with ``lax.ppermute`` while accumulating the softmax online
(flash-attention style running max/denominator), so attention over a
sequence of length L uses O(L/D) memory per device and the K/V transfers
ride ICI neighbor links.

Math: per-block scores s_i = q k_i^T * scale; with running (o, m, l):
    m' = max(m, max_j s_ij);  corr = exp(m - m')
    l' = l * corr + sum_j exp(s_ij - m')
    o' = o * corr + exp(s_i - m') v_i
and o / l at the end equals exact softmax attention — every device sees
every K/V block after axis_size rotations, so no approximation is made.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from seist_tpu.parallel.mesh import AXIS_SEQ


def _rotate(x, axis_name: str, axis_size: int):
    """Send this device's block to the next ring neighbor."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis_name, perm)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = AXIS_SEQ,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-device body (call inside ``shard_map``): local blocks
    ``q (N, Lq, H, E)``, ``k/v (N, Lk, H, E)`` sharded on the sequence axis.

    Returns the local ``(N, Lq, H, E)`` output block of exact attention over
    the *global* sequence.
    """
    n, lq, h, e = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    axis_size = lax.psum(1, axis_name)

    def accumulate(o, m, l, k_blk, v_blk):
        s = jnp.einsum(
            "nlhe,nmhe->nhlm", q * scale, k_blk, preferred_element_type=jnp.float32
        )
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "nhlm,nmhe->nhle", p, v_blk, preferred_element_type=jnp.float32
        )
        return o_new, m_new, l_new

    o = jnp.zeros((n, h, lq, e), dtype=jnp.float32)
    m = jnp.full((n, h, lq), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((n, h, lq), dtype=jnp.float32)
    if hasattr(lax, "pvary"):
        # Newer shard_map tracks varying-axis types through scan: the carry
        # becomes seq-varying after one step, so the initial values must be
        # marked varying too.
        o, m, l = (lax.pvary(t, (axis_name,)) for t in (o, m, l))

    # Peel the first (local-block) step so the scan rotates BEFORE each
    # accumulation — axis_size-1 rotations total, none wasted on a block
    # that would be discarded.
    o, m, l = accumulate(o, m, l, k.astype(jnp.float32), v.astype(jnp.float32))

    def body(carry, _):
        o, m, l, k_blk, v_blk = carry
        k_blk = _rotate(k_blk, axis_name, axis_size)
        v_blk = _rotate(v_blk, axis_name, axis_size)
        o, m, l = accumulate(o, m, l, k_blk, v_blk)
        return (o, m, l, k_blk, v_blk), None

    if axis_size > 1:
        (o, m, l, _, _), _ = lax.scan(
            body,
            (o, m, l, k.astype(jnp.float32), v.astype(jnp.float32)),
            None,
            length=axis_size - 1,
        )
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = AXIS_SEQ,
    batch_axis: Optional[str] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention with Q/K/V ``(N, L, H, E)`` sequence-sharded over
    ``mesh[seq_axis]``. Global L (and K/V's M) must divide evenly by the
    axis size. ``batch_axis`` additionally shards the batch dim — pass
    ``'data'`` when calling inside a data-parallel jitted step so the
    shard_map composes with DP instead of gathering the batch."""
    spec = P(batch_axis, seq_axis, None, None)
    body = partial(ring_attention_local, axis_name=seq_axis, scale=scale)
    try:
        from jax import shard_map

        fn = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
    except ImportError:  # older jax keeps the experimental path + check_rep
        from jax.experimental.shard_map import shard_map

        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_rep=False,
        )
    return fn(q, k, v)


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-device reference: plain softmax attention over (N, L, H, E).
    Shared implementation — see pallas_attention._einsum_attention."""
    from seist_tpu.ops.pallas_attention import _einsum_attention

    e = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    return _einsum_attention(q, k, v, scale)
