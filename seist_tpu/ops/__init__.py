"""On-device output processing: picking, event detection, metrics.

The reference runs these on host inside the train loop (a per-trace
numpy/obspy loop at training/postprocess.py:129,181 — hot-loop #2 in
SURVEY.md §3). Here they are fixed-shape vectorized XLA ops so eval math
stays on device and fuses into the jitted step.
"""

from seist_tpu.ops.postprocess import (  # noqa: F401
    detect_events,
    pick_peaks,
    process_outputs,
    PAD_VALUE,
)
from seist_tpu.ops.metrics import (  # noqa: F401
    Metrics,
    batch_counters,
    data_plane_counters,
    finalize,
    merge,
)
from seist_tpu.ops.results import ResultSaver  # noqa: F401
