"""Vectorized peak picking and event detection (fixed shapes, jit-able).

Behavior-parity redesign of the reference output pipeline
(training/postprocess.py:15-250): ``_detect_peaks`` (a per-trace BMC-style
numpy loop), ``_detect_event`` (obspy ``trigger_onset`` per trace) and
``process_outputs``. The reference executes these on host every training
step, serializing a device->host copy; here each is one batched XLA program
over the whole (N, L) output, so results stay on device and eval math fuses
with the step.

Semantics matched exactly (encoded in tests/test_postprocess.py):

* peaks: rising-edge local maxima (plateau keeps the rising edge), first and
  last sample excluded, height >= ``mph``, the ``topk`` tallest kept, then
  greedy minimum-distance suppression in height order, results sorted by
  position and padded with ``padding_value`` (ref postprocess.py:51-111,
  181-185).
* events: maximal runs with prob > threshold (obspy ``trigger_onset`` with
  equal on/off thresholds, ref postprocess.py:130), sorted by duration
  descending, truncated/padded to ``topk`` with ``[1, 0]`` pairs
  (ref postprocess.py:135-141).

One intentional divergence: ties in peak height / run length break toward the
*earlier* index (``lax.top_k`` order); the reference's reversed stable sort
breaks height ties toward the later peak. Exactly-equal float probabilities
do not occur in practice.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Sequence, Union

import jax
import jax.numpy as jnp

PAD_VALUE = int(-1e7)  # ref postprocess.py:230


@partial(jax.jit, static_argnames=("min_peak_dist", "topk", "padding_value"))
def pick_peaks(
    x: jnp.ndarray,
    prob_threshold: float,
    min_peak_dist: int,
    topk: int,
    padding_value: int = PAD_VALUE,
) -> jnp.ndarray:
    """Batched peak picking: ``x`` (N, L) -> (N, topk) int32 peak indices.

    Vectorized equivalent of ``_detect_peaks(mph=prob_threshold,
    mpd=min_peak_dist, topk=topk)`` mapped over the batch
    (ref postprocess.py:161-193).
    """
    if x.ndim != 2:
        raise ValueError(f"pick_peaks expects (N, L), got {x.shape}")
    n, length = x.shape
    x = x.astype(jnp.float32)

    # Rising-edge candidates: dx_prev > 0 and dx_next <= 0 (plateaus keep the
    # rising edge; ref postprocess.py:69-70). First/last sample excluded
    # (ref :83-86).
    dx = x[:, 1:] - x[:, :-1]
    zeros = jnp.zeros((n, 1), dtype=x.dtype)
    dx_next = jnp.concatenate([dx, zeros], axis=1)
    dx_prev = jnp.concatenate([zeros, dx], axis=1)
    cand = (dx_next <= 0) & (dx_prev > 0)
    cand = cand.at[:, 0].set(False).at[:, -1].set(False)
    cand = cand & (x >= prob_threshold)  # mph filter (ref :88-89)

    # topk tallest candidates (ref sorts by height then truncates, :96-99).
    heights = jnp.where(cand, x, -jnp.inf)
    top_h, top_i = jax.lax.top_k(heights, topk)
    valid = jnp.isfinite(top_h)

    if min_peak_dist > 1:
        # Greedy NMS in height order among the topk (ref :100-109). K is
        # small (max_detect_event_num), so the O(K^2) sweep is cheap.
        def row_nms(top_i_row, valid_row):
            idel0 = ~valid_row

            def body(k, idel):
                alive = (~idel[k]) & valid_row[k]
                close = (top_i_row >= top_i_row[k] - min_peak_dist) & (
                    top_i_row <= top_i_row[k] + min_peak_dist
                )
                idel = jnp.where(alive, idel | close, idel)
                return idel.at[k].set(jnp.where(alive, False, idel[k]))

            idel = jax.lax.fori_loop(0, topk, body, idel0)
            return ~idel & valid_row

        keep = jax.vmap(row_nms)(top_i, valid)
    else:
        keep = valid

    # Sort kept peaks back into positional order, pad the rest (ref :109,
    # 183-184).
    sentinel = length + 1
    pos = jnp.where(keep, top_i, sentinel)
    pos = jnp.sort(pos, axis=1)
    return jnp.where(pos >= sentinel, padding_value, pos).astype(jnp.int32)


@partial(jax.jit, static_argnames=("topk",))
def detect_events(
    x: jnp.ndarray, prob_threshold: float, topk: int
) -> jnp.ndarray:
    """Batched event detection: ``x`` (N, L) -> (N, topk*2) int32 [on, off].

    Maximal runs where prob > threshold (obspy ``trigger_onset`` with equal
    on/off thresholds, ref postprocess.py:130), sorted by duration
    descending, padded with [1, 0] (ref :135-141).
    """
    if x.ndim != 2:
        raise ValueError(f"detect_events expects (N, L), got {x.shape}")
    n, length = x.shape
    above = x > prob_threshold
    false_col = jnp.zeros((n, 1), dtype=bool)
    starts = above & ~jnp.concatenate([false_col, above[:, :-1]], axis=1)
    ends = above & ~jnp.concatenate([above[:, 1:], false_col], axis=1)

    # Each maximal run has exactly one start and one end; run id = running
    # count of starts. Scatter start/end positions into fixed-capacity slots
    # (<= ceil(L/2) runs possible for alternating above/below).
    capacity = length // 2 + 1
    run_id = jnp.cumsum(starts, axis=1) - 1  # id at any in-run position
    pos = jnp.arange(length)

    def row_runs(starts_row, ends_row, run_id_row):
        s_ids = jnp.where(starts_row, run_id_row, capacity)
        e_ids = jnp.where(ends_row, run_id_row, capacity)
        s_arr = jnp.full((capacity + 1,), -1).at[s_ids].set(pos)
        e_arr = jnp.full((capacity + 1,), -1).at[e_ids].set(pos)
        return s_arr[:capacity], e_arr[:capacity]

    s_arr, e_arr = jax.vmap(row_runs)(starts, ends, run_id)
    run_valid = s_arr >= 0
    lengths = jnp.where(run_valid, e_arr - s_arr, -1)

    # topk longest runs; lax.top_k ties break toward the earlier run, which
    # matches Python's stable sort in the reference (ref :135-136).
    _, idx = jax.lax.top_k(lengths, topk)
    sel_valid = jnp.take_along_axis(run_valid, idx, axis=1)
    on = jnp.where(sel_valid, jnp.take_along_axis(s_arr, idx, axis=1), 1)
    off = jnp.where(sel_valid, jnp.take_along_axis(e_arr, idx, axis=1), 0)
    return jnp.stack([on, off], axis=-1).reshape(n, topk * 2).astype(jnp.int32)


def process_outputs(
    outputs: Union[Any, Sequence[Any]],
    label_names: Sequence[Union[str, Sequence[str]]],
    sampling_rate: int,
    *,
    ppk_threshold: float = 0.3,
    spk_threshold: float = 0.3,
    det_threshold: float = 0.5,
    min_peak_dist: float = 1.0,
    max_detect_event_num: int = 1,
) -> Dict[str, jnp.ndarray]:
    """Convert raw model outputs to per-task results (ref postprocess.py:196-250).

    ``outputs`` is the model output (one array or a tuple, one per label
    group); dense per-sample groups are channels-last ``(N, L, C)`` (the
    reference is ``(N, C, L)``). Returns ``{task: array}`` with fixed shapes:
    ppk/spk -> (N, topk) indices, det -> (N, topk*2) on/off pairs, others
    passed through (at least 2-D).
    """
    outputs_list = outputs if isinstance(outputs, (tuple, list)) else [outputs]
    mpd = int(min_peak_dist * sampling_rate)
    results: Dict[str, jnp.ndarray] = {}
    for out, label_group in zip(outputs_list, label_names):
        if isinstance(label_group, (tuple, list)):
            for i, name in enumerate(label_group):
                if name in ("ppk", "spk"):
                    results[name] = pick_peaks(
                        out[..., i],
                        prob_threshold=(
                            ppk_threshold if name == "ppk" else spk_threshold
                        ),
                        min_peak_dist=mpd,
                        topk=max_detect_event_num,
                    )
                elif name == "det":
                    results[name] = detect_events(
                        out[..., i],
                        prob_threshold=det_threshold,
                        topk=max_detect_event_num,
                    )
                else:
                    tmp = out[..., i]
                    if tmp.ndim < 2:
                        tmp = tmp[:, None]
                    results[name] = tmp
        else:
            results[label_group] = out
    return results


#: Decision-level picker results a catalog keeps (dense per-sample
#: probability channels like ``non``/``det+`` are decode intermediates,
#: not catalog content).
_CATALOG_PICK_NAMES = ("ppk", "spk", "det")


def decode_head_batch(
    spec: Any,
    outputs: Any,
    *,
    is_picker: bool,
    sampling_rate: int,
    ppk_threshold: float = 0.3,
    spk_threshold: float = 0.3,
    det_threshold: float = 0.5,
    min_peak_dist: float = 1.0,
    max_events: int = 8,
) -> Dict[str, Any]:
    """Batched decode of ONE head's raw outputs into named result arrays
    — device-resident; the caller makes a single batched
    ``jax.device_get`` over every head's results (the Metrics.to_dict
    idiom) and feeds them to :func:`seist_tpu.ops.results.catalog_rows`.

    Pickers route through :func:`process_outputs` (the same compiled
    pick/detect programs the eval loop and serve decode use), keeping
    only the decision-level ``ppk``/``spk``/``det`` arrays. VALUE heads
    apply the spec's results transform (e.g. baz (cos,sin)->degrees,
    magnet mean-only) and yield one per-label array with leading dim N;
    ONEHOT heads yield the (N, C) score matrix (argmax happens host-side
    in ``catalog_rows``)."""
    if is_picker:
        res = process_outputs(
            outputs,
            spec.labels,
            sampling_rate,
            ppk_threshold=ppk_threshold,
            spk_threshold=spk_threshold,
            det_threshold=det_threshold,
            min_peak_dist=min_peak_dist,
            max_detect_event_num=max_events,
        )
        return {k: v for k, v in res.items() if k in _CATALOG_PICK_NAMES}
    transform = spec.outputs_transform_for_results
    outs = transform(outputs) if transform else outputs
    outs_list = outs if isinstance(outs, (tuple, list)) else [outs]
    if len(outs_list) != len(spec.labels):
        raise ValueError(
            f"head produced {len(outs_list)} outputs for "
            f"{len(spec.labels)} labels"
        )
    return {str(name): arr for name, arr in zip(spec.labels, outs_list)}
