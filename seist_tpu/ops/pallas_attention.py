"""Pallas TPU kernel: fused pooled-KV attention.

The SeisT encoder's attention keeps full-length Q but pools K/V by
``attn_aggr_ratio`` (ref seist.py:321-393), so scores are (L x M) with
M = L/r. XLA's unfused path materializes the (N, H, L, M) probability
tensor in HBM — at the reference training shape (batch 500, stage 1:
L=1024, M=128) that is ~0.5 GB of HBM traffic per layer per direction.
This kernel fuses qk-matmul + softmax + pv-matmul in VMEM (one grid step
per batch-head; L, M and E are small enough that a whole batch-head's
Q/K/V fit on-chip), writing only the (L, E) output.

Training works through a custom VJP whose backward is a second fused
kernel (recompute-p flash-style backward), so no probability tensor is
ever materialized in either direction.

``fused_pooled_attention`` is numerically identical (fp32) to the einsum
path the model uses elsewhere; on non-TPU backends it falls back to that
einsum, and ``interpret=True`` drives the same kernels through the Pallas
interpreter for CPU testing.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def _einsum_attention(q, k, v, scale):
    s = jnp.einsum("nlhe,nmhe->nhlm", q * scale, k)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("nhlm,nmhe->nlhe", p, v)


# -- kernels (operate on one (batch*head) slice in VMEM) ---------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale):
    q = q_ref[0].astype(jnp.float32)  # (L, E)
    k = k_ref[0].astype(jnp.float32)  # (M, E)
    v = v_ref[0].astype(jnp.float32)  # (M, E)
    s = jnp.dot(q * scale, k.T, preferred_element_type=jnp.float32)  # (L, M)
    s = s - s.max(axis=-1, keepdims=True)
    p = jnp.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    o_ref[0] = jnp.dot(p, v, preferred_element_type=jnp.float32).astype(
        o_ref.dtype
    )


def _bwd_kernel(q_ref, k_ref, v_ref, g_ref, dq_ref, dk_ref, dv_ref, *, scale):
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)  # (L, E) upstream grad
    s = jnp.dot(q * scale, k.T, preferred_element_type=jnp.float32)
    s = s - s.max(axis=-1, keepdims=True)
    p = jnp.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)  # recomputed probs (L, M)
    dv = jnp.dot(p.T, g, preferred_element_type=jnp.float32)
    dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)  # (L, M)
    ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))  # softmax jvp
    dq = jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale
    dk = jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flatten_heads(x):
    n, l, h, e = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(n * h, l, e)


def _unflatten_heads(x, n, h):
    nh, l, e = x.shape
    return jnp.transpose(x.reshape(n, h, l, e), (0, 2, 1, 3))


def _call_fused(kernel, out_shapes, inputs, interpret):
    from jax.experimental import pallas as pl

    nh = inputs[0].shape[0]

    def spec(x):
        return pl.BlockSpec((1,) + x.shape[1:], lambda i: (i, 0, 0))

    return pl.pallas_call(
        kernel,
        grid=(nh,),
        in_specs=[spec(x) for x in inputs],
        out_specs=(
            [spec_like(o) for o in out_shapes]
            if isinstance(out_shapes, (list, tuple))
            else spec_like(out_shapes)
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(*inputs)


def spec_like(sds):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((1,) + sds.shape[1:], lambda i: (i, 0, 0))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(q3, k3, v3, scale, interpret):
    o = _call_fused(
        partial(_fwd_kernel, scale=scale),
        jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        (q3, k3, v3),
        interpret,
    )
    return o


def _fused_fwd(q3, k3, v3, scale, interpret):
    return _fused(q3, k3, v3, scale, interpret), (q3, k3, v3)


def _fused_bwd(scale, interpret, res, g):
    q3, k3, v3 = res
    dq, dk, dv = _call_fused(
        partial(_bwd_kernel, scale=scale),
        (
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        (q3, k3, v3, g),
        interpret,
    )
    return dq, dk, dv


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_pooled_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    *,
    interpret: bool = False,
    force: bool = False,
) -> jnp.ndarray:
    """Fused attention for ``q (N, L, H, E)``, ``k/v (N, M, H, E)``.

    Uses the Pallas kernel on TPU (or when ``interpret``/``force`` is set);
    otherwise the XLA einsum path — both compute identical fp32 math.
    """
    e = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    on_tpu = jax.default_backend() == "tpu"
    if not (on_tpu or interpret or force):
        return _einsum_attention(q, k, v, scale)
    n, _, h, _ = q.shape
    o3 = _fused(
        _flatten_heads(q), _flatten_heads(k), _flatten_heads(v), scale, interpret
    )
    return _unflatten_heads(o3, n, h)
