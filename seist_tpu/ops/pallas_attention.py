"""Pallas TPU kernel: fused pooled-KV attention, with in-kernel dropout.

The SeisT encoder's attention keeps full-length Q but pools K/V by
``attn_aggr_ratio`` (ref seist.py:321-393), so scores are (L x M) with
M = L/r. XLA's unfused path materializes the (N, H, L, M) probability
tensor in HBM — at the reference training shape (batch 500, stage 1:
L=1024, M=128) that is ~0.5 GB of HBM traffic per layer per direction.
This kernel fuses qk-matmul + softmax + (dropout) + pv-matmul in VMEM
(one grid step per batch element, heads unrolled in-kernel over the
feature axis; L, M and H*E are small enough that a whole batch element's
Q/K/V fit on-chip), writing only the (L, H*E) output. Q/K/V enter as
(N, L, H*E) — exactly the layout the Dense projections produce — so no
head transpose is ever materialized in HBM (the (N,L,H,E)->(N,H,L,E)
copies were ~2 ms/step in the round-2 seist_l profile).

Training works through a custom VJP whose backward is a second fused
kernel (recompute-p flash-style backward), so no probability tensor is
ever materialized in either direction.

Attention-probability dropout (ref seist.py:383-388 applies
``attn_drop`` after softmax) is generated *inside* the kernel from a
counter-based hash PRNG written in plain jnp ops, so the exact same
mask math runs in three places: the compiled TPU kernel, the Pallas
interpreter (CPU tests), and the XLA einsum fallback. The backward
kernel regenerates the identical mask from the saved seed, so no mask
tensor is materialized either.

``fused_pooled_attention`` is numerically identical (fp32) to the
einsum path for the same seed; on non-TPU backends it falls back to
that einsum, and ``interpret=True`` drives the same kernels through the
Pallas interpreter for CPU testing.
"""

from __future__ import annotations

import logging
import math
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_log = logging.getLogger("seist_tpu.pallas_attention")


def _wrap_i32(v: int) -> np.int32:
    """Python int -> int32 scalar with explicit two's-complement wrap.

    ``jnp.int32(big)`` raises under numpy>=2; the counter math here wraps
    mod 2^32 by design (long-context L*M can exceed 2^31 — the hash mixes
    the wrapped bits the same way on every path).

    Returns a NUMPY scalar, not a jnp array: numpy scalars trace as inline
    jaxpr literals, while jnp arrays become captured constants — which
    Mosaic's pallas_call rejects outright ("captures constants ... pass
    them as inputs", observed live on TPU 2026-08-02). The arithmetic is
    identical either way, so the kernel, the interpreter, and the XLA
    einsum fallback keep bit-identical mask math.
    """
    return np.int32(np.uint32(int(v) & 0xFFFFFFFF))


def _mix_to_uniform(x, seed) -> jnp.ndarray:
    """murmur3-finalizer hash of int32 counter array ``x`` -> U[0,1).

    int32 throughout (Mosaic lacks uint32<->float casts): multiplies wrap
    two's-complement — identical low 32 bits to the uint32 murmur mix —
    and shifts are explicit logical shifts.
    """

    def c(u):  # uint32 constant as wrapped int32 (numpy scalar: traces as
        # an inline literal — a jnp constant would be a captured const,
        # which pallas_call rejects; see _wrap_i32)
        return np.int32(np.uint32(u))

    shr = lambda x, n: lax.shift_right_logical(x, np.int32(n))
    x = x ^ (seed.astype(jnp.int32) * c(0x9E3779B9))
    x = x ^ shr(x, 16)
    x = x * c(0x85EBCA6B)
    x = x ^ shr(x, 13)
    x = x * c(0xC2B2AE35)
    x = x ^ shr(x, 16)
    return shr(x, 8).astype(jnp.float32) * (1.0 / (1 << 24))


def _uniform01(seed, pid, l: int, m: int) -> jnp.ndarray:
    """Deterministic (L, M) uniforms in [0, 1) for batch-head slice ``pid``.

    Counter-based (murmur3-finalizer over a linear element index), pure jnp
    — runs identically inside a Pallas kernel, under the interpreter, and in
    the XLA fallback, so all three paths agree bit-for-bit on the mask.
    The ring-attention path generates the same stream blockwise via
    ``_uniform01_block``.
    """
    row = lax.broadcasted_iota(jnp.int32, (l, m), 0)
    col = lax.broadcasted_iota(jnp.int32, (l, m), 1)
    x = pid.astype(jnp.int32) * _wrap_i32(l * m) + row * _wrap_i32(m) + col
    return _mix_to_uniform(x, seed)


def _apply_dropout(p, seed, pid, rate: float):
    """Zero entries where u < rate; scale survivors by 1/(1-rate)."""
    l, m = p.shape[-2], p.shape[-1]
    u = _uniform01(seed, pid, l, m)
    keep = u >= np.float32(rate)
    return jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)


def _einsum_attention(q, k, v, scale, dropout_rate=0.0, dropout_seed=None):
    s = jnp.einsum("nlhe,nmhe->nhlm", q * scale, k)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        n, h, l, m = p.shape
        pid = lax.broadcasted_iota(jnp.int32, (n * h, 1, 1), 0)
        u = jax.vmap(
            lambda i: _uniform01(dropout_seed[0], i.reshape(()), l, m)
        )(pid.reshape(n * h))
        keep = u.reshape(n, h, l, m) >= jnp.float32(dropout_rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    return jnp.einsum("nhlm,nmhe->nlhe", p, v)


# -- kernels (operate on one (batch*head) slice in VMEM) ---------------------


def _softmax_rows(q, k, scale):
    s = jnp.dot(q * scale, k.T, preferred_element_type=jnp.float32)  # (L, M)
    s = s - s.max(axis=-1, keepdims=True)
    p = jnp.exp(s)
    return p / p.sum(axis=-1, keepdims=True)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, *, scale, rate, heads):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)  # (L, H*E)
    k = k_ref[0].astype(jnp.float32)  # (M, H*E)
    v = v_ref[0].astype(jnp.float32)  # (M, H*E)
    e = q.shape[-1] // heads
    for h in range(heads):
        sl = slice(h * e, (h + 1) * e)
        p = _softmax_rows(q[:, sl], k[:, sl], scale)
        if rate > 0.0:
            pid = pl.program_id(0) * heads + h
            p = _apply_dropout(p, seed_ref[0], pid, rate)
        o_ref[0, :, sl] = jnp.dot(
            p, v[:, sl], preferred_element_type=jnp.float32
        ).astype(o_ref.dtype)


def _bwd_kernel(
    seed_ref,
    q_ref,
    k_ref,
    v_ref,
    g_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    *,
    scale,
    rate,
    heads,
):
    from jax.experimental import pallas as pl

    qa = q_ref[0].astype(jnp.float32)  # (L, H*E)
    ka = k_ref[0].astype(jnp.float32)  # (M, H*E)
    va = v_ref[0].astype(jnp.float32)
    ga = g_ref[0].astype(jnp.float32)  # (L, H*E) upstream grad
    e = qa.shape[-1] // heads
    for h in range(heads):
        sl = slice(h * e, (h + 1) * e)
        q, k, v, g = qa[:, sl], ka[:, sl], va[:, sl], ga[:, sl]
        pid = pl.program_id(0) * heads + h
        p = _softmax_rows(q, k, scale)  # recomputed probs (L, M)
        if rate > 0.0:
            pd = _apply_dropout(p, seed_ref[0], pid, rate)
        else:
            pd = p
        dv = jnp.dot(pd.T, g, preferred_element_type=jnp.float32)
        dpd = jnp.dot(g, v.T, preferred_element_type=jnp.float32)  # (L, M)
        if rate > 0.0:
            # d(dropout)/dp is the same keep/scale mask; reuse via pd =
            # mask*p/kp: where p > 0, mask*inv_keep = pd / p. Regenerate
            # instead (exact, avoids 0/0): same counter stream.
            dp = _apply_dropout(dpd, seed_ref[0], pid, rate)
        else:
            dp = dpd
        ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))  # softmax vjp
        dq = jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale
        dk = jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale
        dq_ref[0, :, sl] = dq.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)


def _fold_heads(x):
    """(N, L, H, E) -> (N, L, H*E): a pure bitcast reshape (no transpose —
    the heads stay interleaved on the feature axis exactly as the q/k/v
    Dense projections produce them; the kernel slices per head in VMEM)."""
    n, l, h, e = x.shape
    return x.reshape(n, l, h * e)


def _call_fused(kernel, out_shapes, seed, inputs, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh = inputs[0].shape[0]

    def spec(x):
        return pl.BlockSpec((1,) + x.shape[1:], lambda i, s: (i, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nh,),
        in_specs=[spec(x) for x in inputs],
        out_specs=(
            [spec(o) for o in out_shapes]
            if isinstance(out_shapes, (list, tuple))
            else spec(out_shapes)
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(seed, *inputs)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused(q3, k3, v3, seed, scale, rate, heads, interpret):
    o = _call_fused(
        partial(_fwd_kernel, scale=scale, rate=rate, heads=heads),
        jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        seed,
        (q3, k3, v3),
        interpret,
    )
    return o


def _fused_fwd(q3, k3, v3, seed, scale, rate, heads, interpret):
    return (
        _fused(q3, k3, v3, seed, scale, rate, heads, interpret),
        (q3, k3, v3, seed),
    )


def _fused_bwd(scale, rate, heads, interpret, res, g):
    q3, k3, v3, seed = res
    dq, dk, dv = _call_fused(
        partial(_bwd_kernel, scale=scale, rate=rate, heads=heads),
        (
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        seed,
        (q3, k3, v3, g),
        interpret,
    )
    return dq, dk, dv, np.zeros(seed.shape, dtype=jax.dtypes.float0)


_fused.defvjp(_fused_fwd, _fused_bwd)


# -- kernel health probe ------------------------------------------------------
#
# A Mosaic version can reject the kernel at compile time (the head-folded
# layout writes E-wide feature slices that are not 128-lane aligned). That
# failure would surface only when the *enclosing* train-step jit compiles —
# taking down the default train path. Instead, the first TPU-backend call per
# (L, M, H*E, dropout?, dtype) signature AOT-compiles the kernel fwd+bwd on a
# batch-1 slice of the real shape (the grid is over batch, so batch-1
# exercises the exact per-step block shapes) and executes the compiled
# program once on zero buffers. On failure we log once and route that
# signature to the identical-math einsum path. Explicit requests
# (interpret/force/SEIST_ATTN_IMPL=fused) bypass the probe so parity
# tooling still sees the raw error.

_KERNEL_STATUS: dict = {}
# Last observed probe outcome per signature, INCLUDING transient failures
# (which are deliberately kept out of _KERNEL_STATUS so a later trace
# re-probes). kernel_status_summary() reads this, so a transient failure
# that baked einsum into a compiled step is still visible in bench JSON
# and the worker log.
_KERNEL_EVENTS: dict = {}
_FALLBACK_LOGGED = False


def _probe_kernel(l, m, he, heads, rate, dtype) -> None:
    # AOT lower+compile, then one real execution. Unlike a traced call,
    # .lower() never binds into an ambient trace, so this is safe to run
    # while the enclosing train step is being traced (the previous
    # ensure_compile_time_eval escape broke outright when JAX moved to the
    # eager-trace-stack internals — observed live 2026-08-02: constants
    # created under the eval trace were hoisted out of the kernel trace as
    # captured consts, then pl.program_id had no eval rule). Mosaic
    # rejections and VMEM/scratch exhaustion surface at compile; the
    # execution step keeps runtime-only faults (HBM-full OOM, DMA errors)
    # routing to the einsum fallback too — the compiled executable takes
    # concrete (numpy) buffers, so it runs eagerly under any trace.
    qs = jax.ShapeDtypeStruct((1, l, he), dtype)
    ks = jax.ShapeDtypeStruct((1, m, he), dtype)
    ss = jax.ShapeDtypeStruct((1,), jnp.int32)

    def f(q, k, v, seed):
        return _fused(q, k, v, seed, 1.0, rate, heads, False).sum()

    compiled = jax.jit(jax.grad(f, argnums=(0, 1, 2))).lower(
        qs, ks, ks, ss
    ).compile()
    npdt = np.dtype(dtype)  # ml_dtypes covers bf16 for numpy zeros
    g = compiled(
        np.zeros((1, l, he), npdt),
        np.zeros((1, m, he), npdt),
        np.zeros((1, m, he), npdt),
        np.zeros((1,), np.int32),
    )
    jax.block_until_ready(g)


_TRANSIENT_ERROR_MARKERS = ("RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED", "UNAVAILABLE")
# A deterministic kernel VMEM/scratch overflow ALSO surfaces as
# RESOURCE_EXHAUSTED; unlike HBM pressure it never clears, so re-probing
# it on every trace would cost a probe compile + warning forever.
_PERMANENT_EXHAUSTION_MARKERS = ("vmem", "scratch", "smem")
# Even genuinely-transient failures stop being worth re-probing after a
# few traces in the same process — cap, then cache as unusable.
_MAX_TRANSIENT_PROBES = 3
_TRANSIENT_COUNTS: dict = {}


def _is_transient(exc: Exception) -> bool:
    # A probe can fail for reasons that say nothing about Mosaic's ability to
    # compile the kernel — e.g. HBM already occupied by the train state, or a
    # flaky backend connection. Those must not poison the per-process cache.
    # A VMEM/scratch exhaustion is the opposite: deterministic for the shape,
    # so treat it as a permanent Mosaic rejection.
    msg = f"{type(exc).__name__}: {exc}"
    if not any(marker in msg for marker in _TRANSIENT_ERROR_MARKERS):
        return False
    return not any(m in msg.lower() for m in _PERMANENT_EXHAUSTION_MARKERS)


def _kernel_usable(l, m, he, heads, rate, dtype) -> bool:
    key = (l, m, he, heads, rate > 0.0, jnp.dtype(dtype).name)
    hit = _KERNEL_STATUS.get(key)
    if hit is not None:
        return hit
    try:
        # The call site usually sits under the train step's jit trace; the
        # probe must not be traced into it (a nested traced call would
        # inline instead of compile, and the probe would "fail" on a
        # perfectly good kernel, permanently einsum-ing the default path).
        # _probe_kernel uses AOT .lower().compile(), which opens its own
        # trace context regardless of the ambient one.
        _probe_kernel(l, m, he, heads, float(rate), dtype)
        ok = True
    except Exception as exc:  # noqa: BLE001 - any compile/runtime rejection
        head = str(exc).splitlines()[0][:200] if str(exc) else ""
        if _is_transient(exc):
            n = _TRANSIENT_COUNTS[key] = _TRANSIENT_COUNTS.get(key, 0) + 1
            if n >= _MAX_TRANSIENT_PROBES:
                # Enough: stop paying a probe compile per trace. Cache as
                # unusable (the event log keeps the transient history).
                _KERNEL_STATUS[key] = False
                _KERNEL_EVENTS[key] = (
                    f"einsum-fallback (transient x{n}, re-probe cap hit: "
                    f"{head})"
                )
                _log.warning(
                    "fused attention probe failed transiently %d times for "
                    "shape L=%d M=%d HE=%d H=%d %s; caching einsum fallback "
                    "for this process (%s)",
                    n, l, m, he, heads, jnp.dtype(dtype).name, head,
                )
                return False
            # Fall back for THIS trace (the enclosing jit bakes einsum in
            # permanently for this program!) but leave the retry cache
            # empty so a LATER trace — a re-jit, another shape — re-probes
            # once memory pressure clears. Record the event so the
            # fallback is still observable, and log every occurrence (the
            # one-shot flag below is reserved for permanent rejections).
            _KERNEL_EVENTS[key] = f"einsum-fallback (transient {head})"
            _log.warning(
                "fused attention probe hit a transient error for shape "
                "L=%d M=%d HE=%d H=%d %s (%s: %s); THIS trace falls back "
                "to the identical-math einsum path; the kernel will be "
                "re-probed on the next trace",
                l, m, he, heads, jnp.dtype(dtype).name,
                type(exc).__name__, head,
            )
            return False
        global _FALLBACK_LOGGED
        if not _FALLBACK_LOGGED:
            _FALLBACK_LOGGED = True
            _log.warning(
                "fused attention kernel unusable for shape L=%d M=%d HE=%d "
                "H=%d %s (%s: %s); falling back to the identical-math einsum "
                "path (SEIST_ATTN_IMPL=fused to force the kernel)",
                l, m, he, heads, jnp.dtype(dtype).name,
                type(exc).__name__, head,
            )
        ok = False
    _KERNEL_STATUS[key] = ok
    prior = _KERNEL_EVENTS.get(key, "")
    if ok and "transient" in prior:
        # An earlier trace of this signature baked einsum in permanently;
        # this re-probe only helps traces from here on. Keep the history
        # visible (and keep `overall` degraded) so a bench/worker summary
        # can't claim a clean "fused" run.
        _KERNEL_EVENTS[key] = (
            "fused (re-probed ok; an earlier trace fell back to einsum: "
            + prior + ")"
        )
    else:
        _KERNEL_EVENTS[key] = "fused" if ok else "einsum-fallback"
    return ok


def kernel_status_summary() -> dict:
    """Machine-readable outcome of the fused-kernel health probes so far
    (VERDICT r3 #4: a Mosaic rejection must never silently cost the fused
    win again). Returns ``{"overall": "fused"|"einsum-fallback"|"unprobed",
    "signatures": {"L512/M16/HE96/H8/drop=False/bf16": "fused"|
    "einsum-fallback"|"einsum-fallback (transient ...)"}}`` — bench.py
    emits this in its JSON line and train/worker.py logs it after the
    first step. Reads the EVENT log, so a transient probe failure (kept
    out of the retry cache) is still reported for the trace it affected.
    """
    sigs = {}
    for (l, m, he, heads, drop, dtype), status in _KERNEL_EVENTS.items():
        sigs[f"L{l}/M{m}/HE{he}/H{heads}/drop={drop}/{dtype}"] = status
    if not sigs:
        overall = "unprobed"
    elif all(v == "fused" for v in sigs.values()):
        overall = "fused"
    else:
        overall = "einsum-fallback"
    return {"overall": overall, "signatures": sigs}


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def fused_pooled_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: Optional[float] = None,
    *,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jnp.ndarray] = None,
    interpret: bool = False,
    force: bool = False,
) -> jnp.ndarray:
    """Fused attention for ``q (N, L, H, E)``, ``k/v (N, M, H, E)``.

    Uses the Pallas kernel on TPU (or when ``interpret``/``force`` is set);
    otherwise the XLA einsum path — both compute identical fp32 math,
    including the dropout mask (same counter-based PRNG in both).

    ``dropout_rate`` > 0 applies post-softmax probability dropout (ref
    seist.py:383-388) and requires ``dropout_seed``, an int32 array of
    shape (1,) — derive it per step from the flax 'dropout' rng stream.
    """
    e = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(e)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    if dropout_seed is None:
        dropout_seed = jnp.zeros((1,), jnp.int32)
    dropout_seed = dropout_seed.astype(jnp.int32)
    # Escape hatches: SEIST_ATTN_IMPL=einsum forces the identical-math XLA
    # path even on TPU; =fused forces the kernel (skipping the health probe,
    # so a Mosaic rejection surfaces raw). Unset = auto: kernel on TPU with
    # a one-time per-shape compile probe and automatic einsum fallback.
    # Explicit kernel requests (interpret/force, used by parity tooling)
    # take precedence over the ambient env var.
    env_impl = os.environ.get("SEIST_ATTN_IMPL")
    if env_impl not in (None, "", "fused", "einsum"):
        raise ValueError(
            f"unknown SEIST_ATTN_IMPL {env_impl!r} (use fused or einsum)"
        )
    if env_impl == "einsum" and not (interpret or force):
        return _einsum_attention(q, k, v, scale, dropout_rate, dropout_seed)
    if not (_on_tpu() or interpret or force):
        return _einsum_attention(q, k, v, scale, dropout_rate, dropout_seed)
    h = q.shape[2]
    if not (interpret or force or env_impl == "fused"):
        l, m, he = q.shape[1], k.shape[1], h * e
        if not _kernel_usable(l, m, he, h, dropout_rate, q.dtype):
            return _einsum_attention(
                q, k, v, scale, dropout_rate, dropout_seed
            )
    o3 = _fused(
        _fold_heads(q),
        _fold_heads(k),
        _fold_heads(v),
        dropout_seed,
        scale,
        float(dropout_rate),
        h,
        interpret,
    )
    return o3.reshape(q.shape)
