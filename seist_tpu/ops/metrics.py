"""Streaming metrics as psum-able counter pytrees.

Behavior-parity redesign of the reference metrics engine
(utils/metrics.py:13-383). The reference is a stateful class whose
``compute`` fills counters for one batch and whose ``add`` accumulates
batches; cross-rank sync all-reduces the counters and all-gathers targets
(metrics.py:83-98 via NCCL). Here the core is *functional*: a plain dict of
jnp scalars/vectors computed per batch by :func:`batch_counters` (one jitted
program, no host transfer), merged with :func:`merge` (tree add — valid under
``lax.psum`` across devices too), and turned into final metric values by
:func:`finalize`. The :class:`Metrics` wrapper reproduces the reference's
class API on top.

Per-task semantics matched exactly (tests/test_metrics.py):

* ppk/spk — greedy nearest matching of multi-phase predictions to targets
  (ref :101-125); TP when both indices in [0, num_samples) and
  |t - p| <= time_threshold*fs (ref :150-165); residual metrics masked by TP.
* det — interval-overlap indicator sums over the sample axis (ref :166-189).
* onehot — argmax -> per-class confusion counters, macro-averaged at
  finalize (ref :190-205, 296-307).
* value — mean/rmse/mae/mape over per-sample residual means; baz residuals
  wrap at +/-180 degrees (ref :207-235); R2 against gathered raw targets
  (memory-unbounded by design, ref :237-241, 320-328).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

EPSILON = 1e-6  # ref metrics.py:19
CMAT_KEYS = ("tp", "predp", "possp")  # ref :21
REGR_KEYS = ("sum_res", "sum_squ_res", "sum_abs_res", "sum_abs_per_res")  # ref :20
AVAILABLE_METRICS = (
    "precision",
    "recall",
    "f1",
    "mean",
    "rmse",
    "mae",
    "mape",
    "r2",
)  # ref :22

_CMAT_METRICS = frozenset(("precision", "recall", "f1"))
_REGR_METRICS = frozenset(("mean", "rmse", "mae", "mape"))


def _needs(metric_names: Sequence[str]) -> Tuple[bool, bool, bool]:
    names = set(metric_names)
    return (
        bool(names & _CMAT_METRICS),
        bool(names & (_REGR_METRICS | {"r2"})),
        "r2" in names,
    )


def order_phases(targets: jnp.ndarray, preds: jnp.ndarray) -> jnp.ndarray:
    """Greedily match predicted phase indices to targets by |distance|.

    Vectorized equivalent of the reference's per-sample numpy loop
    (metrics.py:101-125): repeatedly take the globally closest
    (target, pred) pair, assign, and mask that row/column. Returns the
    reordered predictions, shape (N, P).
    """
    num_phases = targets.shape[-1]
    # Consumed rows/cols are masked with +inf. (Deliberate divergence: the
    # reference masks with 1/epsilon = 1e6, metrics.py:120-121, which is
    # SMALLER than the ~1e7 distance to a PAD_VALUE prediction — its argmin
    # can re-select a masked cell and overwrite a correct assignment.)
    big = jnp.inf

    def one_row(t_row, p_row):
        dmat0 = jnp.abs(t_row[:, None] - p_row[None, :]).astype(jnp.float32)

        def body(_, carry):
            dmat, ordered = carry
            flat = jnp.argmin(dmat)
            ito, ifr = flat // num_phases, flat % num_phases
            ordered = ordered.at[ito].set(p_row[ifr])
            dmat = dmat.at[ito, :].set(big).at[:, ifr].set(big)
            return dmat, ordered

        _, ordered = jax.lax.fori_loop(
            0, num_phases, body, (dmat0, jnp.zeros_like(p_row))
        )
        return ordered

    return jax.vmap(one_row)(targets, preds)


def init_counters(
    metric_names: Sequence[str], num_classes: int = 1
) -> Dict[str, jnp.ndarray]:
    """Zero counters; ``num_classes > 1`` only for onehot tasks (per-class
    confusion vectors, ref metrics.py:203-205)."""
    want_cmat, want_regr, _ = _needs(metric_names)
    data: Dict[str, jnp.ndarray] = {}
    if want_cmat:
        shape = (num_classes,) if num_classes > 1 else ()
        for k in CMAT_KEYS:
            data[k] = jnp.zeros(shape, dtype=jnp.float32)
    if want_regr:
        for k in REGR_KEYS:
            data[k] = jnp.zeros((), dtype=jnp.float32)
    data["data_size"] = jnp.zeros((), dtype=jnp.int32)
    return data


def batch_counters(
    task: str,
    metric_names: Sequence[str],
    targets: jnp.ndarray,
    preds: jnp.ndarray,
    *,
    num_samples: int,
    time_threshold_samples: int = 0,
) -> Dict[str, jnp.ndarray]:
    """Counters for ONE batch (jit-friendly; shapes (N, ...) -> scalars).

    Mirrors ``Metrics.compute`` (ref metrics.py:127-247) for one call; use
    :func:`merge` to accumulate across batches/devices.
    """
    task = task.lower()
    metric_names = tuple(n.lower() for n in metric_names)
    want_cmat, want_regr, _ = _needs(metric_names)
    data: Dict[str, jnp.ndarray] = {}
    data["data_size"] = jnp.asarray(targets.shape[0], dtype=jnp.int32)
    mask = 1.0

    if want_cmat:
        if task in ("ppk", "spk"):
            t = targets.astype(jnp.int32)
            p = preds.astype(jnp.int32)
            if t.shape[-1] > 1:
                p = order_phases(t, p).astype(jnp.int32)
            preds_bin = (p >= 0) & (p < num_samples)
            targets_bin = (t >= 0) & (t < num_samples)
            ae = jnp.abs(t - p)
            tp_bin = preds_bin & targets_bin & (ae <= time_threshold_samples)
            mask = tp_bin
            targets, preds = t, p
            data["tp"] = jnp.sum(tp_bin).astype(jnp.float32)
            data["predp"] = jnp.sum(preds_bin).astype(jnp.float32)
            data["possp"] = jnp.sum(targets_bin).astype(jnp.float32)
        elif task == "det":
            bs = targets.shape[0]
            t = targets.astype(jnp.int32).reshape(bs, -1, 2)
            p = preds.astype(jnp.int32).reshape(bs, -1, 2)
            idx = jnp.arange(num_samples)[None, None, :]
            targets_bin = jnp.sum(
                (t[:, :, :1] <= idx) & (idx <= t[:, :, 1:]), axis=-2
            )
            preds_bin = jnp.sum((p[:, :, :1] <= idx) & (idx <= p[:, :, 1:]), axis=-2)
            data["tp"] = jnp.sum(
                jnp.clip(targets_bin * preds_bin, 0, 1)
            ).astype(jnp.float32)
            data["predp"] = jnp.sum(jnp.clip(preds_bin, 0, 1)).astype(jnp.float32)
            data["possp"] = jnp.sum(jnp.clip(targets_bin, 0, 1)).astype(jnp.float32)
        else:  # onehot: argmax -> per-class counters (ref :190-205)
            p1 = jax.nn.one_hot(jnp.argmax(preds, axis=-1), preds.shape[-1])
            t1 = jax.nn.one_hot(jnp.argmax(targets, axis=-1), targets.shape[-1])
            data["tp"] = jnp.sum(t1 * p1, axis=0)
            data["predp"] = jnp.sum(p1, axis=0)
            data["possp"] = jnp.sum(t1, axis=0)
            targets, preds = t1, p1

    if want_regr:
        res = (targets - preds).astype(jnp.float32)
        if task == "baz":  # wrap residuals at +/-180 deg (ref :210-213)
            res = jnp.where(
                jnp.abs(res) > 180, -jnp.sign(res) * (360 - jnp.abs(res)), res
            )
        res_m = res * mask
        data["sum_res"] = res_m.mean(-1).sum()
        data["sum_squ_res"] = jnp.square(res_m).mean(-1).sum()
        data["sum_abs_res"] = jnp.abs(res_m).mean(-1).sum()
        data["sum_abs_per_res"] = (
            jnp.abs(res_m / (targets.astype(jnp.float32) + EPSILON)).mean(-1).sum()
        )
    return data


def merge(a: Dict[str, jnp.ndarray], b: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Accumulate counters (ref Metrics.add, metrics.py:249-267). Also the
    correct cross-device reduction: ``lax.psum`` of this pytree."""
    if set(a) != set(b):
        raise TypeError(f"Mismatched data fields: {set(a)} and {set(b)}")
    return {k: a[k] + b[k] for k in a}


def finalize(
    task: str,
    metric_names: Sequence[str],
    counters: Dict[str, jnp.ndarray],
    tgts: Optional[np.ndarray] = None,
) -> Dict[str, float]:
    """Final metric values from accumulated counters (ref metrics.py:293-332).

    ``tgts`` (all raw targets, any rank-gather already done) is required only
    for R2.
    """
    task = task.lower()
    out: Dict[str, float] = {}
    c = {k: np.asarray(v, dtype=np.float64) for k, v in counters.items()}
    for key in (n.lower() for n in metric_names):
        if key == "precision":
            v = (c["tp"] / (c["predp"] + EPSILON)).mean()
        elif key == "recall":
            v = (c["tp"] / (c["possp"] + EPSILON)).mean()
        elif key == "f1":
            pr = c["tp"] / (c["predp"] + EPSILON)
            re = c["tp"] / (c["possp"] + EPSILON)
            v = (2 * pr * re / (pr + re + EPSILON)).mean()
        elif key == "mean":
            v = c["sum_res"] / c["data_size"]
        elif key == "rmse":
            v = np.sqrt(c["sum_squ_res"] / c["data_size"])
        elif key == "mae":
            v = c["sum_abs_res"] / c["data_size"]
        elif key == "mape":
            v = c["sum_abs_per_res"] / c["data_size"]
        elif key == "r2":
            if tgts is None:
                raise ValueError("r2 requires the gathered targets")
            t = np.asarray(tgts, dtype=np.float64)
            t = t - t.mean()
            if task == "baz":
                t = np.where(np.abs(t) > 180, -np.sign(t) * (360 - np.abs(t)), t)
            v = 1 - c["sum_squ_res"] / (np.square(t).mean(-1).sum() + EPSILON)
        else:
            raise ValueError(f"Unexpected metric name: '{key}'")
        out[key] = float(v)
    return out


class Metrics:
    """Stateful wrapper with the reference's API (utils/metrics.py:13-383):
    ``compute`` per batch, ``+``/``add`` to accumulate, ``get_metrics`` to
    read. Counters live on device; R2 targets accumulate on host."""

    def __init__(
        self,
        task: str,
        metric_names: Union[list, tuple],
        sampling_rate: int,
        time_threshold: float,
        num_samples: int,
    ) -> None:
        self._task = task.lower()
        self._metric_names = tuple(n.lower() for n in metric_names)
        unexpected = set(self._metric_names) - set(AVAILABLE_METRICS)
        if unexpected:
            raise AssertionError(f"Unexpected metrics:{unexpected}")
        self._t_thres = int(time_threshold * sampling_rate)
        self._num_samples = num_samples
        self._counters: Optional[Dict[str, jnp.ndarray]] = None
        self._host_counters: Optional[Dict[str, np.ndarray]] = None
        self._tgts: List[np.ndarray] = []
        self._results: Optional[Dict[str, float]] = None

    @property
    def counters(self) -> Optional[Dict[str, jnp.ndarray]]:
        return self._counters

    def compute(self, targets, preds) -> None:
        """Accumulate one batch (targets/preds shape (N, ...))."""
        batch = batch_counters(
            self._task,
            self._metric_names,
            jnp.asarray(targets),
            jnp.asarray(preds),
            num_samples=self._num_samples,
            time_threshold_samples=self._t_thres,
        )
        self._counters = batch if self._counters is None else merge(self._counters, batch)
        if "r2" in self._metric_names:
            self._tgts.append(np.asarray(targets))
        self._results = None

    def add(self, other: "Metrics") -> None:
        if type(self) is not type(other):
            raise TypeError(f"Type of `other` must be `Metrics`, got `{type(other)}`")
        if other._counters is not None:
            self._counters = (
                copy.deepcopy(other._counters)
                if self._counters is None
                else merge(self._counters, other._counters)
            )
        self._tgts.extend(other._tgts)
        self._results = None

    def __add__(self, other: "Metrics") -> "Metrics":
        c = copy.deepcopy(self)
        c.add(other)
        return c

    def synchronize_between_processes(self) -> None:
        """All-reduce counters and all-gather R2 targets across hosts
        (ref metrics.py:83-98; here via jax multihost utils over ICI/DCN)."""
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        if self._counters is not None:
            self._counters = jax.tree.map(
                lambda x: multihost_utils.process_allgather(x).sum(axis=0),
                self._counters,
            )
        if self._tgts:
            # Per-host row counts differ when the split doesn't divide
            # evenly; process_allgather needs identical shapes, so pad to
            # the global max and trim each host's segment by its count.
            local = np.concatenate(self._tgts, axis=0)
            counts = np.asarray(
                multihost_utils.process_allgather(np.int64(local.shape[0]))
            ).reshape(-1)
            max_n = int(counts.max())
            padded = np.zeros((max_n,) + local.shape[1:], dtype=local.dtype)
            padded[: local.shape[0]] = local
            gathered = np.asarray(multihost_utils.process_allgather(padded))
            self._tgts = [
                np.concatenate(
                    [gathered[p, : counts[p]] for p in range(len(counts))], axis=0
                )
            ]
        self._results = None

    def _all(self) -> Dict[str, float]:
        if self._results is None:
            tgts = (
                np.concatenate(self._tgts, axis=0) if self._tgts else None
            )
            counters = (
                self._counters
                if self._counters is not None
                else init_counters(self._metric_names)
            )
            # ONE batched transfer of the whole counter dict; finalize's
            # per-key np.asarray is then a host no-op instead of a
            # device->host round trip per counter.
            self._host_counters = jax.device_get(counters)
            self._results = finalize(
                self._task, self._metric_names, self._host_counters, tgts
            )
        return self._results

    def get_metric(self, name: str) -> float:
        return self._all()[name.lower()]

    def get_metrics(self, names: Sequence[str]) -> Dict[str, float]:
        all_m = self._all()
        return {n: all_m[n.lower()] for n in names if n.lower() in all_m}

    def get_all_metrics(self) -> Dict[str, float]:
        return dict(self._all())

    def metric_names(self) -> List[str]:
        return list(self._metric_names)

    def __repr__(self) -> str:
        return "  ".join(f"{k.upper()} {v:6.4f}" for k, v in self._all().items())

    def to_dict(self) -> dict:
        # _all() batch-fetches every counter in one jax.device_get; the
        # per-key loop below then walks host numpy arrays only (the old
        # per-entry arr.item() loop was one device sync per counter).
        self._all()
        out: dict = {}
        if self._counters:
            for k, arr in self._host_counters.items():
                arr = np.asarray(arr)
                if arr.ndim == 0:
                    # jaxlint: disable=host-sync-item-loop -- host numpy; the batched device_get in _all() already moved it
                    out[k] = arr.item()
                else:
                    for i, vi in enumerate(arr.tolist()):
                        out[f"{k}.{i}"] = vi
        out.update(self._all())
        return out


def data_plane_counters() -> Dict[str, int]:
    """Snapshot of the data-plane guard counters (reads, retries,
    handle reopens, quarantined samples, fallback reads, stall trips,
    loader deaths) — the ops-facing view of
    ``seist_tpu.data.io_guard.COUNTERS``. Train-worker epoch logs, the
    BENCH ``data_plane`` section (bench.py) AND the metrics bus's
    ``data_plane`` collector (obs/bus.py
    ``register_default_collectors``, i.e. the ``seist_data_plane_*``
    Prometheus series on ``--metrics-port``) all read through this one
    function, so the surfaces can never disagree."""
    from seist_tpu.data.io_guard import COUNTERS

    return COUNTERS.snapshot()
